"""Vectorized masked retrieval kernels over padded ``(Q, L)`` query matrices.

Each kernel returns a ``(Q,)`` vector of per-query scores and is the single source of
truth for both the functional API (one query = one row) and the stateful classes
(whole corpus = one call). Semantics mirror the reference single-query functions in
``functional/retrieval/*.py`` (cited per kernel), including the reference's
``preds > 0`` relevance-filter quirk where present.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .utils import _ranked_by_preds, _row_segment_ids, _tie_average_ranks

Array = jax.Array


def _positions_within_k(mask_ranked: Array, top_k: int) -> Array:
    """Bool (Q, L): ranked position is a real (non-pad) entry within the top-k."""
    n = mask_ranked.shape[-1]
    return mask_ranked & (jnp.arange(n)[None, :] < top_k)


def _ap_kernel(preds: Array, target: Array, mask: Array, top_k: Optional[int] = None) -> Array:
    """Average precision (reference functional/retrieval/average_precision.py:16)."""
    k = top_k or preds.shape[-1]
    tgt = jnp.where(preds > 0, target, 0)  # reference filter quirk
    ranked, rmask = _ranked_by_preds(preds, tgt, mask)
    rel = (ranked > 0) & _positions_within_k(rmask, k)
    relf = rel.astype(jnp.float32)
    cum = jnp.cumsum(relf, axis=-1)
    prec_at = cum / jnp.arange(1, preds.shape[-1] + 1, dtype=jnp.float32)[None, :]
    n_rel = relf.sum(axis=-1)
    return jnp.where(n_rel > 0, (prec_at * relf).sum(axis=-1) / jnp.maximum(n_rel, 1.0), 0.0)


def _rr_kernel(preds: Array, target: Array, mask: Array, top_k: Optional[int] = None) -> Array:
    """Reciprocal rank (reference functional/retrieval/reciprocal_rank.py:16)."""
    k = top_k or preds.shape[-1]
    tgt = jnp.where(preds > 0, target, 0)
    ranked, rmask = _ranked_by_preds(preds, tgt, mask)
    rel = (ranked > 0) & _positions_within_k(rmask, k)
    first = jnp.argmax(rel, axis=-1)
    return jnp.where(rel.any(axis=-1), 1.0 / (first + 1.0), 0.0)


def _precision_kernel(
    preds: Array, target: Array, mask: Array, top_k: Optional[int] = None, adaptive_k: bool = False
) -> Array:
    """Precision@k (reference functional/retrieval/precision.py:20)."""
    n_valid = mask.sum(axis=-1).astype(jnp.float32)
    k = preds.shape[-1] if top_k is None else top_k
    tgt = jnp.where(preds > 0, target, 0)
    ranked, rmask = _ranked_by_preds(preds, tgt, mask)
    rel = ((ranked > 0) & _positions_within_k(rmask, k)).sum(axis=-1).astype(jnp.float32)
    if top_k is None:
        # reference sets top_k to each query's document count when unset
        # (functional/retrieval/precision.py:20) — the denominator is the per-row
        # valid count, NOT the padded matrix width
        denom = n_valid
    elif adaptive_k:
        denom = jnp.minimum(float(k), n_valid)
    else:
        denom = jnp.full_like(n_valid, float(k))
    has_pos = (jnp.where(mask, target, 0) > 0).any(axis=-1)
    return jnp.where(has_pos, rel / denom, 0.0)


def _recall_kernel(preds: Array, target: Array, mask: Array, top_k: Optional[int] = None) -> Array:
    """Recall@k (reference functional/retrieval/recall.py:20)."""
    k = preds.shape[-1] if top_k is None else top_k
    tgt = jnp.where(preds > 0, target, 0)
    ranked, rmask = _ranked_by_preds(preds, tgt, mask)
    rel = ((ranked > 0) & _positions_within_k(rmask, k)).sum(axis=-1).astype(jnp.float32)
    total = (jnp.where(mask, target, 0) > 0).sum(axis=-1).astype(jnp.float32)
    return jnp.where(total > 0, rel / jnp.maximum(total, 1.0), 0.0)


def _hit_rate_kernel(preds: Array, target: Array, mask: Array, top_k: Optional[int] = None) -> Array:
    """HitRate@k (reference functional/retrieval/hit_rate.py:20) — no preds>0 filter."""
    k = preds.shape[-1] if top_k is None else top_k
    ranked, rmask = _ranked_by_preds(preds, target, mask)
    rel = ((ranked > 0) & _positions_within_k(rmask, k)).sum(axis=-1)
    return (rel > 0).astype(jnp.float32)


def _fall_out_kernel(preds: Array, target: Array, mask: Array, top_k: Optional[int] = None) -> Array:
    """FallOut@k over negative targets (reference functional/retrieval/fall_out.py:20)."""
    k = preds.shape[-1] if top_k is None else top_k
    neg = jnp.where(mask, 1 - target, 0)
    ranked, rmask = _ranked_by_preds(preds, neg, mask)
    rel = ((ranked > 0) & _positions_within_k(rmask, k)).sum(axis=-1).astype(jnp.float32)
    total = (neg > 0).sum(axis=-1).astype(jnp.float32)
    return jnp.where(total > 0, rel / jnp.maximum(total, 1.0), 0.0)


def _r_precision_kernel(preds: Array, target: Array, mask: Array) -> Array:
    """R-Precision (reference functional/retrieval/r_precision.py:16)."""
    ranked, rmask = _ranked_by_preds(preds, target, mask)
    n_rel = (jnp.where(mask, target, 0) > 0).sum(axis=-1)
    within = rmask & (jnp.arange(preds.shape[-1])[None, :] < n_rel[:, None])
    rel = ((ranked > 0) & within).sum(axis=-1).astype(jnp.float32)
    return jnp.where(n_rel > 0, rel / jnp.maximum(n_rel.astype(jnp.float32), 1.0), 0.0)


def _dcg_tie_averaged(preds: Array, gains: Array, mask: Array, top_k: int) -> Array:
    """Tie-averaged DCG per row (reference functional/retrieval/ndcg.py:_tie_average_dcg,
    translated from sklearn): within a tie group the gain is the group mean, weighted by
    the group's share of the discount budget."""
    n = preds.shape[-1]
    discount = 1.0 / jnp.log2(jnp.arange(n, dtype=jnp.float32) + 2.0)
    discount = jnp.where(jnp.arange(n) < top_k, discount, 0.0)
    eff = jnp.where(mask, preds, -jnp.inf)
    order = jnp.argsort(-eff, axis=-1, stable=True)
    sorted_preds = jnp.take_along_axis(eff, order, axis=-1)
    sorted_gains = jnp.take_along_axis(jnp.where(mask, gains, 0.0), order, axis=-1)
    seg = _row_segment_ids(sorted_preds)
    seg_gain = jax.vmap(lambda s, v: jax.ops.segment_sum(v, s, num_segments=n))(seg, sorted_gains)
    seg_cnt = jax.vmap(lambda s: jax.ops.segment_sum(jnp.ones(n, jnp.float32), s, num_segments=n))(seg)
    seg_disc = jax.vmap(lambda s: jax.ops.segment_sum(discount, s, num_segments=n))(
        jnp.broadcast_to(seg, seg.shape)
    )
    avg_gain = seg_gain / jnp.maximum(seg_cnt, 1.0)
    return (avg_gain * seg_disc).sum(axis=-1)


def _dcg_ideal(gains: Array, mask: Array, top_k: int) -> Array:
    """Ideal (sorted-by-gain) DCG per row, ties irrelevant."""
    n = gains.shape[-1]
    discount = 1.0 / jnp.log2(jnp.arange(n, dtype=jnp.float32) + 2.0)
    discount = jnp.where(jnp.arange(n) < top_k, discount, 0.0)
    sorted_gains = -jnp.sort(-jnp.where(mask, gains, 0.0), axis=-1)
    return (sorted_gains * discount).sum(axis=-1)


def _ndcg_kernel(preds: Array, target: Array, mask: Array, top_k: Optional[int] = None) -> Array:
    """NDCG (reference functional/retrieval/ndcg.py:retrieval_normalized_dcg)."""
    k = preds.shape[-1] if top_k is None else top_k
    gains = jnp.where(mask, target, 0).astype(jnp.float32)
    dcg = _dcg_tie_averaged(preds, gains, mask, k)
    ideal = _dcg_ideal(gains, mask, k)
    return jnp.where(ideal > 0, dcg / jnp.maximum(ideal, 1e-38), 0.0)


def _auroc_kernel(preds: Array, target: Array, mask: Array, top_k: Optional[int] = None) -> Array:
    """Per-query binary AUROC via tie-averaged rank statistics (Mann-Whitney U),
    restricted to the top-k documents (reference functional/retrieval/auroc.py:16).

    AUROC = (R_pos - n_pos(n_pos+1)/2) / (n_pos * n_neg) with R_pos the sum of
    tie-averaged ascending ranks of the positives.
    """
    n = preds.shape[-1]
    k = n if top_k is None else top_k
    ranked_t, rmask = _ranked_by_preds(preds, target, mask)
    ranked_p = jnp.take_along_axis(jnp.where(mask, preds, -jnp.inf), jnp.argsort(-jnp.where(mask, preds, -jnp.inf), axis=-1, stable=True), axis=-1)
    within = _positions_within_k(rmask, k)
    ranks = _tie_average_ranks(ranked_p, within)
    pos = (ranked_t > 0) & within
    neg = (ranked_t == 0) & within
    n_pos = pos.sum(axis=-1).astype(jnp.float32)
    n_neg = neg.sum(axis=-1).astype(jnp.float32)
    r_pos = jnp.where(pos, ranks, 0.0).sum(axis=-1)
    auc = (r_pos - n_pos * (n_pos + 1) / 2) / jnp.maximum(n_pos * n_neg, 1.0)
    return jnp.where((n_pos > 0) & (n_neg > 0), auc, 0.0)
