"""Lip Vertex Error (reference ``functional/multimodal/lve.py``)."""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp


def lip_vertex_error(
    vertices_pred,
    vertices_gt,
    mouth_map: Sequence[int],
    validate_args: bool = True,
) -> jnp.ndarray:
    r"""Mean over frames of the max squared L2 error over lip vertices:
    ``LVE = mean_i max_{v in lip} ||x_{i,v} - xhat_{i,v}||^2``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import lip_vertex_error
        >>> vertices_pred = (jnp.arange(90, dtype=jnp.float32).reshape(5, 6, 3) * 37 % 19) / 19
        >>> vertices_gt = (jnp.arange(90, dtype=jnp.float32).reshape(5, 6, 3) * 31 % 17) / 17
        >>> lip_vertex_error(vertices_pred, vertices_gt, mouth_map=[1, 2, 3])
        Array(0.9050102, dtype=float32)
    """
    vertices_pred = jnp.asarray(vertices_pred)
    vertices_gt = jnp.asarray(vertices_gt)
    if validate_args:
        if vertices_pred.ndim != 3 or vertices_gt.ndim != 3:
            raise ValueError(
                f"Expected both vertices_pred and vertices_gt to have 3 dimensions but got "
                f"{vertices_pred.ndim} and {vertices_gt.ndim} dimensions respectively."
            )
        if vertices_pred.shape[1:] != vertices_gt.shape[1:]:
            raise ValueError(
                f"Expected vertices_pred and vertices_gt to have same vertex and coordinate dimensions but got "
                f"{vertices_pred.shape} and {vertices_gt.shape}."
            )
        if len(mouth_map) == 0:
            raise ValueError("Expected mouth_map to be non-empty.")
        if max(mouth_map) >= vertices_gt.shape[1]:
            raise ValueError(
                f"Invalid vertex index {max(mouth_map)} in mouth_map for mesh with {vertices_gt.shape[1]} vertices."
            )
    min_frames = min(vertices_pred.shape[0], vertices_gt.shape[0])
    pred_mouth = vertices_pred[:min_frames, jnp.asarray(list(mouth_map))]
    gt_mouth = vertices_gt[:min_frames, jnp.asarray(list(mouth_map))]
    sq_err = ((pred_mouth - gt_mouth) ** 2).sum(axis=-1)  # (T, |mouth|)
    return sq_err.max(axis=-1).mean()
