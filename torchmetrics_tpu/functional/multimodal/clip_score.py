"""CLIPScore (reference ``functional/multimodal/clip_score.py``).

The embedder is pluggable: ``model_name_or_path`` is a HF CLIP checkpoint (loaded
``local_files_only`` — an air-gapped pod cannot download; a clear error points at the
cache requirement) or any object exposing ``get_image_features(images) -> (N, D)`` and
``get_text_features(texts) -> (N, D)`` returning jnp arrays (e.g. a jitted flax CLIP
apply). The scoring itself — paired cosine similarity x 100, clamped at 0 — is a tiny
jnp expression over whatever embedder is plugged in.
"""

from __future__ import annotations

from typing import Any, List, Tuple, Union

import jax.numpy as jnp

from ...utilities.imports import _module_available

_TRANSFORMERS_AVAILABLE = _module_available("transformers")


def _detect_modality(input_data) -> str:
    if hasattr(input_data, "shape"):
        return "image"
    if isinstance(input_data, list):
        if len(input_data) == 0:
            raise ValueError("Empty input list")
        if hasattr(input_data[0], "shape"):
            return "image"
        if isinstance(input_data[0], str):
            return "text"
    if isinstance(input_data, str):
        return "text"
    raise ValueError("Could not automatically determine modality for input_data")


def _process_image_data(images) -> List:
    images = [images] if hasattr(images, "shape") and images.ndim == 3 else list(images)
    if not all(hasattr(i, "shape") and i.ndim == 3 for i in images):
        raise ValueError("Expected all images to be 3d but found image that has either more or less")
    return images


def _process_text_data(texts) -> List[str]:
    return [texts] if not isinstance(texts, list) else texts


class _HFClipWrapper:
    """Adapts a HF CLIPModel+Processor to the pluggable embedder protocol."""

    def __init__(self, model_name_or_path: str) -> None:
        if not _TRANSFORMERS_AVAILABLE:
            raise ModuleNotFoundError(
                "`clip_score` metric requires `transformers` package be installed."
                " Either install with `pip install transformers>=4.10.0` or `pip install torchmetrics[multimodal]`."
            )
        import torch  # noqa: F401
        from transformers import CLIPModel, CLIPProcessor

        try:
            self.model = CLIPModel.from_pretrained(model_name_or_path, local_files_only=True)
            self.processor = CLIPProcessor.from_pretrained(model_name_or_path, local_files_only=True)
        except OSError as err:  # HF raises OSError subclasses for cache misses
            raise ModuleNotFoundError(
                f"CLIP checkpoint {model_name_or_path!r} is not in the local HF cache and this "
                "environment has no network egress to download it. Pre-populate the cache offline, "
                "or pass a custom embedder object with get_image_features/get_text_features."
            ) from err
        self.model.eval()

    def get_image_features(self, images) -> jnp.ndarray:
        import numpy as np
        import torch

        processed = self.processor(images=[np.asarray(i) for i in images], return_tensors="pt", padding=True)
        with torch.no_grad():
            feats = self.model.get_image_features(processed["pixel_values"])
        return jnp.asarray(feats.numpy())

    def get_text_features(self, texts: List[str]) -> jnp.ndarray:
        import torch

        processed = self.processor(text=texts, return_tensors="pt", padding=True)
        max_pos = getattr(getattr(self.model.config, "text_config", None), "max_position_embeddings", None)
        if max_pos is not None and processed["attention_mask"].shape[-1] > max_pos:
            processed = {k: v[..., :max_pos] for k, v in processed.items()}
        with torch.no_grad():
            feats = self.model.get_text_features(processed["input_ids"], processed["attention_mask"])
        return jnp.asarray(feats.numpy())


def _resolve_clip(model_name_or_path: Union[str, Any]):
    if isinstance(model_name_or_path, str):
        return _HFClipWrapper(model_name_or_path)
    if hasattr(model_name_or_path, "get_image_features") and hasattr(model_name_or_path, "get_text_features"):
        return model_name_or_path
    raise ValueError(
        "Expected `model_name_or_path` to be a HF checkpoint string or an object with "
        "get_image_features/get_text_features."
    )


def _get_features(data, modality: str, model) -> jnp.ndarray:
    if modality == "image":
        return jnp.asarray(model.get_image_features(data))
    if modality == "text":
        return jnp.asarray(model.get_text_features(data))
    raise ValueError(f"invalid modality {modality}")


def _clip_score_features(source, target, model) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Validate one batch and run the (host/eager) embedder: ``(N, D)`` feature pair.

    This is the non-jittable half of the CLIPScore update — the metric class calls it
    from ``_prepare_inputs`` so the scoring half (normalize + paired cosine) stays
    inside the jitted, AOT-cacheable "update" program."""
    source_modality = _detect_modality(source)
    target_modality = _detect_modality(target)
    source_data = _process_image_data(source) if source_modality == "image" else _process_text_data(source)
    target_data = _process_image_data(target) if target_modality == "image" else _process_text_data(target)
    if len(source_data) != len(target_data):
        raise ValueError(
            "Expected the number of source and target examples to be the same but got "
            f"{len(source_data)} and {len(target_data)}"
        )
    return _get_features(source_data, source_modality, model), _get_features(target_data, target_modality, model)


def _clip_score_update(source, target, model) -> Tuple[jnp.ndarray, int]:
    source_features, target_features = _clip_score_features(source, target, model)
    n_samples = source_features.shape[0]
    source_features = source_features / jnp.linalg.norm(source_features, axis=-1, keepdims=True)
    target_features = target_features / jnp.linalg.norm(target_features, axis=-1, keepdims=True)
    score = 100 * (source_features * target_features).sum(axis=-1)
    return score, n_samples


def clip_score(
    source,
    target,
    model_name_or_path: Union[str, Any] = "openai/clip-vit-large-patch14",
) -> jnp.ndarray:
    r"""CLIPScore: ``max(100 * cos(E_source, E_target), 0)`` averaged over pairs;
    source/target can each be images or texts."""
    model = _resolve_clip(model_name_or_path)
    score, _ = _clip_score_update(source, target, model)
    return jnp.maximum(score.mean(), 0.0)
