"""Multimodal tower — stateless kernels (reference ``src/torchmetrics/functional/multimodal/``)."""

from .clip_iqa import clip_image_quality_assessment
from .clip_score import clip_score
from .lve import lip_vertex_error

__all__ = ["clip_image_quality_assessment", "clip_score", "lip_vertex_error"]
