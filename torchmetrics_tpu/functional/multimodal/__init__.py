"""Multimodal tower — stateless kernels (reference ``src/torchmetrics/functional/multimodal/``)."""

from .clip_score import clip_score
from .lve import lip_vertex_error

__all__ = ["clip_score", "lip_vertex_error"]
