"""One-shot functional CLIP-IQA (reference ``functional/multimodal/clip_iqa.py:220``).

Unlike the class metric (which averages over accumulated images), the functional
form returns PER-IMAGE prompt probabilities: a ``(N,)`` array for a single
prompt, else ``{prompt_name: (N,)}``.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple, Union

import jax
import jax.numpy as jnp


def _prompt_pair_probs(model, anchors: jnp.ndarray, images, data_range: float) -> jnp.ndarray:
    """(N, P) probabilities that each image matches the positive prompt of each pair.

    Stable two-way softmax: sigmoid of the logit difference (raw exp overflows f32
    for |cosine| > ~0.887 at the x100 scale).
    """
    images = jnp.asarray(images, jnp.float32) / data_range
    img_feats = jnp.asarray(model.get_image_features(list(images)))
    img_feats = img_feats / jnp.linalg.norm(img_feats, axis=-1, keepdims=True)
    logits = 100 * jnp.einsum("nd,pcd->npc", img_feats, anchors)
    return jax.nn.sigmoid(logits[..., 0] - logits[..., 1])


def clip_image_quality_assessment(
    images,
    model_name_or_path: Union[str, Any] = "clip_iqa",
    data_range: float = 1.0,
    prompts: Tuple[Union[str, Tuple[str, str]], ...] = ("quality",),
) -> Union[jnp.ndarray, Dict[str, jnp.ndarray]]:
    from ...multimodal.clip_iqa import CLIPImageQualityAssessment

    metric = CLIPImageQualityAssessment(
        model_name_or_path=model_name_or_path, data_range=data_range, prompts=prompts
    )
    probs = _prompt_pair_probs(metric.model, metric._prompt_anchors(), images, metric.data_range)
    if len(metric.prompt_names) == 1:
        return probs.squeeze()  # 0-d for a single image, like the reference
    return {name: probs[:, i] for i, name in enumerate(metric.prompt_names)}
