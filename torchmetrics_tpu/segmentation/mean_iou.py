"""MeanIoU metric class (reference ``segmentation/mean_iou.py:30``)."""

from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax.numpy as jnp

from ..functional.segmentation.mean_iou import (
    _mean_iou_compute,
    _mean_iou_update,
    _mean_iou_validate_args,
)
from ..metric import Metric


class MeanIoU(Metric):
    """Static-shape sum states (per-class score sums + valid-batch counts) — fully
    in-graph shardable. ``num_classes`` may be inferred from the first batch when the
    input format carries a class axis (reference mean_iou.py:131-169).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.segmentation import MeanIoU
        >>> preds = jnp.asarray([[[0, 1, 1, 0], [1, 1, 0, 0], [2, 2, 1, 0], [2, 0, 0, 0]]])
        >>> target = jnp.asarray([[[0, 1, 1, 0], [1, 0, 0, 0], [2, 2, 0, 0], [2, 2, 0, 0]]])
        >>> metric = MeanIoU(num_classes=3, input_format='index')
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.6833334, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        num_classes: Optional[int] = None,
        include_background: bool = True,
        per_class: bool = False,
        input_format: str = "one-hot",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        _mean_iou_validate_args(num_classes, include_background, per_class, input_format)
        self.num_classes = num_classes
        self.include_background = include_background
        self.per_class = per_class
        self.input_format = input_format
        self._is_initialized = False
        if num_classes is not None:
            self._init_states(num_classes)

    def _init_states(self, num_classes: int) -> None:
        num_out = num_classes - 1 if not self.include_background else num_classes
        self.add_state("score", default=np.zeros(num_out if self.per_class else 1), dist_reduce_fx="sum")
        self.add_state("num_batches", default=np.zeros(num_out if self.per_class else 1), dist_reduce_fx="sum")
        self._is_initialized = True

    def _prepare_inputs(self, preds, target):
        if not self._is_initialized:
            if self.input_format == "one-hot":
                self.num_classes = preds.shape[1]
            elif self.input_format == "mixed":
                if preds.ndim == target.ndim + 1:
                    self.num_classes = preds.shape[1]
                elif preds.ndim + 1 == target.ndim:
                    self.num_classes = target.shape[1]
                else:
                    raise ValueError(
                        "Predictions and targets are expected to have the same shape, "
                        f"got {preds.shape} and {target.shape}."
                    )
            else:
                raise ValueError("Argument `num_classes` must be provided when `input_format` is 'index'.")
            if self.num_classes == 0:
                raise ValueError(f"Expected argument `num_classes` to be a positive integer, but got {self.num_classes}.")
            self._init_states(self.num_classes)
        return (preds, target), {}

    def update_state(self, state, *args, **kwargs):
        if not self._is_initialized:
            from ..utilities.exceptions import TorchMetricsUserError

            raise TorchMetricsUserError(
                "MeanIoU cannot run in-graph with inferred `num_classes`; pass `num_classes` "
                "at construction (or run one stateful `update` first)."
            )
        return super().update_state(state, *args, **kwargs)

    def _batch_state(self, preds, target):
        intersection, union = _mean_iou_update(
            preds, target, self.num_classes, self.include_background, self.input_format
        )
        score = _mean_iou_compute(intersection, union, zero_division=0.0)
        valid = (union > 0).astype(jnp.float32)
        if self.per_class:
            return {"score": (score * valid).sum(axis=0), "num_batches": valid.sum(axis=0)}
        return {"score": (score * valid).sum()[None], "num_batches": valid.sum()[None]}

    def _compute(self, state):
        out = state["score"] / state["num_batches"]
        return jnp.nan_to_num(out, nan=-1.0) if self.per_class else jnp.nanmean(out)
