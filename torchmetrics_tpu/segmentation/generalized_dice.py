"""GeneralizedDiceScore metric class (reference ``segmentation/generalized_dice.py:34``)."""

from __future__ import annotations

from typing import Any

import numpy as np
import jax.numpy as jnp

from ..functional.segmentation.generalized_dice import (
    _generalized_dice_compute,
    _generalized_dice_update,
    _generalized_dice_validate_args,
)
from ..metric import Metric


class GeneralizedDiceScore(Metric):
    """Static-shape sum states (score, samples) — fully in-graph shardable.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.segmentation import GeneralizedDiceScore
        >>> preds = jnp.asarray([[[0, 1, 1, 0], [1, 1, 0, 0], [2, 2, 1, 0], [2, 0, 0, 0]]])
        >>> target = jnp.asarray([[[0, 1, 1, 0], [1, 0, 0, 0], [2, 2, 0, 0], [2, 2, 0, 0]]])
        >>> metric = GeneralizedDiceScore(num_classes=3, input_format='index')
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array([0.7905575], dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        num_classes: int,
        include_background: bool = True,
        per_class: bool = False,
        weight_type: str = "square",
        input_format: str = "one-hot",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        _generalized_dice_validate_args(num_classes, include_background, per_class, weight_type, input_format)
        self.num_classes = num_classes
        self.include_background = include_background
        self.per_class = per_class
        self.weight_type = weight_type
        self.input_format = input_format
        num_out = num_classes - 1 if not include_background else num_classes
        self.add_state("score", default=np.zeros(num_out if per_class else 1), dist_reduce_fx="sum")
        self.add_state("samples", default=np.zeros(1), dist_reduce_fx="sum")

    def _batch_state(self, preds, target):
        numerator, denominator = _generalized_dice_update(
            preds, target, self.num_classes, self.include_background, self.weight_type, self.input_format
        )
        score = _generalized_dice_compute(numerator, denominator, self.per_class).sum(axis=0)
        n = jnp.asarray(preds).shape[0]
        return {"score": score.reshape(self._defaults["score"].shape), "samples": jnp.full((1,), float(n))}

    def _compute(self, state):
        return state["score"] / state["samples"]
