"""DiceScore metric class (reference ``segmentation/dice.py:35``)."""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from ..functional.segmentation.dice import (
    _dice_score_compute,
    _dice_score_update,
    _dice_score_validate_args,
)
from ..metric import Metric


class DiceScore(Metric):
    """Dice score over per-sample sufficient statistics (cat states, like the reference
    segmentation/dice.py:139-141 — samplewise aggregation needs per-sample rows).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.segmentation import DiceScore
        >>> preds = jnp.asarray([[[0, 1, 1, 0], [1, 1, 0, 0], [2, 2, 1, 0], [2, 0, 0, 0]]])
        >>> target = jnp.asarray([[[0, 1, 1, 0], [1, 0, 0, 0], [2, 2, 0, 0], [2, 2, 0, 0]]])
        >>> metric = DiceScore(num_classes=3, input_format='index')
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.81022406, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        num_classes: int,
        include_background: bool = True,
        average: Optional[str] = "macro",
        aggregation_level: Optional[str] = "samplewise",
        input_format: str = "one-hot",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        _dice_score_validate_args(num_classes, include_background, average, input_format, aggregation_level)
        self.num_classes = num_classes
        self.include_background = include_background
        self.average = average
        self.aggregation_level = aggregation_level
        self.input_format = input_format
        self.add_state("numerator", default=[], dist_reduce_fx="cat")
        self.add_state("denominator", default=[], dist_reduce_fx="cat")
        self.add_state("support", default=[], dist_reduce_fx="cat")

    def _batch_state(self, preds, target):
        numerator, denominator, support = _dice_score_update(
            preds, target, self.num_classes, self.include_background, self.input_format
        )
        return {"numerator": numerator, "denominator": denominator, "support": support}

    def _compute(self, state):
        return jnp.nanmean(
            _dice_score_compute(
                state["numerator"],
                state["denominator"],
                self.average,
                self.aggregation_level,
                support=state["support"] if self.average == "weighted" else None,
            ),
            axis=0,
        )
