"""HausdorffDistance metric class (reference ``segmentation/hausdorff_distance.py:31``)."""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import numpy as np
import jax.numpy as jnp

from ..functional.segmentation.hausdorff_distance import (
    _hausdorff_distance_validate_args,
    hausdorff_distance,
)
from ..metric import Metric


class HausdorffDistance(Metric):
    """Mean Hausdorff distance over (sample, class) pairs; scalar sum + count states.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.segmentation import HausdorffDistance
        >>> preds = jnp.asarray([[[0, 1, 1, 0], [1, 1, 0, 0], [2, 2, 1, 0], [2, 0, 0, 0]]])
        >>> target = jnp.asarray([[[0, 1, 1, 0], [1, 0, 0, 0], [2, 2, 0, 0], [2, 2, 0, 0]]])
        >>> metric = HausdorffDistance(num_classes=3, input_format='index')
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(1.5, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        num_classes: int,
        include_background: bool = False,
        distance_metric: str = "euclidean",
        spacing: Optional[Union[Sequence[float], Any]] = None,
        directed: bool = False,
        input_format: str = "one-hot",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        _hausdorff_distance_validate_args(
            num_classes, include_background, distance_metric, spacing, directed, input_format
        )
        self.num_classes = num_classes
        self.include_background = include_background
        self.distance_metric = distance_metric
        self.spacing = spacing
        self.directed = directed
        self.input_format = input_format
        self.add_state("score", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, preds, target):
        score = hausdorff_distance(
            preds,
            target,
            self.num_classes,
            include_background=self.include_background,
            distance_metric=self.distance_metric,
            spacing=self.spacing,
            directed=self.directed,
            input_format=self.input_format,
        )
        return {"score": score.sum(), "total": jnp.asarray(float(score.size))}

    def _compute(self, state):
        return state["score"] / state["total"]
