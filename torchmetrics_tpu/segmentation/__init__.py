"""Segmentation tower — stateful metric classes (reference ``src/torchmetrics/segmentation/``)."""

from .dice import DiceScore
from .generalized_dice import GeneralizedDiceScore
from .hausdorff_distance import HausdorffDistance
from .mean_iou import MeanIoU

__all__ = ["DiceScore", "GeneralizedDiceScore", "HausdorffDistance", "MeanIoU"]
