"""MetricCollection with compute groups.

Parity: reference ``collections.py:59`` (update:237, _merge_compute_groups:269,
_equal_metric_states:306, _compute_groups_create_state_ref:338, _compute_and_reduce:362,
add_metrics:437). Compute groups: metrics with identical states (same names, same values
after the first update) share ONE state dict by reference; only the group leader runs
``update`` — the reference claims 2-3× update-loop speedup from this
(docs overview.rst:393-401). Here sharing the dict object makes the leader's jitted,
donated update serve every member for free; XLA additionally CSEs shared subexpressions
if members are later fused into one jit.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from copy import deepcopy
from typing import Any, Dict, Hashable, Iterable, List, Mapping, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from . import aot as _aot
from . import observability as _observability
from .metric import Metric
from .observability import tracing as _tracing
from .parallel import coalesce as _coalesce
from .parallel import sync as _par_sync
from .reliability.guards import validate_state
from .utilities.data import _flatten_dict, allclose
from .utilities.exceptions import TorchMetricsUserError
from .utilities.prints import rank_zero_warn

_ERROR_MSG = "Unknown input to MetricCollection."

_ON_ERROR_MODES = ("raise", "skip", "quarantine")


@dataclasses.dataclass(frozen=True)
class QuarantinedMetric:
    """Status marker surfaced in ``compute()`` for a metric that failed under
    ``on_error="quarantine"`` (or errored at compute under ``on_error="skip"``).

    The healthy rest of the collection keeps computing; this object carries what a
    monitoring layer needs: which metric, at which stage, the last error, and how
    many updates it had absorbed before failing.
    """

    name: str
    status: str  # "quarantined" (permanent until reset) | "skipped" (this compute only)
    stage: str  # "update" | "forward" | "compute"
    error: str  # repr of the triggering exception
    update_count: int

    def __repr__(self) -> str:  # compact, log-friendly
        return (
            f"QuarantinedMetric({self.name!r}, status={self.status!r}, stage={self.stage!r}, "
            f"after {self.update_count} updates: {self.error})"
        )


def _flatten_with_naming(res: Dict[str, Any], set_name) -> Dict[str, Any]:
    """Flatten nested dict results; bare sub-keys unless they collide across metrics."""
    _, duplicates = _flatten_dict(res)
    out: Dict[str, Any] = {}
    for k, v in res.items():
        if isinstance(v, dict):
            for sub_k, sub_v in v.items():
                key = f"{k}_{sub_k}" if duplicates else sub_k
                out[set_name(key)] = sub_v
        else:
            out[set_name(k)] = v
    return out


class MetricCollection:
    """Dict-of-metrics with single update/compute/reset (reference collections.py:59).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MetricCollection
        >>> from torchmetrics_tpu.classification import MulticlassAccuracy, MulticlassPrecision
        >>> preds = jnp.asarray([[0.75, 0.05, 0.20], [0.10, 0.80, 0.10], [0.20, 0.30, 0.50], [0.25, 0.40, 0.35]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> collection = MetricCollection({'acc': MulticlassAccuracy(num_classes=3), 'prec': MulticlassPrecision(num_classes=3)})
        >>> collection.update(preds, target)
        >>> {k: round(float(v), 4) for k, v in collection.compute().items()}
        {'acc': 1.0, 'prec': 1.0}
    """

    _modules: "OrderedDict[str, Metric]"

    def __init__(
        self,
        metrics: Union[Metric, Sequence[Metric], Mapping[str, Metric]],
        *additional_metrics: Metric,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
        compute_groups: Union[bool, List[List[str]]] = True,
        on_error: str = "raise",
    ) -> None:
        """``on_error`` (graceful-degradation policy, reliability layer):

        - ``"raise"`` (default): any metric error propagates — today's behavior.
        - ``"skip"``: the failing metric misses that batch (warned); a compute
          failure yields a :class:`QuarantinedMetric` marker for that key only.
        - ``"quarantine"``: the failing metric is frozen at its last good state,
          split out of its compute group (the donated fused update keeps serving
          the healthy members), excluded from further updates, and reported as a
          :class:`QuarantinedMetric` in ``compute()``. ``reset()`` lifts it.
        """
        self._modules = OrderedDict()
        self.prefix = self._check_arg(prefix, "prefix")
        self.postfix = self._check_arg(postfix, "postfix")
        self._enable_compute_groups = compute_groups
        self._groups_checked = False
        self._state_is_copy = False
        self._groups: Dict[int, List[str]] = {}
        if on_error not in _ON_ERROR_MODES:
            raise ValueError(f"Expected `on_error` to be one of {_ON_ERROR_MODES}, got {on_error!r}")
        self.on_error = on_error
        self._quarantined: Dict[str, Tuple[str, BaseException]] = {}  # name -> (stage, exc)
        self._degraded = False  # any failure-driven group split happened since reset
        self.add_metrics(metrics, *additional_metrics)

    @staticmethod
    def _check_arg(arg: Optional[str], name: str) -> Optional[str]:
        if arg is None or isinstance(arg, str):
            return arg
        raise ValueError(f"Expected input `{name}` to be a string, but got {type(arg)}")

    # ------------------------------------------------------------- container

    def add_metrics(
        self, metrics: Union[Metric, Sequence[Metric], Mapping[str, Metric]], *additional_metrics: Metric
    ) -> None:
        """Reference collections.py:437."""
        if isinstance(metrics, Metric):
            metrics = [metrics]
        if isinstance(metrics, Sequence):
            remain: list = []
            for m in additional_metrics:
                (metrics if isinstance(m, Metric) else remain).append(m)
            if remain:
                rank_zero_warn(
                    f"You have passes extra arguments {remain} which are not `Metric` so they will be ignored."
                )
        elif additional_metrics:
            raise ValueError(
                f"You have passed extra arguments {additional_metrics} which are only valid if input is a sequence."
            )
        if isinstance(metrics, Mapping):
            for name in sorted(metrics.keys()):
                metric = metrics[name]
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Value {metric} belonging to key {name} is not an instance of"
                        " `torchmetrics_tpu.Metric` or `torchmetrics_tpu.MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    self._modules[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        self._modules[f"{name}_{k}"] = v
        elif isinstance(metrics, Sequence):
            for metric in metrics:
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Input {metric} to `MetricCollection` is not a instance of"
                        " `torchmetrics_tpu.Metric` or `torchmetrics_tpu.MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    name = type(metric).__name__
                    if name in self._modules:
                        raise ValueError(f"Encountered two metrics both named {name}")
                    self._modules[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        if k in self._modules:
                            raise ValueError(f"Encountered two metrics both named {k}")
                        self._modules[k] = v
        else:
            raise ValueError(_ERROR_MSG)
        self._groups_checked = False

    def keys(self, keep_base: bool = False) -> Iterable[str]:
        if keep_base:
            return self._modules.keys()
        return [self._set_name(k) for k in self._modules]

    def values(self) -> Iterable[Metric]:
        return self._modules.values()

    def items(self, keep_base: bool = False) -> Iterable[Tuple[str, Metric]]:
        if keep_base:
            return self._modules.items()
        return [(self._set_name(k), v) for k, v in self._modules.items()]

    def __getitem__(self, key: str) -> Metric:
        return self._modules[key]

    def __iter__(self):
        return iter(self.keys())

    def __len__(self) -> int:
        return len(self._modules)

    def __contains__(self, key: str) -> bool:
        return key in self._modules or key in set(self.keys())

    def __repr__(self) -> str:
        repr_str = self.__class__.__name__ + "("
        for name, metric in self._modules.items():
            repr_str += f"\n  {name}: {metric!r}"
        if self.prefix:
            repr_str += f"\n  prefix={self.prefix}"
        if self.postfix:
            repr_str += f"\n  postfix={self.postfix}"
        return repr_str + "\n)"

    def _set_name(self, base: str) -> str:
        name = base if self.prefix is None else self.prefix + base
        return name if self.postfix is None else name + self.postfix

    # --------------------------------------------------------- compute groups

    @property
    def compute_groups(self) -> Dict[int, List[str]]:
        return self._groups

    def _init_compute_groups(self) -> None:
        """Reference collections.py:521. Quarantined metrics never join a group —
        their state is frozen and must not alias a live leader's dict."""
        if isinstance(self._enable_compute_groups, list):
            self._groups = dict(enumerate(self._enable_compute_groups))
            for v in self._groups.values():
                for name in v:
                    if name not in self._modules:
                        raise ValueError(
                            f"Input {name} in `compute_groups` argument does not match a metric in the collection."
                        )
            if self._quarantined:
                self._groups = {
                    i: kept
                    for i, (_, kept) in enumerate(
                        (gid, [n for n in members if n not in self._quarantined])
                        for gid, members in self._groups.items()
                    )
                    if kept
                }
            self._groups_checked = True
        elif self._enable_compute_groups:
            self._groups = {
                i: [str(k)] for i, k in enumerate(k for k in self._modules if k not in self._quarantined)
            }
        else:
            self._groups = {}

    @staticmethod
    def _equal_metric_states(metric1: Metric, metric2: Metric) -> bool:
        """Reference collections.py:306."""
        if not metric1._defaults or not metric2._defaults:
            return False
        if metric1._defaults.keys() != metric2._defaults.keys():
            return False
        if {k: str(v) for k, v in metric1._reductions.items()} != {k: str(v) for k, v in metric2._reductions.items()}:
            return False
        for key in metric1._defaults:
            s1, s2 = metric1._state[key], metric2._state[key]
            if isinstance(s1, list) != isinstance(s2, list):
                return False
            if isinstance(s1, list):
                if len(s1) != len(s2):
                    return False
                if not all(a.shape == b.shape and allclose(a, b) for a, b in zip(s1, s2)):
                    return False
            else:
                if s1.shape != s2.shape or not allclose(s1, s2):
                    return False
        return True

    def _merge_compute_groups(self) -> None:
        """O(n²) pairwise state-equality merge (reference collections.py:269-303)."""
        num_groups = len(self._groups)
        while True:
            for cg_idx1, cg_members1 in deepcopy(self._groups).items():
                for cg_idx2, cg_members2 in deepcopy(self._groups).items():
                    if cg_idx1 >= cg_idx2:
                        continue
                    metric1 = self._modules[cg_members1[0]]
                    metric2 = self._modules[cg_members2[0]]
                    if self._equal_metric_states(metric1, metric2):
                        self._groups[cg_idx1].extend(self._groups.pop(cg_idx2))
                        break
                else:
                    continue
                break
            else:
                break
        if len(self._groups) == num_groups:
            pass
        self._groups = {i: v for i, v in enumerate(self._groups.values())}

    def _compute_groups_create_state_ref(self, copy_state: bool = False) -> None:
        """Members alias the leader's state dict (reference collections.py:338)."""
        if not self._state_is_copy or copy_state:
            for members in self._groups.values():
                leader = self._modules[members[0]]
                for name in members[1:]:
                    member = self._modules[name]
                    if copy_state:
                        member._state = {
                            k: (list(v) if isinstance(v, list) else v) for k, v in leader._state.items()
                        }
                    else:
                        member._state = leader._state
        self._state_is_copy = copy_state

    # ----------------------------------------------------- graceful degradation

    @property
    def quarantined(self) -> Dict[str, BaseException]:
        """Currently quarantined metrics: name → last exception (empty when healthy)."""
        return {name: exc for name, (_, exc) in self._quarantined.items()}

    def _status_marker(self, name: str) -> QuarantinedMetric:
        stage, exc = self._quarantined[name]
        return QuarantinedMetric(
            name=name, status="quarantined", stage=stage, error=repr(exc),
            update_count=self._modules[name]._update_count,
        )

    def _failure_marker(self, name: str, stage: str, exc: BaseException) -> QuarantinedMetric:
        status = "quarantined" if name in self._quarantined else "skipped"
        return QuarantinedMetric(
            name=name, status=status, stage=stage, error=repr(exc),
            update_count=self._modules[name]._update_count,
        )

    def _detach_from_group(self, name: str) -> None:
        """Split ``name`` out of its compute group: de-alias its state (members share
        the leader's state DICT OBJECT, so a frozen/failed member must get its own
        copy before the survivors' donated update mutates the shared one). Buffers
        are copied too, not just containers — the survivors' jitted update DONATES
        the shared arrays, which would leave the detached metric holding deleted
        buffers (same hazard Metric.__deepcopy__ documents)."""
        metric = self._modules[name]
        metric._state = self._state_backup(metric)
        metric._computed = None
        for gid in list(self._groups):
            members = self._groups[gid]
            if name in members:
                members.remove(name)
                if not members:
                    del self._groups[gid]
                break

    @staticmethod
    def _state_backup(metric: Metric) -> Dict[str, Any]:
        """Undonated copies of a metric's tensor leaves (list leaves keep their
        elements — they never enter the donated call, only the containers are
        copied so a failed batch's appends can be rolled back)."""
        return {
            k: (list(v) if isinstance(v, list) else jnp.copy(v))
            for k, v in metric._state.items()
        }

    @staticmethod
    def _state_restore(metric: Metric, backup: Dict[str, Any]) -> None:
        """Roll a metric back to a pre-attempt backup IN PLACE — group members
        alias the state dict object, so the dict itself must survive. A failed
        donated dispatch may have deleted the live buffers (real donation on TPU;
        a no-op on CPU), which is why degrading policies back up before every
        attempt instead of assuming dispatch atomicity."""
        metric._state.clear()
        metric._state.update(backup)
        metric._n_prev_dev = None  # the device-side counter was donated too
        metric._computed = None

    def _handle_metric_error(self, name: str, exc: BaseException, stage: str) -> None:
        """Degrade per policy (never called under ``on_error="raise"``)."""
        self._detach_from_group(name)
        self._degraded = True
        rec = _observability._ACTIVE
        if rec is not None:
            # the degradation decision lands in the telemetry stream at the
            # moment it is made, not only as a marker in a later compute()
            rec.record_quarantine(
                name, stage,
                "quarantined" if self.on_error == "quarantine" else "skipped",
                exc, self._modules[name]._update_count,
            )
        if self.on_error == "quarantine":
            self._quarantined[name] = (stage, exc)
            rank_zero_warn(
                f"Metric {name!r} failed during {stage} and was quarantined "
                f"(on_error='quarantine'); the rest of the collection continues: {exc!r}",
                UserWarning,
            )
        else:  # skip: misses this batch only; continues as its own compute group
            if self._groups_checked and self._enable_compute_groups:
                # applies to explicit compute_groups lists too — without a group of
                # its own the metric would silently miss every future batch
                self._groups[max(self._groups, default=-1) + 1] = [name]
            rank_zero_warn(
                f"Metric {name!r} failed during {stage} and was skipped for this batch "
                f"(on_error='skip'): {exc!r}",
                UserWarning,
            )

    # -------------------------------------------------------------- lifecycle

    def _update_group(self, members: List[str], args: tuple, kwargs: dict) -> None:
        """Update one compute group; on failure under a degrading policy the shared
        state rolls back to its pre-attempt backup (the donated buffers may be
        deleted), the failing member is split out, and the next member takes over
        as leader for THIS batch."""
        while members:
            leader = self._modules[members[0]]
            if self.on_error == "raise":
                leader.update(*args, **leader._filter_kwargs(**kwargs))
            else:
                backup = self._state_backup(leader)
                try:
                    leader.update(*args, **leader._filter_kwargs(**kwargs))
                except Exception as exc:  # noqa: BLE001 — policy decides
                    self._state_restore(leader, backup)
                    self._handle_metric_error(members[0], exc, "update")
                    continue
            for name in members[1:]:
                member = self._modules[name]
                member._update_count = leader._update_count
                member._computed = None
            return

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Reference collections.py:237-267."""
        if self._groups_checked and self._groups:
            # only group leaders run update; members share the leader's state dict
            for members in list(self._groups.values()):
                self._update_group(members, args, kwargs)
            if self._state_is_copy:
                self._compute_groups_create_state_ref()
        else:
            failed_this_batch = False
            for name, metric in list(self._modules.items()):
                if name in self._quarantined:
                    continue
                if self.on_error == "raise":
                    metric.update(*args, **metric._filter_kwargs(**kwargs))
                else:
                    backup = self._state_backup(metric)
                    try:
                        metric.update(*args, **metric._filter_kwargs(**kwargs))
                    except Exception as exc:  # noqa: BLE001
                        self._state_restore(metric, backup)
                        self._handle_metric_error(name, exc, "update")
                        failed_this_batch = True
            if failed_this_batch and not self._groups_checked:
                # never derive fusion groups from a batch where some metric was
                # rolled back to defaults: state-equality would wrongly fuse
                # distinct metrics sitting at identical default states — wait for
                # a clean batch instead
                return
            if self._enable_compute_groups and not self._groups_checked:
                self._init_compute_groups()
                if not isinstance(self._enable_compute_groups, list):
                    self._merge_compute_groups()
                self._compute_groups_create_state_ref()
            self._groups_checked = True

    def _forward_group(self, members: List[str], res: Dict[str, Any], args: tuple, kwargs: dict) -> None:
        while members:
            name = members[0]
            leader = self._modules[name]
            if self.on_error == "raise":
                res[name] = leader.forward(*args, **leader._filter_kwargs(**kwargs))
            else:
                backup = self._state_backup(leader)
                try:
                    res[name] = leader.forward(*args, **leader._filter_kwargs(**kwargs))
                except Exception as exc:  # noqa: BLE001
                    self._state_restore(leader, backup)
                    self._handle_metric_error(name, exc, "forward")
                    res[name] = self._failure_marker(name, "forward", exc)
                    continue
            for mname in list(members[1:]):
                member = self._modules[mname]
                if self.on_error == "raise":
                    res[mname] = member._compute(leader._last_batch_state)
                else:
                    try:
                        res[mname] = member._compute(leader._last_batch_state)
                    except Exception as exc:  # noqa: BLE001
                        # the leader's forward already folded this batch into the
                        # SHARED state the member detaches with — sync the count
                        # first, or count-weighted ('mean') states skew forever
                        member._update_count = leader._update_count
                        self._handle_metric_error(mname, exc, "forward")
                        res[mname] = self._failure_marker(mname, "forward", exc)
                        continue
                member._update_count = leader._update_count
                member._computed = None
            return

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Batch values for all metrics + state accumulation."""
        res: Dict[str, Any] = {}
        if self._groups_checked and self._groups:
            for members in list(self._groups.values()):
                self._forward_group(members, res, args, kwargs)
            for name in self._quarantined:
                res.setdefault(name, self._status_marker(name))
            res = {name: res[name] for name in self._modules if name in res}
        else:
            failed_this_batch = False
            for name, metric in list(self._modules.items()):
                if name in self._quarantined:
                    res[name] = self._status_marker(name)
                    continue
                if self.on_error == "raise":
                    res[name] = metric.forward(*args, **metric._filter_kwargs(**kwargs))
                else:
                    backup = self._state_backup(metric)
                    try:
                        res[name] = metric.forward(*args, **metric._filter_kwargs(**kwargs))
                    except Exception as exc:  # noqa: BLE001
                        self._state_restore(metric, backup)
                        self._handle_metric_error(name, exc, "forward")
                        res[name] = self._failure_marker(name, "forward", exc)
                        failed_this_batch = True
            if failed_this_batch and not self._groups_checked:
                # as in update(): rolled-back default states must not seed the
                # state-equality group derivation
                return self._flatten_res(res)
            if self._enable_compute_groups and not self._groups_checked:
                self._init_compute_groups()
                if not isinstance(self._enable_compute_groups, list):
                    self._merge_compute_groups()
                self._compute_groups_create_state_ref()
            self._groups_checked = True
        return self._flatten_res(res)

    __call__ = forward

    def compute(self) -> Dict[str, Any]:
        # coalesced pre-sync: every member that would sync inside its own
        # compute() syncs HERE through one bucketed collective set instead of
        # K independent per-metric syncs (members see _is_synced and skip
        # their own); unsync restores local views afterwards
        presynced = self._presync_for_compute()
        try:
            res: Dict[str, Any] = {}
            for name, metric in self._modules.items():
                if name in self._quarantined:
                    res[name] = self._status_marker(name)
                elif self.on_error == "raise":
                    res[name] = metric.compute()
                else:
                    try:
                        res[name] = metric.compute()
                    except Exception as exc:  # noqa: BLE001
                        self._handle_metric_error(name, exc, "compute")
                        res[name] = self._failure_marker(name, "compute", exc)
        finally:
            for metric in presynced:
                if metric._is_synced:
                    metric.unsync()
        return self._flatten_res(res)

    def _presync_for_compute(self) -> List[Metric]:
        """Coalesce the sync_on_compute syncs of all members into one bucketed
        sync. Only under ``on_error="raise"`` (degrading policies attribute
        failures per member, which a fused collective cannot); any condition
        the fast path cannot honor simply leaves members to sync themselves
        inside compute() exactly as before. Returns the members this call
        synced (the caller owns their unsync)."""
        if self.on_error != "raise":
            return []
        members = [
            m
            for m in self._modules.values()
            if m.sync_on_compute
            and not m._is_synced
            and not (m.compute_with_cache and m._computed is not None)
            # only replace the sync that Metric.compute itself would run; a
            # member with a custom compute keeps its own sync discipline
            and type(m).compute is Metric.compute
        ]
        if not members:
            return []
        if not self._coalesced_sync(members):
            return []
        return [m for m in members if m._is_synced]

    def _flatten_res(self, res: Dict[str, Any]) -> Dict[str, Any]:
        """Flatten nested dict outputs + apply prefix/postfix (reference :388-407)."""
        return _flatten_with_naming(res, self._set_name)

    def merge_state(self, incoming: "MetricCollection") -> None:
        """Pairwise child merge by key (commless map-reduce plane, like
        ``Metric.merge_state``).

        With active compute groups, members ALIAS the group leader's state dict,
        so only one metric per group may fold (then members re-alias); merging
        every member would apply the fold once per group member."""
        if not isinstance(incoming, MetricCollection):
            raise ValueError(f"Expected a MetricCollection, got {type(incoming).__name__}")
        mine = dict(self._modules)
        theirs = dict(incoming._modules)
        if set(mine) != set(theirs):
            raise ValueError(
                f"Cannot merge collections with different metrics: {sorted(set(mine) ^ set(theirs))}"
            )
        frozen = set(self._quarantined) | set(incoming._quarantined)
        if frozen:
            rank_zero_warn(
                f"merge_state skipping quarantined metrics {sorted(frozen)}: their states are "
                "frozen at the last good value and must not fold.",
                UserWarning,
            )
        if self._groups_checked and self._groups:
            grouped = {name for members in self._groups.values() for name in members}
            for members in self._groups.values():
                # fold through the first member healthy on BOTH sides: an incoming
                # quarantine only freezes THAT metric's shard, not its group-mates'
                # contributions (skipping the whole group would silently drop them)
                live = [n for n in members if n not in frozen]
                if not live:
                    rank_zero_warn(
                        f"merge_state: compute group {members} has no member healthy on "
                        "both sides; the incoming contribution of this group is dropped.",
                        UserWarning,
                    )
                    continue
                leader = live[0]
                mine[leader].merge_state(theirs[leader])
                for name in members:
                    if name == leader:
                        continue
                    mine[name]._state = mine[leader]._state
                    mine[name]._update_count = mine[leader]._update_count
                    mine[name]._computed = None
            for name, metric in mine.items():
                if name not in grouped and name not in frozen:
                    metric.merge_state(theirs[name])
        else:
            for name, metric in mine.items():
                if name not in frozen:
                    metric.merge_state(theirs[name])

    def reset(self) -> None:
        for metric in self._modules.values():
            metric.reset()
        if self._quarantined or self._degraded:
            # lift quarantine and forget the failure-driven group splits: groups
            # re-derive from scratch on the next update (same as a fresh collection).
            # A healthy skip/quarantine collection keeps its fused groups — only
            # collections that actually split pay the re-derivation. Formerly-grouped
            # members must also stop ALIASING one state: with groups cleared the next
            # update runs every metric separately, so a still-shared dict would absorb
            # the same batch once per member (double-counting) and a shared BUFFER
            # would be deleted by the first member's donated update.
            for metric in self._modules.values():
                metric._state = self._state_backup(metric)
            self._quarantined.clear()
            self._degraded = False
            self._groups = {}
            self._groups_checked = False
            self._state_is_copy = False
        elif self._groups_checked and self._groups:
            self._compute_groups_create_state_ref()

    def clone(self, prefix: Optional[str] = None, postfix: Optional[str] = None) -> "MetricCollection":
        mc = deepcopy(self)
        if prefix:
            mc.prefix = self._check_arg(prefix, "prefix")
        if postfix:
            mc.postfix = self._check_arg(postfix, "postfix")
        return mc

    def __deepcopy__(self, memo: dict) -> "MetricCollection":
        cls = self.__class__
        new = cls.__new__(cls)
        memo[id(self)] = new
        for k, v in self.__dict__.items():
            if k == "_modules":
                object.__setattr__(new, k, OrderedDict((n, deepcopy(m, memo)) for n, m in v.items()))
            else:
                object.__setattr__(new, k, deepcopy(v, memo))
        # re-link group state refs inside the copy
        if new._groups_checked and new._groups and not new._state_is_copy:
            new._compute_groups_create_state_ref()
        return new

    def persistent(self, mode: bool = True) -> None:
        for metric in self._modules.values():
            metric.persistent(mode)

    def state_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for name, metric in self._modules.items():
            metric.state_dict(out, prefix=f"{name}.")
        return out

    def load_state_dict(self, state_dict: Dict[str, Any], validate: bool = True) -> None:
        for name, metric in self._modules.items():
            metric.load_state_dict(state_dict, prefix=f"{name}.", validate=validate)

    def sync(self, async_: bool = False, sync_config: Optional[Any] = None, **kwargs: Any) -> Any:
        """Cross-process sync of every member. Fast path: ALL members' states
        coalesce into one bucketed collective set (K·L per-leaf collectives →
        1 metadata gather + one padded gather per dtype); fused compute-group
        members share one state dict and are gathered/charged exactly once,
        re-aliasing on commit. Falls back to per-member ``Metric.sync`` when
        members disagree on gather seams (mixed ``dist_sync_fn``/
        ``process_group``/availability).

        ``async_=True`` returns an
        :class:`~torchmetrics_tpu.parallel.AsyncSyncHandle` instead of
        blocking: the bucketed gather of the CURRENT states launches in the
        background while the collection keeps updating (the next window);
        ``handle.commit()`` barriers, validates, and atomically swaps every
        member to the synced previous-window state — the live (since-updated)
        state parks in the sync cache and ``unsync()`` restores it, so the
        overlap loses nothing. A failed gather commits NOTHING (members keep
        their last good state). See ``docs/streaming.md``.

        ``sync_config`` (:class:`~torchmetrics_tpu.parallel.SyncConfig`) opts
        the coalesced fast path into quantized (bf16/int8) buckets; use ONE
        config per collection across repeated syncs so its error-feedback
        residuals fold correctly. The per-member fallback below stays exact —
        residual keys are positional within the coalesced leaf table, so a
        per-member re-run must not consume them (docs/distributed.md)."""
        if async_:
            return self._async_sync(sync_config=sync_config, **kwargs)
        if self._coalesced_sync(list(self._modules.values()), sync_config=sync_config, **kwargs):
            return None
        for metric in self._modules.values():
            metric.sync(**kwargs)
        return None

    def _coalesced_sync(
        self,
        metrics: List[Metric],
        dist_sync_fn: Optional[Any] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        distributed_available: Optional[Any] = None,
        sync_config: Optional[Any] = None,
    ) -> bool:
        """Coalesced multi-metric sync. Returns ``True`` when this call fully
        handled the sync (including the distributed-unavailable no-op) and
        ``False`` when the caller must fall back to per-member syncs.

        Reliability contract: nothing is committed until every bucket has
        gathered and every member's synced state validated, so a faulty
        bucketed gather (e.g. ``FlakyGather``) leaves every member at its last
        good state — exactly the per-leaf rollback guarantee. Retry uses the
        first member's ``ReliabilityConfig`` (members of one collection share
        a policy in practice; mixed policies still roll back atomically)."""
        if not should_sync or not metrics:
            return True
        fns = {id(dist_sync_fn or m.dist_sync_fn) for m in metrics}
        groups = {id(process_group or m.process_group) for m in metrics}
        # a plain list, never a Metric-keyed dict: Metric.__hash__ is state-id
        # based (fused members collide) and __eq__ builds CompositionalMetric,
        # so distinct members would silently collapse to one entry
        avail_fns = [(distributed_available or m.distributed_available_fn) for m in metrics]
        if len(fns) > 1 or len(groups) > 1:
            return False  # mixed gather seams: per-member semantics required
        if any(type(m).sync is not Metric.sync for m in metrics):
            return False  # a member customizes sync: honor it per-member
        # ordering mirrors Metric.sync: the already-synced error fires BEFORE
        # the availability check, so the no-op below can't swallow it
        if any(m._is_synced for m in metrics):
            raise TorchMetricsUserError("The Metric has already been synced.")
        avails = {bool(fn()) for fn in avail_fns}
        if len(avails) > 1:
            return False
        if not avails.pop():
            return True  # nowhere to sync — same no-op as per-member path
        fn = dist_sync_fn or metrics[0].dist_sync_fn
        group = process_group or metrics[0].process_group
        # fused compute-group members alias ONE state dict: gather it once
        holders: "OrderedDict[int, Metric]" = OrderedDict()
        aliased: Dict[int, List[Metric]] = {}
        for m in metrics:
            key = id(m._state)
            holders.setdefault(key, m)
            aliased.setdefault(key, []).append(m)
        states = [holders[k]._state for k in holders]
        reductions = [holders[k]._reductions for k in holders]
        rec = _observability._ACTIVE
        t0 = _tracing.monotonic() if rec is not None else 0.0
        bytes_total = sum(_par_sync._payload_bytes(s) for s in states)
        coll0 = rec.counters.value("sync_collectives") if rec is not None else 0
        coal0 = rec.counters.value("gathers_coalesced") if rec is not None else 0
        def attempt() -> List[Dict[str, Any]]:
            return _coalesce.coalesced_process_sync(
                states, reductions, process_group=group, dist_sync_fn=fn,
                sync_config=sync_config,
            )

        def count_attempt(exc: BaseException, attempt_no: int) -> None:
            # a transiently-failed attempt still entered the sync plane — count
            # it like the per-metric path does (process_sync records at entry)
            if rec is not None:
                rec.counters.record_sync(bytes_total)

        retry = next(
            (m._reliability.retry for m in metrics if m._reliability is not None and m._reliability.retry is not None),
            None,
        )
        with _tracing.trace_span("MetricCollection.sync"):
            try:
                if retry is None:
                    synced = attempt()
                else:
                    synced = retry.call(attempt, on_retry=count_attempt, describe="MetricCollection.sync")
            except _coalesce.CoalesceFallback:
                # nothing committed AND nothing recorded for this attempt: the
                # per-member path records its own syncs (charging the abandoned
                # attempt too would double-count one logical sync)
                return False
        if rec is not None:  # the successful attempt is one sync entry
            rec.counters.record_sync(bytes_total)
        # validate BEFORE committing anything: a corrupt contribution must not
        # become any member's state (and a partial commit must never happen).
        # Fused members share one dict AND one validation semantics (fusion
        # requires equal defaults/reductions) — scan each distinct dict once,
        # with the strictest finiteness setting among its members.
        for key, synced_dict in zip(holders, synced):
            validators = [m for m in aliased[key] if m._reliability is not None and m._reliability.validate_on_sync]
            if validators:
                validate_state(
                    validators[0], synced_dict,
                    context=f"{type(validators[0]).__name__}.sync",
                    check_finite=any(m._reliability.check_finite for m in validators),
                )
        # atomic commit: one shared cache + one shared synced dict per distinct
        # state dict, so group members keep ALIASING through sync/unsync
        for key, synced_dict in zip(holders, synced):
            holder = holders[key]
            cache = {
                k: (list(v) if isinstance(v, list) else v) for k, v in holder._state.items()
            }
            for m in aliased[key]:
                m._cache = cache
                m._state = synced_dict
                m._is_synced = True
        if rec is not None:
            rec.record_sync(
                self, rec.finish(synced, t0), bytes_total,
                collectives=rec.counters.value("sync_collectives") - coll0,
                coalesced_leaves=rec.counters.value("gathers_coalesced") - coal0,
            )
        return True

    def _async_sync(
        self,
        dist_sync_fn: Optional[Any] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        distributed_available: Optional[Any] = None,
        rebuffer: bool = True,
        sync_config: Optional[Any] = None,
    ) -> "Any":
        """Launch the double-buffered background sync (``sync(async_=True)``).

        Freeze is a SHALLOW snapshot of each distinct state dict — jax arrays
        are immutable, so freezing copies nothing. The hazard is donation:
        the members' jitted updates donate (delete) their live buffers, so
        under ``rebuffer=True`` (default) the LIVE dict entries are replaced
        with value copies (metric states are bytes-to-KBs) and the in-flight
        gather owns the frozen buffers exclusively. A caller that rotates the
        window itself (``reset()`` right after launch replaces the live
        entries wholesale) can pass ``rebuffer=False`` for a fully zero-copy
        freeze. Unlike the blocking path there is no per-member fallback —
        mixed gather seams or custom ``sync`` overrides raise, because a
        background per-member sync could not preserve per-member semantics.

        Commit protocol (``AsyncSyncHandle.commit``): barrier → validate
        every member's synced state (nothing installs on a corrupt
        contribution or a failed gather — members keep their last good
        state) → atomically swap: each member's live (possibly since-updated)
        state becomes its sync cache, the synced previous-window state
        becomes ``_state``, ``_is_synced`` flips; ``unsync()`` restores the
        live state. Fused compute groups keep aliasing through the swap.
        """
        from .parallel.async_sync import AsyncSyncHandle

        metrics = list(self._modules.values())
        if any(m._is_synced for m in metrics):
            raise TorchMetricsUserError("The Metric has already been synced.")
        fns = {id(dist_sync_fn or m.dist_sync_fn) for m in metrics}
        groups = {id(process_group or m.process_group) for m in metrics}
        if len(fns) > 1 or len(groups) > 1 or any(type(m).sync is not Metric.sync for m in metrics):
            raise TorchMetricsUserError(
                "sync(async_=True) requires uniform gather seams and the default Metric.sync "
                "across members; use the blocking sync() for mixed collections."
            )
        avail_fns = [(distributed_available or m.distributed_available_fn) for m in metrics]
        avails = {bool(fn()) for fn in avail_fns}
        if len(avails) > 1:
            raise TorchMetricsUserError(
                "sync(async_=True) requires members to agree on distributed availability."
            )
        if not should_sync or not metrics or not avails.pop():
            return AsyncSyncHandle.noop(label="MetricCollection.sync")
        fn = dist_sync_fn or metrics[0].dist_sync_fn
        group = process_group or metrics[0].process_group
        holders: "OrderedDict[int, Metric]" = OrderedDict()
        aliased: Dict[int, List[Metric]] = {}
        for m in metrics:
            key = id(m._state)
            holders.setdefault(key, m)
            aliased.setdefault(key, []).append(m)
        holder_keys = list(holders)
        frozen: List[Dict[str, Any]] = []
        for key in holder_keys:
            live = holders[key]._state
            fro: Dict[str, Any] = {}
            for name, v in list(live.items()):
                if isinstance(v, list):
                    # freeze the CONTAINER (appends to the live list must not
                    # leak into the in-flight gather); elements never donate
                    fro[name] = list(v)
                else:
                    fro[name] = v
                    if rebuffer:
                        live[name] = jnp.copy(v)  # live side re-buffered; frozen owns the original
            frozen.append(fro)
        reductions = [holders[k]._reductions for k in holder_keys]
        retry = next(
            (m._reliability.retry for m in metrics if m._reliability is not None and m._reliability.retry is not None),
            None,
        )

        def committer(synced: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
            # validate BEFORE committing anything (same discipline as the
            # blocking coalesced sync): a corrupt contribution must not become
            # any member's state, and a partial commit must never happen
            for key, synced_dict in zip(holder_keys, synced):
                validators = [
                    m for m in aliased[key]
                    if m._reliability is not None and m._reliability.validate_on_sync
                ]
                if validators:
                    validate_state(
                        validators[0], synced_dict,
                        context=f"{type(validators[0]).__name__}.sync",
                        check_finite=any(m._reliability.check_finite for m in validators),
                    )
            for key, synced_dict in zip(holder_keys, synced):
                holder = holders[key]
                # the CURRENT (possibly overlap-updated) state parks in the
                # cache; unsync restores it — the next window loses nothing
                cache = {
                    k2: (list(v) if isinstance(v, list) else v) for k2, v in holder._state.items()
                }
                for m in aliased[key]:
                    m._cache = cache
                    m._state = synced_dict
                    m._is_synced = True
            return synced

        return AsyncSyncHandle(
            frozen, reductions, process_group=group, dist_sync_fn=fn,
            retry=retry, committer=committer, label="MetricCollection.sync",
            sync_config=sync_config,
        )

    def unsync(self, **kwargs: Any) -> None:
        for metric in self._modules.values():
            metric.unsync(**kwargs)

    def set_dtype(self, dst_type: Any) -> "MetricCollection":
        for metric in self._modules.values():
            metric.set_dtype(dst_type)
        return self

    def to_device(self, device_or_sharding: Any) -> "MetricCollection":
        for metric in self._modules.values():
            metric.to_device(device_or_sharding)
        return self

    def plot(self, val=None, ax=None, together: bool = False):
        from .utilities.plot import plot_single_or_multi_val

        val = val if val is not None else self.compute()
        if together:
            return [plot_single_or_multi_val(val, ax=ax)]
        return [plot_single_or_multi_val({k: v}, ax=ax) for k, v in val.items()]

    # ------------------------------------------------------- warm start (aot/)

    def precompile(
        self,
        *example_inputs: Any,
        tags: Sequence[str] = ("update",),
        cache_dir: Optional[str] = None,
        force: bool = False,
        prefetch_workers: int = 8,
        **example_kwargs: Any,
    ) -> Dict[str, Any]:
        """Warm-start the whole collection: compile every member's dispatch
        program(s) for the example input shapes and publish the serialized
        executables into the AOT cache (``torchmetrics_tpu.aot``).

        Every member precompiles individually — on a fresh boot the first
        real batch dispatches each member once before compute groups derive,
        so per-member entries are exactly what that first batch loads.
        Heterogeneous collections reuse the update-path kwarg filtering;
        quarantined members are skipped. Returns ``{member: {tag: row}}``.

        Members whose entries were already cached (status ``"cached"``) are
        additionally **prefetched**: their serialized executables deserialize
        NOW, on a ``prefetch_workers``-wide thread pool, into each member's
        dispatch memo — a 16-member boot overlaps loads that the first real
        batch would otherwise pay one after another (the per-load wall-clock
        still lands in ``aot_deserialize_us`` when a telemetry session
        observes the first dispatch). The ``"_prefetch"`` report row carries
        the overlap win: ``serial_load_s`` (sum of individual loads) vs
        ``wall_s`` (what the pool actually took). ``prefetch_workers=0``
        disables it; an explicit ``cache_dir`` skips it too (the one-off
        plane is not the one traffic will dispatch against).
        """
        report: Dict[str, Any] = {}
        for name, metric in self._modules.items():
            if name in self._quarantined:
                report[name] = {"status": "skipped", "reason": "quarantined"}
                continue
            report[name] = metric.precompile(
                *example_inputs,
                tags=tags,
                cache_dir=cache_dir,
                force=force,
                **metric._filter_kwargs(**example_kwargs),
            )
        if prefetch_workers and cache_dir is None and _aot._ACTIVE is not None:
            prefetch = self._prefetch_members(
                report, example_inputs, example_kwargs, tags, prefetch_workers
            )
            if prefetch is not None:  # only when cached entries actually loaded
                report["_prefetch"] = prefetch
        return report

    def _prefetch_members(
        self,
        report: Dict[str, Any],
        example_inputs: tuple,
        example_kwargs: Dict[str, Any],
        tags: Sequence[str],
        workers: int,
    ) -> Optional[Dict[str, Any]]:
        """Deserialize the members' already-cached entries concurrently (each
        thread touches only its own member's memo; plane stats are
        lock-guarded). Freshly ``"written"`` members are already primed by
        ``precompile_program`` and skip the pool."""
        import concurrent.futures

        def _cached_tags(row: Any) -> List[str]:
            if not isinstance(row, dict):
                return []
            return [tag for tag in tags
                    if isinstance(row.get(tag), dict) and row[tag].get("status") == "cached"]

        todo = [
            (name, self._modules[name], _cached_tags(row))
            for name, row in report.items()
            if name in self._modules and _cached_tags(row)
        ]
        if not todo:
            return None

        def _one(item):
            name, metric, member_tags = item
            try:
                return name, metric.prefetch_compiled(
                    *example_inputs, tags=tuple(member_tags),
                    **metric._filter_kwargs(**example_kwargs),
                )
            except Exception as err:  # noqa: BLE001 — prefetch must never fail a boot
                return name, {"error": f"{type(err).__name__}: {err}"[:200]}

        t0 = _tracing.monotonic()
        with concurrent.futures.ThreadPoolExecutor(max_workers=min(workers, len(todo))) as pool:
            rows = dict(pool.map(_one, todo))
        wall = _tracing.monotonic() - t0
        loaded = [
            r for row in rows.values() if isinstance(row, dict)
            for r in row.values() if isinstance(r, dict) and r.get("status") == "loaded"
        ]
        serial = sum(r.get("load_s", 0.0) for r in loaded)
        return {
            "workers": min(workers, len(todo)),
            "loaded": len(loaded),
            "wall_s": round(wall, 6),
            "serial_load_s": round(serial, 6),
            "overlap_x": round(serial / wall, 2) if wall > 0 and serial > 0 else None,
            "members": rows,
        }

    # --------------------------------------------------------------- telemetry

    def state_memory(self) -> Dict[str, Any]:
        """Per-member state-memory footprint (metadata only, zero D2H).

        Fused compute-group members ALIAS their leader's state dict, so a naive
        per-member sum would charge one buffer once per member; aliased members
        report their bytes but carry an ``aliased_to`` pointer and only the
        first holder of each distinct state dict contributes to
        ``total_bytes`` — the number that actually lives in HBM.
        """
        from .observability import memory as _memory

        members: Dict[str, Any] = {}
        seen: Dict[int, str] = {}
        total = 0
        for name, metric in self._modules.items():
            report = _memory.state_memory(metric._state)
            holder = seen.get(id(metric._state))
            if holder is not None:
                report["aliased_to"] = holder
            else:
                seen[id(metric._state)] = name
                total += report["total_bytes"]
            members[name] = report
        return {"members": members, "total_bytes": total}

    def telemetry_summary(self) -> Dict[str, Any]:
        """Per-member dispatch attribution from the active telemetry session.

        Fused compute groups dispatch once through their leader; members show
        zero dispatches of their own plus a ``fused_into`` pointer, which is
        exactly the attribution an operator needs to read a trace of a fused
        collection ("why does only ``acc`` show compiles?"). Quarantined
        members carry their frozen status. ``{"enabled": False}`` when no
        session is active.
        """
        rec = _observability.active()
        if rec is None:
            return {"enabled": False}
        groups = {gid: list(m) for gid, m in self._groups.items()} if self._groups_checked else {}
        leader_of = {
            name: members[0] for members in groups.values() for name in members[1:]
        }
        mem = self.state_memory()
        members_out: Dict[str, Any] = {}
        for name, metric in self._modules.items():
            info = rec.metric_summary(metric)
            latency = rec.metric_latency(metric)
            if latency:  # per-stage p50/p99 from the session's histograms
                info["latency_us"] = latency
            if name in leader_of:
                info["fused_into"] = leader_of[name]
            if name in self._quarantined:
                stage, exc = self._quarantined[name]
                info["status"] = "quarantined"
                info["quarantine_stage"] = stage
            info["state_bytes"] = mem["members"][name]["total_bytes"]
            members_out[name] = info
        return {
            "enabled": True,
            "members": members_out,
            "compute_groups": groups,
            "counters": rec.counters.snapshot().summary(brief=True),
            "state_memory_bytes": mem["total_bytes"],
        }

    # ------------------------------------------------------------- fused pure API

    def as_pure(self) -> "PureCollection":
        """One jittable program over the whole collection (SURVEY §7: compute groups
        as the *default fused path*).

        Returns a :class:`PureCollection` of pure functions —
        ``init() -> states``, ``update(states, *batch) -> states``,
        ``compute(states) -> values``, ``apply(states, *batch) -> (states, values)`` —
        each one XLA program when jitted. No group bookkeeping is needed: metrics with
        identical sufficient statistics (Accuracy/F1/... sharing tp/fp/tn/fn) collapse
        by common-subexpression elimination inside the fused jit, which is the
        compiler-backed version of the reference's compute groups
        (reference collections.py:269-303 maintains them by hand).

        Only tensor-state metrics participate (concat states are host-side by design);
        a metric with list states raises ``TorchMetricsUserError`` at trace time.
        """
        return PureCollection(self)


class PureCollection:
    """Pure functional view of a :class:`MetricCollection` (see ``as_pure``)."""

    def __init__(self, collection: MetricCollection) -> None:
        self._metrics = OrderedDict(collection.items(keep_base=True))
        self._set_name = collection._set_name

    def init(self) -> Dict[str, Any]:
        """Fresh default states, keyed by metric name."""
        return {name: m.init_state() for name, m in self._metrics.items()}

    def update(self, states: Dict[str, Any], *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Fold one batch into every metric's state (pure, jittable)."""
        return {
            name: m.update_state(states[name], *args, **m._filter_kwargs(**kwargs))
            for name, m in self._metrics.items()
        }

    def compute(self, states: Dict[str, Any]) -> Dict[str, Any]:
        """Values for every metric from its state (pure, jittable). Key naming follows
        the stateful path's ``_flatten_res`` (bare sub-keys unless they collide)."""
        res = {name: m.compute_state(states[name]) for name, m in self._metrics.items()}
        return _flatten_with_naming(res, self._set_name)

    def apply(self, states: Dict[str, Any], *args: Any, **kwargs: Any) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Fused eval step: update all states AND emit current values (pure)."""
        new_states = self.update(states, *args, **kwargs)
        return new_states, self.compute(new_states)

    def reduce(self, states: Dict[str, Any], axis_name: Any) -> Dict[str, Any]:
        """Cross-device reduction of every member's state inside ``shard_map``,
        coalesced across the WHOLE collection: all members' leaves share one
        collective per (reduction-class × dtype) bucket instead of one per
        leaf. Members overriding ``reduce_state`` (exact-fold metrics like
        Pearson) keep their own reduction."""
        out: Dict[str, Any] = {}
        default_names = [
            name for name, m in self._metrics.items()
            if type(m).reduce_state is Metric.reduce_state
        ]
        for name, m in self._metrics.items():
            if name not in default_names:
                out[name] = m.reduce_state(states[name], axis_name)
        if default_names:
            reduced = _coalesce.reduce_many(
                [(states[n], self._metrics[n]._reductions) for n in default_names], axis_name
            )
            out.update(dict(zip(default_names, reduced)))
        return {name: out[name] for name in self._metrics}
