"""Core ``Metric`` runtime — TPU-native redesign of the reference's
``src/torchmetrics/metric.py`` (1,312 LoC).

Reference design: stateful ``nn.Module`` with in-place tensor mutation, a double-update
``forward`` trick (metric.py:287-402), and a barrier+pad+gather sync protocol
(metric.py:501-540).

TPU-native design (SURVEY §7 translation table): the metric is a **pytree of pure
functions** —

    init()                     -> State                  (dict pytree)
    _batch_state(*inputs)      -> State  (this batch's contribution; REQUIRED, pure)
    _merge(a, b)               -> State  (fold; default driven by per-state reduce tag)
    _compute(State)            -> value  (REQUIRED, pure for tensor-state metrics)

Everything else falls out of purity:

- ``update``  = one jitted, buffer-donated XLA call: ``merge(global, batch_state(x))``.
- ``forward`` = same call, additionally returning ``compute(batch_state)`` — no
  cache/restore gymnastics (reference's ``_forward_full_state_update`` double-update).
- ``merge_state`` = pytree fold (free).
- sync = bucketed ``psum/pmax/pmin/all_gather`` over mesh axes (in-graph) or a
  coalesced process-allgather + fold (multi-controller) — one collective per
  (reduction-class × dtype) bucket, not per leaf; see ``parallel/sync.py`` and
  ``parallel/coalesce.py``.
- checkpoint = the state dict *is* a pytree; hand it to orbax as-is.

A thin stateful OO shell on top preserves the reference's public API surface
(``add_state``/``update``/``compute``/``reset``/``forward``/``merge_state``/operator
arithmetic/persistence).

Concat ("cat") states hold dynamic-length data and therefore live as host-side lists of
device arrays (appended per batch, concatenated at compute) — XLA requires static
shapes; metrics that can express their state in static shape (binned curves, sufficient
statistics) always do so.
"""

from __future__ import annotations

import functools
import inspect
from copy import deepcopy
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import aot as _aot
from . import observability as _observability
from .observability import costs as _obs_costs
from .observability import memory as _obs_memory
from .observability import tracing as _tracing
from .parallel import sync as _sync
from .reliability.guards import validate_restored, validate_state
from .reliability.retry import ReliabilityConfig
from .utilities.checks import _is_traced
from .utilities.data import _flatten, dim_zero_cat
from .utilities.exceptions import TorchMetricsUserError
from .utilities.prints import rank_zero_warn

Array = jax.Array
StateDict = Dict[str, Any]

_ALLOWED_REDUCE = ("sum", "mean", "cat", "min", "max", None)

#: reserved leaf name for the per-row update-count vector a serving stack
#: carries next to the real tensor states (``torchmetrics_tpu/serving``) —
#: the stacked analogue of the scalar ``_device_update_count`` counter
TENANT_COUNT_KEY = "__tenant_n"

#: reserved leaves inside a :class:`~torchmetrics_tpu.streaming.SlidingWindow`
#: ring pytree: the monotone roll cursor (slot = cursor mod window, kept on
#: device so rolling never pays a per-update host round-trip) and the per-slot
#: fill vector ("has this bucket received an update yet") that window folds
#: mask on
WINDOW_CURSOR_KEY = "__window_cursor"
WINDOW_COUNT_KEY = "__window_n"

#: reserved leaf carrying an :class:`~torchmetrics_tpu.streaming.
#: ExponentialDecay` wrapper's decayed update-weight scalar (the
#: exponentially-discounted analogue of ``_device_update_count`` — the weight
#: "mean" states fold against)
DECAY_WEIGHT_KEY = "__decay_n"

#: reserved leaf-name prefixes inside the two-stack window representation
#: (``streaming.SlidingWindow`` tier "two_stack" and the serving engine's
#: windowed tenant stacks): each real tensor-state name ``k`` gets companion
#: accumulator leaves under ``prefix + k`` — the (DABA-style) front
#: suffix-fold stack, the back pane-fold stack, and the running fold of the
#: back stack. The dual tier needs no companion leaves: its pair packs into
#: one ``(2, *shape)`` leaf under the state's own name (row 0 = expiring
#: previous block, row 1 = current block) — fewer buffers per donated call
#: than the ring.
WINDOW_FRONT_KEY = "__window_front:"
WINDOW_BACK_KEY = "__window_back:"
WINDOW_BAGG_KEY = "__window_bagg:"

#: window tiers, in preference order: "dual" (constant pair of block
#: accumulators — sum/mean/None reduce-tags), "two_stack" (DABA-style paned
#: two-stack — adds max/min/callable semigroup folds), "ring" (the PR 10
#: per-update bucket ring — custom merges, list/cat states, exact trailing-N)
WINDOW_TIERS = ("dual", "two_stack", "ring")

#: reserved leaf-name prefix of the quantized sync plane's error-feedback
#: residual buffers (``parallel/quantize.py`` — the store keys residuals as
#: ``prefix + "<state_idx>:<leaf_name>"``). Mirrors
#: ``parallel.quantize.RESIDUAL_KEY_PREFIX`` (pinned equal by test) so the
#: graftlint reserved-key registry, which parses metric.py's ``*_KEY``
#: constants, covers the quant namespace too.
QUANT_RESIDUAL_KEY = "__quant_err:"


def _fresh_leaf(default: Any) -> Array:
    """Fresh device buffer from a state default, with no device→host readback.

    ``update()`` donates state buffers to XLA, so the live state must never alias the
    default. Device-array defaults are value-copied on device (``jnp.copy``); host
    (numpy/python) defaults upload. Reading a device default back through numpy is
    deliberately avoided: a single D2H readback flips tunneled TPU runtimes into
    synchronous per-call dispatch for the rest of the process (~80x slower)."""
    if isinstance(default, jax.Array):
        return jnp.copy(default)
    return jnp.asarray(default)


# ---------------------------------------------------------------------------
# Tiered window representation (streaming.SlidingWindow / serving window=)
#
# The recurrent↔dual trade from compiler-first O(1)-caching stacks
# (arXiv:2603.09555) applied to metric algebra: the PR 10 ring is the "dual"
# (attention-like) form — it materializes every update's contribution and is
# exact at per-update granularity, at O(window) HBM. The recurrent forms below
# collapse the window to a CONSTANT number of accumulators; the window
# boundary then advances in hops (block/pane granularity), and the value is
# exactly the metric over the trailing ``covered`` updates with
# ``window <= covered < window + hop``. Which form a metric gets is derived
# from its reduce-tags (`window_tier`), the same derivation graftlint's
# admissibility matrix performs statically.
# ---------------------------------------------------------------------------

#: fixed two-stack depth: panes per window. Window-independent by
#: construction — a 100k-update window still costs 2·depth+2 accumulators.
WINDOW_STACK_DEPTH = 16


def window_tier(metric: "Metric") -> str:
    """The tiered-window representation this metric's reduce-tags admit.

    - ``"dual"`` — every tensor reduction is ``sum``/``mean``/``None``: the
      window collapses to a pair of block accumulators (running current block
      + expiring previous block), no ring, no scatter.
    - ``"two_stack"`` — additionally ``max``/``min``/callable semigroup
      folds: a DABA-style paned two-stack (front suffix-fold stack + back
      pane-fold stack + flip), O(1) amortized, window-independent memory.
    - ``"ring"`` — custom ``_merge`` or list ("cat") states: only the PR 10
      per-update bucket ring can represent them (also the exact-trailing-N
      opt-in for any metric).
    """
    if metric._has_custom_merge() or metric._list_state_names:
        return "ring"
    tags = set()
    for fx in metric._reductions.values():
        if fx == "cat":
            return "ring"  # cat TENSOR state (the wrapper rejects it anyway)
        tags.add("callable" if callable(fx) else fx)
    if tags <= {"sum", "mean", None}:
        return "dual"
    if tags <= {"sum", "mean", "max", "min", None, "callable"}:
        return "two_stack"
    return "ring"


def window_stack_geometry(window: int, pane: Optional[int] = None) -> Tuple[int, int]:
    """``(pane_size, depth)`` for a two-stack window: ``depth`` panes of
    ``pane_size`` updates each, ``depth * pane_size >= window``. ``pane=1``
    degenerates to exact per-update sliding (memory 2·window); the default
    keeps depth at :data:`WINDOW_STACK_DEPTH` so memory is window-independent."""
    if pane is None:
        pane = max(1, -(-int(window) // WINDOW_STACK_DEPTH))  # ceil division
    pane = int(pane)
    if pane < 1:
        raise ValueError(f"Expected `pane` >= 1, got {pane}")
    depth = max(1, -(-int(window) // pane))
    return pane, depth


def _window_init_leaf(default: Any, fx: Any) -> Array:
    """The merge-identity start value for one window accumulator: sum/mean
    leaves accumulate CONTRIBUTIONS only (zeros; the metric default is folded
    back in once at fold time, and mean leaves ride their own weight), while
    max/min/callable/None leaves start at the metric default — which IS their
    merge identity (the ring fold relies on the same invariant).

    Accumulator dtype policy: integer ``sum``/``mean`` leaves promote (int64
    under x64, else float32 — exact for counts below 2^24) so a 100k-update
    window of int32 counts cannot silently saturate; every other leaf keeps
    the metric's own dtype. Documented in docs/streaming.md ("Accumulator
    dtypes"). Dtype inspection is metadata-only and static under trace."""
    d = jnp.asarray(default)
    if fx in ("sum", "mean"):
        if jnp.issubdtype(d.dtype, jnp.integer):
            d = d.astype(jnp.int64 if jax.config.jax_enable_x64 else jnp.float32)
        return jnp.zeros_like(d)
    return jnp.copy(d)


def window_defaults(
    metric: "Metric", window: int, tier: str, pane: Optional[int] = None
) -> StateDict:
    """The default (empty) windowed state pytree for one stream — the single
    definition of each tier's state layout, shared by ``SlidingWindow`` and
    the serving engine's per-tenant stacks (which add a leading row axis)."""
    defaults_t, _ = metric._split_tensor_list(metric.init_state())
    reductions = metric._reductions
    st: StateDict = {}
    if tier == "dual":
        for k, v in defaults_t.items():
            init = _window_init_leaf(v, reductions.get(k))
            # packed pair under ONE leaf: row 0 = previous (expiring) block,
            # row 1 = current block — half the buffers of a two-dict layout,
            # and buffer count is what a donated dispatch pays per call
            st[k] = jnp.repeat(init[None], 2, axis=0)
        st[WINDOW_COUNT_KEY] = jnp.zeros((2,), jnp.float32)  # [prev_n, cur_n]
    elif tier == "two_stack":
        _, depth = window_stack_geometry(window, pane)
        for k, v in defaults_t.items():
            fx = reductions.get(k)
            init = _window_init_leaf(v, fx)
            st[k] = init  # current (partial) pane fold
            st[WINDOW_BAGG_KEY + k] = jnp.copy(init)  # running fold of the back stack
            st[WINDOW_FRONT_KEY + k] = jnp.repeat(init[None], depth, axis=0)
            st[WINDOW_BACK_KEY + k] = jnp.repeat(init[None], depth, axis=0)
        st[WINDOW_COUNT_KEY] = jnp.zeros((3,), jnp.float32)  # [front, back, cur-pane]
    else:  # pragma: no cover — callers route "ring" to the bucket-ring layout
        raise ValueError(f"window_defaults builds 'dual'/'two_stack' layouts, not {tier!r}")
    return st


def _fold_tag(fx: Any, a, b, w_a, w_b):
    """Merge two window accumulators of one state in STREAM ORDER (``a``
    older) under its reduce tag; ``w_*`` are the update counts each side
    covers ("mean" weights; other tags ignore them)."""
    if fx == "mean":
        return _sync.weighted_mean(a, b, w_a, w_b)
    if fx == "sum":
        return a + jnp.asarray(b).astype(jnp.asarray(a).dtype)
    if fx is None:
        return a
    return _sync.pairwise_merge(fx, a, b)


def _dual_step(reductions: Dict[str, Any], defaults_t: StateDict,
               st: StateDict, window, bs_t: StateDict) -> StateDict:
    """One dual-pair window update (single stream; the vmapped serving form
    maps this over tenant rows). Fold the batch into the current block; when
    the block reaches ``window`` updates, rotate: current becomes the
    previous (expiring) block and a fresh block starts. No scatter, no
    cursor indexing — ``window`` is a TRACED scalar, so one executable
    serves every window length."""
    counts = st[WINDOW_COUNT_KEY]
    cur_n = counts[1]
    new_n = cur_n + 1.0
    rotate = new_n >= window
    out: StateDict = {}
    for k in defaults_t:
        pair = st[k]  # (2, *shape): [previous block, current block]
        fx = reductions.get(k)
        b = bs_t.get(k)
        if b is None or fx is None:
            new_cur = pair[1]
        else:
            new_cur = jnp.asarray(_fold_tag(fx, pair[1], b, cur_n, 1.0)).astype(pair.dtype)
        init = _window_init_leaf(defaults_t[k], fx)
        out[k] = jnp.where(
            rotate,
            jnp.stack([new_cur, init]),  # current becomes the expiring block
            pair.at[1].set(new_cur),
        )
    out[WINDOW_COUNT_KEY] = jnp.where(
        rotate,
        jnp.stack([new_n, jnp.zeros_like(new_n)]),
        jnp.stack([counts[0], new_n]),
    )
    return out


def _dual_fold(reductions: Dict[str, Any], defaults_t: StateDict, st: StateDict) -> StateDict:
    """Collapse a dual pair into one compute-ready state: previous block ⊕
    current block, exactly the metric over the trailing
    ``prev_n + cur_n`` updates."""
    counts = st[WINDOW_COUNT_KEY]
    prev_n, cur_n = counts[0], counts[1]
    total = prev_n + cur_n
    out: StateDict = {}
    for k, default in defaults_t.items():
        fx = reductions.get(k)
        d = jnp.asarray(default)
        pair = st[k]  # (2, *shape): [previous block, current block]
        if fx == "sum":
            out[k] = d.astype(pair.dtype) + pair.sum(axis=0)
        elif fx == "mean":
            merged = _sync.weighted_mean(pair[0], pair[1], prev_n, cur_n)
            out[k] = jnp.where(total > 0, merged, d.astype(pair.dtype)).astype(pair.dtype)
        else:  # fx None: keep the local default, exactly as update() does
            out[k] = d
    return out


def _stack_step(reductions: Dict[str, Any], defaults_t: StateDict, depth: int,
                st: StateDict, pane, bs_t: StateDict) -> StateDict:
    """One DABA-style two-stack window update (single stream).

    The window is ``depth`` panes of ``pane`` updates (``pane`` traced,
    ``depth`` static from the stack shapes). The batch folds into the current
    pane; a completed pane is pushed onto the back stack (one tiny
    ``depth``-axis scatter) and folded into the running back aggregate; once
    the window is full each push evicts the oldest front pane by bumping the
    front position — O(1), the front stack holds PRECOMPUTED suffix folds.
    When the front drains, the flip recomputes the suffix folds of the (by
    then exactly full) back stack — ``depth`` static merges, amortized
    O(1/depth) per update, and evaluated under ``where`` so the whole update
    stays ONE branch-free XLA program."""
    counts = st[WINDOW_COUNT_KEY]
    fc, bc, cc = counts[0], counts[1], counts[2]
    cc_next = cc + 1.0
    complete = cc_next >= pane
    d_f = jnp.float32(depth)
    full = (fc + bc) >= d_f
    flip = complete & full & (fc <= 0.0)
    evict = complete & full
    fc_after = jnp.where(flip, d_f - 1.0, jnp.where(evict, fc - 1.0, fc))
    bc_base = jnp.where(flip, 0.0, bc)  # panes in the back stack pre-push
    bc_after = jnp.where(complete, bc_base + 1.0, bc)
    cc_after = jnp.where(complete, 0.0, cc_next)
    push_idx = jnp.where(complete, bc_base, d_f).astype(jnp.int32)  # d = dropped no-op

    out: StateDict = {}
    for k in defaults_t:
        fx = reductions.get(k)
        b = bs_t.get(k)
        cur = st[k]
        if b is None or fx is None:
            pane_fold = cur
        else:
            pane_fold = jnp.asarray(_fold_tag(fx, cur, b, cc, 1.0)).astype(cur.dtype)
        init = _window_init_leaf(defaults_t[k], fx)
        F, B, A = st[WINDOW_FRONT_KEY + k], st[WINDOW_BACK_KEY + k], st[WINDOW_BAGG_KEY + k]
        # flip: suffix folds of the full back stack, oldest-first stream order
        # (static loop — depth is a shape constant, the trace unrolls it)
        suffix = init
        flip_rows: List[Array] = []
        for i in reversed(range(depth)):
            suffix = jnp.asarray(
                _fold_tag(fx, B[i], suffix, pane, (depth - 1 - i) * pane)
            ).astype(cur.dtype)
            flip_rows.append(suffix)
        F_flip = jnp.stack(flip_rows[::-1], axis=0)
        out[WINDOW_FRONT_KEY + k] = jnp.where(flip, F_flip, F)
        # push the completed pane into the back stack + running aggregate
        out[WINDOW_BACK_KEY + k] = B.at[push_idx].set(
            pane_fold.astype(B.dtype), mode="drop"
        )
        A_base = jnp.where(flip, init, A)
        A_pushed = jnp.asarray(
            _fold_tag(fx, A_base, pane_fold, bc_base * pane, cc_next)
        ).astype(cur.dtype)
        out[WINDOW_BAGG_KEY + k] = jnp.where(complete, A_pushed, A)
        out[k] = jnp.where(complete, init, pane_fold)
    out[WINDOW_COUNT_KEY] = jnp.stack([fc_after, bc_after, cc_after])
    return out


def _stack_fold(reductions: Dict[str, Any], defaults_t: StateDict, depth: int,
                st: StateDict, pane) -> StateDict:
    """Collapse a two-stack window into one compute-ready state: front
    suffix-fold (oldest panes, precomputed) ⊕ back aggregate ⊕ current
    partial pane, in stream order."""
    counts = st[WINDOW_COUNT_KEY]
    fc, bc, cc = counts[0], counts[1], counts[2]
    front_n = fc * pane
    back_n = bc * pane
    total = front_n + back_n + cc
    front_pos = jnp.clip(depth - fc, 0, depth - 1).astype(jnp.int32)
    out: StateDict = {}
    for k, default in defaults_t.items():
        fx = reductions.get(k)
        d = jnp.asarray(default)
        init = _window_init_leaf(default, fx)
        top = jnp.take(st[WINDOW_FRONT_KEY + k], front_pos, axis=0)
        acc = jnp.where(fc > 0, top, init)
        acc = jnp.asarray(_fold_tag(fx, acc, st[WINDOW_BAGG_KEY + k], front_n, back_n))
        acc = jnp.asarray(_fold_tag(fx, acc, st[k], front_n + back_n, cc)).astype(init.dtype)
        if fx == "sum":
            out[k] = d.astype(acc.dtype) + acc
        elif fx == "mean":
            out[k] = jnp.where(total > 0, acc, d.astype(acc.dtype))
        elif fx is None:
            out[k] = d
        else:  # max/min/callable: init IS the default (merge identity)
            out[k] = acc
    return out


class Metric:
    """Base class for all metrics (stateful shell over a pure core).

    Subclass contract::

        class MyMetric(Metric):
            def __init__(self, **kwargs):
                super().__init__(**kwargs)
                self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")

            def _batch_state(self, preds, target) -> dict:   # pure, jit-traced
                return {"total": (preds == target).sum()}

            def _compute(self, state) -> jax.Array:          # pure
                return state["total"]

    Supported kwargs (parity with reference metric.py:105-154):
    ``compute_on_cpu``, ``dist_sync_on_step``, ``process_group`` (mesh axis name(s)),
    ``dist_sync_fn``, ``distributed_available_fn``, ``sync_on_compute``,
    ``compute_with_cache``, plus TPU-specific ``jit`` (default True) to disable the
    jitted update path for debugging, and ``reliability`` (a
    :class:`~torchmetrics_tpu.reliability.ReliabilityConfig`, default ``None``) to
    opt into transient-failure retry at the dispatch boundaries and state-integrity
    guards at sync/merge/restore boundaries.
    """

    __jit_warned = False

    is_differentiable: Optional[bool] = None
    higher_is_better: Optional[bool] = None
    full_state_update: Optional[bool] = False  # parity attr; purity makes it moot
    plot_lower_bound: Optional[float] = None
    plot_upper_bound: Optional[float] = None
    plot_legend_name: Optional[str] = None
    _jittable_compute: bool = True  # False => batch-value/compute run eagerly (host path)

    def __init__(self, **kwargs: Any) -> None:
        self._device = None
        self._dtype = None

        self.compute_on_cpu = kwargs.pop("compute_on_cpu", False)
        self.dist_sync_on_step = kwargs.pop("dist_sync_on_step", False)
        if not isinstance(self.dist_sync_on_step, bool):
            raise ValueError(f"Expected keyword argument `dist_sync_on_step` to be a `bool` but got {self.dist_sync_on_step}")
        self.process_group = kwargs.pop("process_group", None)
        self.dist_sync_fn = kwargs.pop("dist_sync_fn", None)
        if self.dist_sync_fn is not None and not callable(self.dist_sync_fn):
            raise ValueError(f"Expected keyword argument `dist_sync_fn` to be an callable function but got {self.dist_sync_fn}")
        self.distributed_available_fn = kwargs.pop("distributed_available_fn", None) or _sync.distributed_available
        self.sync_on_compute = kwargs.pop("sync_on_compute", True)
        if not isinstance(self.sync_on_compute, bool):
            raise ValueError(f"Expected keyword argument `sync_on_compute` to be a `bool` but got {self.sync_on_compute}")
        self.compute_with_cache = kwargs.pop("compute_with_cache", True)
        self._enable_jit = kwargs.pop("jit", True)
        self._reliability = kwargs.pop("reliability", None)
        if self._reliability is not None and not isinstance(self._reliability, ReliabilityConfig):
            raise ValueError(
                f"Expected keyword argument `reliability` to be a `ReliabilityConfig` but got {self._reliability}"
            )
        self._fault_hook = None  # fault-injection seam (reliability/faults.py)
        if kwargs:
            kwargs_ = [f"`{a}`" for a in sorted(kwargs)]
            raise ValueError(f"Unexpected keyword arguments: {', '.join(kwargs_)}")

        self._defaults: Dict[str, Any] = {}
        self._reductions: Dict[str, Any] = {}
        self._persistent: Dict[str, bool] = {}
        self._state: StateDict = {}

        self._update_count = 0
        self._computed: Any = None
        self._is_synced = False
        self._cache: Optional[StateDict] = None
        self._jit_cache: Dict[str, Callable] = {}
        self._update_called_warned = False

    # ------------------------------------------------------------------ states

    def add_state(
        self,
        name: str,
        default: Any,
        dist_reduce_fx: Optional[Union[str, Callable]] = None,
        persistent: bool = False,
    ) -> None:
        """Register a metric state (reference metric.py:201-284).

        ``default`` is either an array (tensor state — lives in the jitted path) or an
        empty list (concat state — host list of per-batch arrays).
        """
        if not isinstance(default, (list,)) and not hasattr(default, "shape"):
            default = np.asarray(default)
        if isinstance(default, list) and default != []:
            raise ValueError("state variable must be a tensor or any empty list (where you can append tensors)")
        if dist_reduce_fx not in _ALLOWED_REDUCE and not callable(dist_reduce_fx):
            raise ValueError("`dist_reduce_fx` must be callable or one of ['mean', 'sum', 'cat', 'min', 'max', None]")
        if isinstance(default, list) and dist_reduce_fx is None:
            dist_reduce_fx = "cat"
        if name in ("_defaults", "_reductions", "_persistent", "_state"):
            raise ValueError(f"The name `{name}` is reserved.")

        # The default is kept wherever it was born — numpy defaults stay numpy, device
        # defaults stay on device. Reading a device array back (np.asarray) is NOT an
        # option here: one D2H readback flips tunneled TPU runtimes into synchronous
        # dispatch for the rest of the process (~80x slower per jitted call). The live
        # state gets a fresh buffer either way, because update() donates state buffers
        # to XLA and an aliased default would be deleted by the first update.
        self._defaults[name] = default
        self._reductions[name] = dist_reduce_fx
        self._persistent[name] = persistent
        self._state[name] = [] if isinstance(default, list) else _fresh_leaf(default)
        self._jit_cache.clear()
        self.__dict__.pop("_aot_memo", None)  # state layout changed — loaded programs are stale

    @property
    def _list_state_names(self) -> Tuple[str, ...]:
        return tuple(n for n, d in self._defaults.items() if isinstance(d, list))

    @property
    def _tensor_state_names(self) -> Tuple[str, ...]:
        return tuple(n for n, d in self._defaults.items() if not isinstance(d, list))

    def __getattr__(self, name: str):
        state = self.__dict__.get("_state")
        if state is not None and name in state:
            return state[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __setattr__(self, name: str, value: Any) -> None:
        state = self.__dict__.get("_state")
        if state is not None and name in state:
            state[name] = value
            return
        object.__setattr__(self, name, value)

    # ------------------------------------------------------------- pure core

    def init_state(self) -> StateDict:
        """Fresh default state (pure)."""
        return {n: ([] if isinstance(d, list) else _fresh_leaf(d)) for n, d in self._defaults.items()}

    def _batch_state(self, *args: Any, **kwargs: Any) -> StateDict:
        """This batch's state contribution (pure, jit-traced). REQUIRED override."""
        raise NotImplementedError

    def _merge(self, a: StateDict, b: StateDict) -> StateDict:
        """Fold ``b`` into ``a``; default uses per-state reduce tags (pure)."""
        return _sync.merge_states(a, b, self._reductions)

    def _compute(self, state: StateDict) -> Any:
        """Final value from a state whose concat states are single arrays. REQUIRED."""
        raise NotImplementedError

    def _prepare_inputs(self, *args: Any, **kwargs: Any) -> Tuple[tuple, dict]:
        """Host-side validation/formatting hook run OUTSIDE jit. Default: identity."""
        return args, kwargs

    # pure in-graph API -----------------------------------------------------

    def update_state(self, state: StateDict, *args: Any, **kwargs: Any) -> StateDict:
        """Pure update for use inside user ``jit``/``shard_map`` (tensor-state only)."""
        if self._list_state_names:
            raise TorchMetricsUserError(
                f"{type(self).__name__} holds dynamic-length concat states and cannot run fully in-graph; "
                "use the stateful API or a binned/static variant."
            )
        if not self._has_custom_merge() and any(fx == "mean" for fx in self._reductions.values()):
            # a bare mean state cannot fold statelessly — without an update count the
            # repeated (a+b)/2 fold diverges from the stateful API's exact running mean
            raise TorchMetricsUserError(
                f"{type(self).__name__} has a 'mean'-reduced state, which cannot fold in-graph "
                "without an update count. Keep sum+weight states instead (see MeanMetric) or "
                "override `_merge`."
            )
        return self._merge(state, self._batch_state(*args, **kwargs))

    def compute_state(self, state: StateDict) -> Any:
        """Pure compute for use inside user ``jit`` (when ``_jittable_compute``)."""
        if not self._jittable_compute:
            leaves = [v for v in jax.tree.leaves(state) if hasattr(v, "dtype")]
            if leaves and _is_traced(*leaves):
                # fail at trace time with guidance instead of a cryptic
                # TracerArrayConversionError from the host-side numpy compute
                raise TorchMetricsUserError(
                    f"{type(self).__name__}.compute runs on host (f64 edge-case handling or "
                    "host algorithms) and cannot trace under jit. Call `pure.compute(states)` "
                    "OUTSIDE jit for collections containing it, and jit only `pure.update`."
                )
        return self._compute(state)

    def reduce_state(self, state: StateDict, axis_name: Union[str, Sequence[str]]) -> StateDict:
        """Cross-device reduction inside ``shard_map`` — coalesced: one
        collective per (reduction-class × dtype) bucket, not one per leaf."""
        return _sync.reduce_states(state, self._reductions, axis_name)

    # ------------------------------------------------------------- lifecycle

    def _split_tensor_list(self, state: StateDict) -> Tuple[StateDict, StateDict]:
        lists = {k: state[k] for k in self._list_state_names if k in state}
        tensors = {k: v for k, v in state.items() if k not in lists}
        return tensors, lists

    def _get_update_fn(self) -> Callable:
        key = "update"
        if key not in self._jit_cache:
            list_names = set(self._list_state_names)

            def fn(tensor_state, n_prev, *args, **kwargs):
                # named_scope: trace-time HLO name prefixes so this metric's ops
                # stay attributable in the xprof device view even after XLA fuses
                # a whole collection into one program (zero runtime cost)
                with jax.named_scope(f"{type(self).__name__}.batch_state"):
                    bs = self._batch_state(*args, **kwargs)
                appends = {k: v for k, v in bs.items() if k in list_names}
                bs_t = {k: v for k, v in bs.items() if k not in list_names}
                # n_prev (prior update count, a DEVICE scalar incremented in-graph —
                # a per-update host transfer costs ~1.7ms through a TPU tunnel) makes
                # "mean" states an exact running mean over updates (reference
                # metric.py:481); other tags ignore the weights
                with jax.named_scope(f"{type(self).__name__}.merge"):
                    new_t = {k: _sync.pairwise_merge(self._reductions.get(k), tensor_state[k], v, weights=(n_prev, 1.0)) for k, v in bs_t.items()} if not self._has_custom_merge() else None
                    if new_t is None:
                        new_t = self._merge({**tensor_state}, bs_t)
                # keep state dtype stable under merge promotion (set_dtype semantics)
                new_t = {k: jnp.asarray(v).astype(tensor_state[k].dtype) if k in tensor_state else v for k, v in new_t.items()}
                # carry through tensor states the batch didn't touch
                for k, v in tensor_state.items():
                    new_t.setdefault(k, v)
                return new_t, appends, n_prev + 1.0

            self._jit_cache[f"{key}.raw"] = fn  # undonated source for _aot_program
            self._jit_cache[key] = jax.jit(fn, donate_argnums=(0, 1)) if self._enable_jit else fn
        return self._jit_cache[key]

    def _get_forward_fn(self) -> Callable:
        key = "forward"
        if key not in self._jit_cache:
            list_names = set(self._list_state_names)

            def fn(tensor_state, n_prev, *args, **kwargs):
                with jax.named_scope(f"{type(self).__name__}.batch_state"):
                    bs = self._batch_state(*args, **kwargs)
                appends = {k: v for k, v in bs.items() if k in list_names}
                bs_t = {k: v for k, v in bs.items() if k not in list_names}
                with jax.named_scope(f"{type(self).__name__}.merge"):
                    new_t = self._merge(dict(tensor_state), bs_t) if self._has_custom_merge() else {
                        k: _sync.pairwise_merge(self._reductions.get(k), tensor_state[k], v, weights=(n_prev, 1.0))
                        for k, v in bs_t.items()
                    }
                new_t = {k: jnp.asarray(v).astype(tensor_state[k].dtype) if k in tensor_state else v for k, v in new_t.items()}
                for k, v in tensor_state.items():
                    new_t.setdefault(k, v)
                batch_full = dict(bs_t)
                defaults_t, _ = self._split_tensor_list(self.init_state())
                for k, v in defaults_t.items():
                    batch_full.setdefault(k, v)
                batch_full.update(appends)
                with jax.named_scope(f"{type(self).__name__}.compute"):
                    val = self._compute(batch_full) if self._jittable_compute else None
                return new_t, appends, val, batch_full

            self._jit_cache[f"{key}.raw"] = fn  # undonated source for _aot_program
            self._jit_cache[key] = jax.jit(fn, donate_argnums=0) if (self._enable_jit and self._jittable_compute) else fn
        return self._jit_cache[key]

    def _get_vupdate_fn(self) -> Callable:
        """The vmapped megabatch program behind the serving engine's stacked
        dispatch (``torchmetrics_tpu/serving``): ONE XLA call updates many
        logical metric states held as a stacked pytree.

        Calling convention (fixed by ``_donation_safe_dispatch`` and the AOT
        plane): ``fn(stacked, n_scalar, idx, args, kwargs)`` where ``stacked``
        maps every tensor-state name to a ``(rows, *state_shape)`` array plus
        the :data:`TENANT_COUNT_KEY` per-row update-count vector, ``idx`` is
        the ``(M,)`` int32 row address of each megabatch entry, and
        ``args``/``kwargs`` are the per-entry batch pytrees stacked along a
        leading ``M`` axis. The body gathers the addressed rows, applies the
        SAME single-metric update fold (``update.raw`` — running-mean weights
        included, so per-row semantics are identical to ``update()``) under
        ``jax.vmap``, and scatters the results back; rows ``idx`` does not
        address pass through untouched. Only the stacked dict is donated —
        the scalar counter argument is the calling-convention placeholder
        every dispatch tag shares (serving keeps its real per-row counts
        inside the stack), and donating it would delete the live
        ``_n_prev_dev`` buffer under the ordinary update path.
        """
        key = "vupdate"
        if key not in self._jit_cache:
            if self._list_state_names:
                raise TorchMetricsUserError(
                    f"{type(self).__name__} holds dynamic-length concat states and cannot be "
                    "served from a stacked pytree; use a binned/static-shape variant."
                )
            self._get_update_fn()  # materializes the shared "update.raw" body
            raw = self._jit_cache["update.raw"]

            def per_row(tensor_state, n_prev, a, kw):
                new_t, _appends, n_next = raw(tensor_state, n_prev, *a, **kw)
                return new_t, n_next

            def fn(stacked, n_scalar, idx, args, kwargs):
                del n_scalar  # placeholder — see the docstring
                counts = stacked[TENANT_COUNT_KEY]
                states = {k: v for k, v in stacked.items() if k != TENANT_COUNT_KEY}
                with jax.named_scope(f"{type(self).__name__}.gather_rows"):
                    rows = {k: jnp.take(v, idx, axis=0) for k, v in states.items()}
                    n_rows = jnp.take(counts, idx, axis=0)
                new_rows, new_n = jax.vmap(per_row)(rows, n_rows, args, kwargs)
                with jax.named_scope(f"{type(self).__name__}.scatter_rows"):
                    out = {k: v.at[idx].set(new_rows[k]) for k, v in states.items()}
                    out[TENANT_COUNT_KEY] = counts.at[idx].set(new_n)
                return out

            self._jit_cache[f"{key}.raw"] = fn  # undonated source for _aot_program
            self._jit_cache[key] = jax.jit(fn, donate_argnums=0) if self._enable_jit else fn
        return self._jit_cache[key]

    def _get_wupdate_fn(self) -> Callable:
        """The windowed roll+scatter program behind
        :class:`~torchmetrics_tpu.streaming.SlidingWindow`: ONE donated XLA
        call writes this batch's isolated state contribution into the next
        ring slot.

        Calling convention (fixed by ``_donation_safe_dispatch`` and the AOT
        plane): ``fn(ring, n_scalar, *args, **kwargs)`` where ``ring`` maps
        every tensor-state name to a ``(window, *state_shape)`` bucket stack
        plus the :data:`WINDOW_CURSOR_KEY` roll counter and
        :data:`WINDOW_COUNT_KEY` per-slot fill vector. The body computes the
        batch state, scatters it into slot ``cursor % window`` (overwriting
        whatever expired there), marks the slot filled, and advances the
        cursor — O(1) work per update regardless of window size, and no
        unbounded concatenation anywhere. Leaves the batch does not touch
        store their DEFAULT value (the merge identity), so the window fold at
        compute time sees exactly what a fresh per-update metric would have
        produced. List ("cat") contributions are returned for the wrapper's
        bounded host-side ring, mirroring the plain update path. Only the
        ring dict is donated; the scalar counter argument is the shared
        calling-convention placeholder (see ``_get_vupdate_fn``)."""
        key = "wupdate"
        if key not in self._jit_cache:
            list_names = set(self._list_state_names)
            defaults_t, _ = self._split_tensor_list(self.init_state())
            reserved = (WINDOW_CURSOR_KEY, WINDOW_COUNT_KEY)

            def fn(ring, n_scalar, *args, **kwargs):
                del n_scalar  # placeholder — see _get_vupdate_fn's docstring
                cursor = ring[WINDOW_CURSOR_KEY]
                counts = ring[WINDOW_COUNT_KEY]
                states = {k: v for k, v in ring.items() if k not in reserved}
                slot = jnp.mod(cursor, counts.shape[0])
                with jax.named_scope(f"{type(self).__name__}.batch_state"):
                    bs = self._batch_state(*args, **kwargs)
                appends = {k: v for k, v in bs.items() if k in list_names}
                bs_t = {k: v for k, v in bs.items() if k not in list_names}
                with jax.named_scope(f"{type(self).__name__}.window_roll"):
                    out = {}
                    for k, v in states.items():
                        contrib = bs_t.get(k, defaults_t.get(k))
                        out[k] = v.at[slot].set(jnp.asarray(contrib).astype(v.dtype))
                    out[WINDOW_COUNT_KEY] = counts.at[slot].set(1.0)
                    out[WINDOW_CURSOR_KEY] = cursor + 1
                return out, appends

            self._jit_cache[f"{key}.raw"] = fn  # undonated source for _aot_program
            self._jit_cache[key] = jax.jit(fn, donate_argnums=0) if self._enable_jit else fn
        return self._jit_cache[key]

    def _get_dupdate_fn(self) -> Callable:
        """The exponentially-decayed update program behind
        :class:`~torchmetrics_tpu.streaming.ExponentialDecay`: the plain
        update fold with the decay factor folded into the accumulating
        leaves AT UPDATE TIME — O(1) state, no history.

        Calling convention: ``fn(tensors, n_scalar, decay, *args, **kwargs)``
        where ``tensors`` carries the decayed state plus the
        :data:`DECAY_WEIGHT_KEY` scalar (the discounted update count "mean"
        states weigh against) and ``decay`` is a traced f32 scalar — keeping
        it in data rather than baked into the program means one executable
        (and one AOT cache entry) serves every halflife. Per reduction tag:
        ``sum`` leaves scale by ``decay`` before absorbing the batch
        (untouched sum leaves still decay — the stream moved on), ``mean``
        leaves fold as a weighted mean against the decayed weight, ``max``/
        ``min``/``None`` keep their ordinary merge (a decayed extremum has no
        defined meaning). Metrics with custom ``_merge``, concat states, or
        callable reductions are rejected by the wrapper — an unknown fold
        cannot be discounted safely."""
        key = "dupdate"
        if key not in self._jit_cache:
            if self._list_state_names:
                raise TorchMetricsUserError(
                    f"{type(self).__name__} holds dynamic-length concat states; exponential "
                    "decay over an unbounded concatenation is undefined — use a "
                    "binned/sufficient-statistic variant."
                )
            if self._has_custom_merge():
                raise TorchMetricsUserError(
                    f"{type(self).__name__} overrides _merge; a decay factor cannot be folded "
                    "into an unknown merge safely."
                )
            reductions = dict(self._reductions)

            def fn(tensors, n_scalar, decay, *args, **kwargs):
                del n_scalar  # placeholder — see _get_vupdate_fn's docstring
                w = tensors[DECAY_WEIGHT_KEY]
                states = {k: v for k, v in tensors.items() if k != DECAY_WEIGHT_KEY}
                with jax.named_scope(f"{type(self).__name__}.batch_state"):
                    bs = self._batch_state(*args, **kwargs)
                bs_t = {k: jnp.asarray(v) for k, v in bs.items()}
                with jax.named_scope(f"{type(self).__name__}.decay_merge"):
                    out = {}
                    for k, v in states.items():
                        fx = reductions.get(k)
                        b = bs_t.get(k)
                        if fx == "sum":
                            contrib = v.dtype.type(0) if b is None else b.astype(v.dtype)
                            out[k] = v * jnp.asarray(decay, v.dtype) + contrib
                        elif fx == "mean" and b is not None:
                            out[k] = jnp.asarray(
                                _sync.weighted_mean(v, b, w * decay, 1.0)
                            ).astype(v.dtype)
                        elif fx == "max" and b is not None:
                            out[k] = jnp.maximum(v, b.astype(v.dtype))
                        elif fx == "min" and b is not None:
                            out[k] = jnp.minimum(v, b.astype(v.dtype))
                        else:  # untouched non-sum leaves and fx=None: keep
                            out[k] = v
                    out[DECAY_WEIGHT_KEY] = w * decay + 1.0
                return out

            self._jit_cache[f"{key}.raw"] = fn  # undonated source for _aot_program
            self._jit_cache[key] = jax.jit(fn, donate_argnums=0) if self._enable_jit else fn
        return self._jit_cache[key]

    def _check_windowable(self, tier: str) -> None:
        """Construction-time guards for the constant-memory window tiers —
        the mirror of what :func:`window_tier` derives (and graftlint's
        matrix pins statically)."""
        if self._list_state_names:
            raise TorchMetricsUserError(
                f"{type(self).__name__} holds dynamic-length concat states; only the "
                "'ring' window tier can hold them (bounded host ring)."
            )
        if self._has_custom_merge():
            raise TorchMetricsUserError(
                f"{type(self).__name__} overrides _merge; an unknown merge cannot be "
                "folded into constant-size window accumulators — use the 'ring' tier."
            )
        allowed = ({"sum", "mean", None} if tier == "dual"
                   else {"sum", "mean", "max", "min", None})
        for name, fx in self._reductions.items():
            if callable(fx):
                if tier == "dual":
                    raise TorchMetricsUserError(
                        f"{type(self).__name__}.{name} uses a callable reduction; the dual "
                        "pair folds only sum/mean closed forms — use tier 'two_stack'."
                    )
                continue
            if fx not in allowed:
                raise TorchMetricsUserError(
                    f"{type(self).__name__}.{name} uses reduction {fx!r}, which the "
                    f"{tier!r} window tier cannot fold; use the 'ring' tier."
                )

    def _get_wdual_fn(self) -> Callable:
        """The dual-pair window program (tier 1 of the tiered window
        representation): ONE donated fused XLA call folds the batch into a
        constant-size pair of block accumulators — no ring, no roll-cursor
        scatter, state cost independent of the window length.

        Calling convention: ``fn(wstate, n_scalar, window, *args, **kwargs)``
        where ``wstate`` is the :func:`window_defaults` dual layout (one
        packed ``(2, *shape)`` pair per tensor state — row 0 the expiring
        previous block, row 1 the current block — plus the ``(2,)``
        :data:`WINDOW_COUNT_KEY` vector) and ``window`` is a TRACED
        f32 scalar — one executable (and one AOT cache entry) serves every
        window length, exactly like ``dupdate``'s traced decay. Returns only
        the new state: extra outputs cost real dispatch overhead on the hot
        path (``SlidingWindow.forward`` recomputes the batch value eagerly,
        like the ring tier's bucket read)."""
        key = "wdual"
        if key not in self._jit_cache:
            self._check_windowable("dual")
            reductions = dict(self._reductions)
            defaults_t, _ = self._split_tensor_list(self.init_state())

            def fn(wstate, n_scalar, window, *args, **kwargs):
                del n_scalar  # placeholder — see _get_vupdate_fn's docstring
                with jax.named_scope(f"{type(self).__name__}.batch_state"):
                    bs = self._batch_state(*args, **kwargs)
                bs_t = {k: jnp.asarray(v) for k, v in bs.items()}
                with jax.named_scope(f"{type(self).__name__}.window_dual"):
                    return _dual_step(reductions, defaults_t, wstate, window, bs_t)

            self._jit_cache[f"{key}.raw"] = fn  # undonated source for _aot_program
            self._jit_cache[key] = jax.jit(fn, donate_argnums=0) if self._enable_jit else fn
        return self._jit_cache[key]

    def _get_wstack_fn(self, depth: int) -> Callable:
        """The two-stack (DABA-style) window program (tier 2): ONE donated
        fused XLA call folds the batch into the current pane and — selected
        branch-free under ``where`` — pushes completed panes, evicts expired
        front panes, and flips the back stack into precomputed suffix folds
        when the front drains. ``depth`` (panes per window) is a static shape
        constant; the pane LENGTH is a traced scalar, so one executable per
        depth serves every window length.

        Calling convention: ``fn(wstate, n_scalar, pane, *args, **kwargs)``
        with the :func:`window_defaults` two-stack layout; returns only the
        new state, like ``wdual``."""
        key = "wstack"
        if key not in self._jit_cache:
            self._check_windowable("two_stack")
            reductions = dict(self._reductions)
            defaults_t, _ = self._split_tensor_list(self.init_state())
            self._jit_cache[f"{key}.depth"] = int(depth)

            def fn(wstate, n_scalar, pane, *args, **kwargs):
                del n_scalar  # placeholder — see _get_vupdate_fn's docstring
                with jax.named_scope(f"{type(self).__name__}.batch_state"):
                    bs = self._batch_state(*args, **kwargs)
                bs_t = {k: jnp.asarray(v) for k, v in bs.items()}
                with jax.named_scope(f"{type(self).__name__}.window_two_stack"):
                    return _stack_step(reductions, defaults_t, depth, wstate, pane, bs_t)

            self._jit_cache[f"{key}.raw"] = fn  # undonated source for _aot_program
            self._jit_cache[key] = jax.jit(fn, donate_argnums=0) if self._enable_jit else fn
        elif self._jit_cache.get(f"{key}.depth") != int(depth):
            raise TorchMetricsUserError(
                "one metric instance can back only one two-stack depth "
                f"(compiled {self._jit_cache.get(f'{key}.depth')}, requested {depth}); "
                "wrap a clone() for a different pane geometry."
            )
        return self._jit_cache[key]

    def _get_vwupdate_fn(self, tier: str, depth: int = 0) -> Callable:
        """The vmapped WINDOWED megabatch program behind
        ``ServingEngine(window=...)``: one XLA call advances many tenants'
        dual/two-stack window states held as a stacked pytree — the serving
        engine's leaves grow by a small constant factor, NOT ×window.

        Calling convention: ``fn(stacked, n_scalar, wparam, idx, args,
        kwargs)`` — like ``vupdate`` plus the traced window parameter
        (``window`` length for the dual tier, ``pane`` length for the
        two-stack tier) threaded through to every row's step."""
        key = "vwupdate"
        if key not in self._jit_cache:
            if self._list_state_names:
                raise TorchMetricsUserError(
                    f"{type(self).__name__} holds dynamic-length concat states and cannot be "
                    "served from a stacked pytree; use a binned/static-shape variant."
                )
            self._check_windowable(tier)
            self._jit_cache[f"{key}.tier"] = (tier, int(depth))
            reductions = dict(self._reductions)
            defaults_t, _ = self._split_tensor_list(self.init_state())

            def fn(stacked, n_scalar, wparam, idx, args, kwargs):
                del n_scalar  # placeholder — see _get_vupdate_fn's docstring
                counts = stacked[TENANT_COUNT_KEY]
                states = {k: v for k, v in stacked.items() if k != TENANT_COUNT_KEY}

                def per_row(row_state, n_prev, a, kw):
                    bs = self._batch_state(*a, **kw)
                    bs_t = {k: jnp.asarray(v) for k, v in bs.items()}
                    if tier == "dual":
                        new = _dual_step(reductions, defaults_t, row_state, wparam, bs_t)
                    else:
                        new = _stack_step(reductions, defaults_t, depth, row_state, wparam, bs_t)
                    return new, n_prev + 1.0

                with jax.named_scope(f"{type(self).__name__}.gather_rows"):
                    rows = {k: jnp.take(v, idx, axis=0) for k, v in states.items()}
                    n_rows = jnp.take(counts, idx, axis=0)
                new_rows, new_n = jax.vmap(per_row)(rows, n_rows, args, kwargs)
                with jax.named_scope(f"{type(self).__name__}.scatter_rows"):
                    out = {k: v.at[idx].set(new_rows[k]) for k, v in states.items()}
                    out[TENANT_COUNT_KEY] = counts.at[idx].set(new_n)
                return out

            self._jit_cache[f"{key}.raw"] = fn  # undonated source for _aot_program
            self._jit_cache[key] = jax.jit(fn, donate_argnums=0) if self._enable_jit else fn
        elif self._jit_cache.get(f"{key}.tier") != (tier, int(depth)):
            raise TorchMetricsUserError(
                "one metric instance can back only one windowed-serving geometry "
                f"(compiled {self._jit_cache.get(f'{key}.tier')}, requested {(tier, depth)})."
            )
        return self._jit_cache[key]

    def _get_vwcompute_fn(self, tier: str, depth: int = 0) -> Callable:
        """The vmapped windowed batch-compute program behind
        ``ServingEngine.compute_all`` when windowed: ONE undonated XLA call
        folds every row's dual/two-stack window AND computes it. The trailing
        batch args are signature carriers only (see ``_get_vcompute_fn``)."""
        key = "vwcompute"
        if key not in self._jit_cache:
            if not self._jittable_compute:
                raise TorchMetricsUserError(
                    f"{type(self).__name__}.compute runs on host and cannot vmap; "
                    "per-tenant compute falls back to eager slicing."
                )
            self._check_windowable(tier)
            self._jit_cache[f"{key}.tier"] = (tier, int(depth))
            reductions = dict(self._reductions)
            defaults_t, _ = self._split_tensor_list(self.init_state())

            def fn(stacked, n_scalar, wparam, *args, **kwargs):
                del n_scalar, args, kwargs  # shape-class identity carriers only
                states = {k: v for k, v in stacked.items() if k != TENANT_COUNT_KEY}

                def per_row(row_state):
                    if tier == "dual":
                        folded = _dual_fold(reductions, defaults_t, row_state)
                    else:
                        folded = _stack_fold(reductions, defaults_t, depth, row_state, wparam)
                    return self._compute(folded)

                with jax.named_scope(f"{type(self).__name__}.vwcompute"):
                    return jax.vmap(per_row)(states)

            self._jit_cache[f"{key}.raw"] = fn  # undonated source for _aot_program
            # no donation: compute is a read — the stack stays live for traffic
            self._jit_cache[key] = jax.jit(fn) if self._enable_jit else fn
        elif self._jit_cache.get(f"{key}.tier") != (tier, int(depth)):
            raise TorchMetricsUserError(
                "one metric instance can back only one windowed-serving geometry "
                f"(compiled {self._jit_cache.get(f'{key}.tier')}, requested {(tier, depth)})."
            )
        return self._jit_cache[key]

    def _get_vcompute_fn(self) -> Callable:
        """The vmapped batch-compute program behind
        ``ServingEngine.compute_all``: ONE undonated XLA call computes every
        row of a stacked tenant pytree at once (the eager alternative slices
        and dispatches once per tenant).

        Calling convention: ``fn(stacked, n_scalar, *args, **kwargs)`` —
        the trailing batch args are SIGNATURE CARRIERS only (the engine
        passes its shape-class's zero pad example so each shape-class keys
        its own compile/cache entry; the body never reads them). Compiled
        WITHOUT donation: the stack keeps serving traffic after the read."""
        key = "vcompute"
        if key not in self._jit_cache:
            if self._list_state_names:
                raise TorchMetricsUserError(
                    f"{type(self).__name__} holds dynamic-length concat states and cannot be "
                    "served from a stacked pytree; use a binned/static-shape variant."
                )
            if not self._jittable_compute:
                raise TorchMetricsUserError(
                    f"{type(self).__name__}.compute runs on host and cannot vmap; "
                    "per-tenant compute falls back to eager slicing."
                )

            def fn(stacked, n_scalar, *args, **kwargs):
                del n_scalar, args, kwargs  # shape-class identity carriers only
                states = {k: v for k, v in stacked.items() if k != TENANT_COUNT_KEY}
                with jax.named_scope(f"{type(self).__name__}.vcompute"):
                    return jax.vmap(self._compute)(states)

            self._jit_cache[f"{key}.raw"] = fn  # undonated source for _aot_program
            # no donation: compute is a read — the stack stays live for traffic
            self._jit_cache[key] = jax.jit(fn) if self._enable_jit else fn
        return self._jit_cache[key]

    def _append_list_state(self, name: str, value: Any) -> None:
        """Append one row to a concat state. compute_on_cpu (reference metric.py:119)
        offloads it to host — list states are where memory grows, and host storage
        frees HBM without touching the jitted tensor-state path."""
        if not self.compute_on_cpu:
            self._state[name].append(value)
            return
        rec = _observability._ACTIVE
        if rec is not None and isinstance(value, jax.Array):
            # the offload is a deliberate device→host readback — count it so an
            # operator can see it (and so the hot tensor loop proves it has none)
            rec.record_d2h("compute_on_cpu_append", value.size * value.dtype.itemsize, metric=self)
        self._state[name].append(np.asarray(value))

    def _device_update_count(self):
        if getattr(self, "_n_prev_dev", None) is None:
            # device_put, not jnp.asarray: a pure H2D transfer. An eager
            # asarray would COMPILE a tiny convert_element_type program, and
            # as the process's first eager op that compile (~40ms, plus jit
            # machinery warmup) lands on the warm-boot critical path — it
            # would dominate the whole AOT loaded-executable budget. Same
            # value, same canonicalized f32 dtype.
            self._n_prev_dev = jax.device_put(np.float32(self._update_count))
        return self._n_prev_dev

    def _has_custom_merge(self) -> bool:
        return type(self)._merge is not Metric._merge

    # --------------------------------------------------------- reliability seam

    def _attempt(self, tag: str, thunk: Callable[[], Any]) -> Any:
        """One dispatch attempt; the fault-injection hook fires where a remote
        compile/dispatch failure would surface (before the XLA call)."""
        hook = self._fault_hook
        if hook is not None:
            hook(tag)
        return thunk()

    def _reliable_call(self, tag: str, thunk: Callable[[], Any], restore: Optional[Callable] = None) -> Any:
        """Dispatch boundary: retries transient failures when a RetryPolicy is
        configured; otherwise today's single-attempt behavior, byte for byte.
        ``restore(exc, attempt)`` re-materializes donated inputs before a retry.

        Telemetry: HostMetric routes its eager ``update``/``forward`` through
        here (the jitted tensor path uses ``_donation_safe_dispatch`` instead),
        so those tags record as host dispatches when a session is active.
        """
        rel = self._reliability
        if rel is None or rel.retry is None:
            attempt = lambda: self._attempt(tag, thunk)
        else:
            attempt = lambda: rel.retry.call(
                lambda: self._attempt(tag, thunk), on_retry=restore,
                describe=f"{type(self).__name__}.{tag}",
            )
        rec = _observability._ACTIVE
        if rec is None or tag not in ("update", "forward"):
            return attempt()
        t0 = _tracing.monotonic()
        with _tracing.trace_span(f"{type(self).__name__}.{tag}"):
            out = attempt()
        rec.record_host_dispatch(self, tag, rec.finish(out, t0))
        return out

    def _donation_safe_dispatch(
        self,
        tag: str,
        call: Callable[..., Any],
        tensors: StateDict,
        inputs: Optional[tuple] = None,
        jitted: Optional[Callable] = None,
        owner: Optional[StateDict] = None,
    ) -> Any:
        """Dispatch a jitted call that DONATES its tensor-state argument (and, for
        ``update``, the device counter). ``call(t, n)`` receives the live tensor
        dict and device-side update counter.

        ``inputs`` is the batch's ``(args, kwargs)`` — read only when a telemetry
        session or the AOT compile plane is active, for the shape/dtype dispatch
        signature (metadata only, no device access). ``jitted`` is the underlying
        ``jax.jit`` object for this tag — the cost-accounting layer AOT-lowers it
        from avals when the dispatch turns out to be a fresh compile
        (``observability/costs.py``). Disabled telemetry and a disabled AOT plane
        each cost one ``None``-check here.

        With the AOT plane active (``torchmetrics_tpu.aot.enable``), a
        first-seen signature consults the on-disk executable cache BEFORE
        dispatching: a hit swaps ``call`` for the deserialized executable (no
        trace, no compile — the warm-start path), a miss is remembered so the
        jit path owns that signature for the rest of the process, and a
        corrupt entry is just a miss. Counters keep
        ``jit_compiles + jit_cache_hits + aot_cache_hits == dispatches`` exact.

        ``owner`` names the dict that OWNS ``tensors`` when it is not this
        metric's ``_state`` (a streaming wrapper's ring/decay pytree): an
        exhausted retry budget restores the pre-attempt backup into the
        owner, so rollback lands in the right state and never pollutes the
        base metric's dict with reserved ring keys.
        """
        plane = _aot._ACTIVE
        aot_slot = None
        if (
            plane is not None
            and inputs is not None
            and self._enable_jit
            and jitted is not None
            and hasattr(jitted, "lower")
        ):
            aot_slot = plane.lookup_dispatch(self, tag, tensors, inputs)
            if aot_slot is not None and aot_slot.compiled is not None:
                a_args, a_kwargs = inputs
                loaded = aot_slot.compiled
                jit_call = call
                used = aot_slot  # closure sees demotion through the slot

                def call(t, n):  # noqa: ANN001 — mirrors the jit-call shape
                    if used.compiled is None:  # demoted on an earlier attempt
                        return jit_call(t, n)
                    try:
                        return loaded(t, n, *a_args, **a_kwargs)
                    except (TypeError, ValueError):
                        # a calling-convention or input-placement/sharding
                        # mismatch the key could not see — detected BEFORE
                        # execution, and cached programs never donate, so the
                        # inputs are intact: demote this slot to a remembered
                        # miss and take the jit path (never an exception on
                        # the dispatch path)
                        used.compiled = None
                        used.source = "demoted"
                        used.event_pending = False
                        used.miss_pending = True
                        return jit_call(t, n)

        rec = _observability._ACTIVE
        if rec is None:
            with _tracing.trace_span(f"{type(self).__name__}.{tag}"):
                result = self._dispatch_donated(tag, call, tensors, owner=owner)
            if aot_slot is not None and aot_slot.store_pending:
                plane.store_from_dispatch(
                    self, tag, tensors, self._device_update_count(), inputs,
                    self._aot_program(tag)[0], aot_slot
                )
            return result
        t0 = _tracing.monotonic()
        with _tracing.trace_span(f"{type(self).__name__}.{tag}"):
            result = self._dispatch_donated(tag, call, tensors, owner=owner)
        # aot_hit is decided AFTER the dispatch: a mid-call demotion means the
        # jit path actually served it
        aot_hit = aot_slot is not None and aot_slot.compiled is not None
        lower = None
        if rec.config.cost_accounting:
            if aot_hit and isinstance(aot_slot.compiled, jax.stages.Compiled):
                # the natively loaded executable IS the compiled program — its
                # cost harvests without the usual re-lower+compile. (A
                # portable-codec load is a jit wrapper, not a Compiled; it
                # falls through to the aval re-lowering path below.)
                lower = lambda c=aot_slot.compiled: c  # noqa: E731
            else:
                # lazy thunk: reference capture only — avals are built (from the
                # donated-then-deleted buffers' surviving metadata) solely when
                # the recorder sees a fresh compile
                lower = _obs_costs.make_lowerer(jitted, tensors, self._device_update_count(), inputs)
        if aot_hit and aot_slot.event_pending:
            aot_slot.event_pending = False  # one aot_load event per cache load
            rec.record_aot_load(self, tag, aot_slot.load_s, aot_slot.nbytes, aot_slot.key, aot_slot.codec)
        if aot_slot is not None and aot_slot.compiled is None and aot_slot.miss_pending:
            aot_slot.miss_pending = False
            rec.record_aot_miss()
        rec.record_dispatch(
            self, tag, inputs, rec.finish(result, t0), lower=lower, aot_loaded=aot_hit,
            # reuse the plane's signature — one pytree flatten per dispatch
            signature=aot_slot.signature if aot_slot is not None else None,
        )
        if aot_slot is not None and aot_slot.store_pending:
            plane.store_from_dispatch(
                self, tag, tensors, self._device_update_count(), inputs,
                self._aot_program(tag)[0], aot_slot
            )
        return result

    def _dispatch_donated(
        self, tag: str, call: Callable[..., Any], tensors: StateDict,
        owner: Optional[StateDict] = None,
    ) -> Any:
        """The donation-safe dispatch body.

        Default path (no retry): single attempt, no copies — byte-for-byte today's
        behavior. With a RetryPolicy: an undonated device-side backup lets every
        retry see intact inputs, and when the budget is exhausted the backup
        replaces the donated (deleted) live buffers in ``self._state`` (or the
        explicit ``owner`` dict a streaming wrapper passes) before the exception
        re-raises, so the metric stays usable at its last good state.
        """
        rel = self._reliability
        if rel is None or rel.retry is None:
            return self._attempt(tag, lambda: call(tensors, self._device_update_count()))
        backup = {k: jnp.copy(v) for k, v in tensors.items()}
        n_backup = jnp.copy(self._device_update_count())
        live = {"t": tensors, "n": self._device_update_count()}

        def restore(exc: BaseException, attempt: int) -> None:
            live["t"] = {k: jnp.copy(v) for k, v in backup.items()}
            live["n"] = jnp.copy(n_backup)

        try:
            return rel.retry.call(
                lambda: self._attempt(tag, lambda: call(live["t"], live["n"])),
                on_retry=restore,
                describe=f"{type(self).__name__}.{tag}",
            )
        except Exception:
            target = self._state if owner is None else owner
            for k, v in backup.items():
                target[k] = v
            self._n_prev_dev = None
            raise

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Accumulate this batch into global state (one donated XLA call)."""
        if self._is_synced:
            raise TorchMetricsUserError(
                "The Metric shouldn't be synced when performing ``update``. "
                "HINT: Did you forget to call ``unsync`` ?"
            )
        args, kwargs = self._prepare_inputs(*args, **kwargs)
        tensors, _ = self._split_tensor_list(self._state)
        fn = self._get_update_fn()
        new_t, appends, self._n_prev_dev = self._donation_safe_dispatch(
            "update", lambda t, n: fn(t, n, *args, **kwargs), tensors, inputs=(args, kwargs),
            jitted=fn,
        )
        for k, v in new_t.items():
            self._state[k] = v
        for k, v in appends.items():
            self._append_list_state(k, v)
        self._update_count += 1
        self._computed = None
        rec = _observability._ACTIVE
        if rec is not None:
            rec.record_state_memory(self)

    def _batch_state_full(self, *args: Any, **kwargs: Any) -> StateDict:
        """Batch state with concat states as single arrays (compute-ready)."""
        return self._batch_state(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Batch value AND global accumulation in one pass (reference metric.py:287).

        Purity kills the double-update trick: the batch state is computed once, its
        value returned, and the same arrays merged into the global state.
        """
        if self._is_synced:
            raise TorchMetricsUserError("The Metric shouldn't be synced when performing ``forward``.")
        if self.dist_sync_on_step:
            # per-step synced value: update then compute-with-sync (reference semantics)
            self.update(*args, **kwargs)
            self._computed = None
            val = self.compute()
            self._computed = None
            return val
        args, kwargs = self._prepare_inputs(*args, **kwargs)
        fwd = self._get_forward_fn()
        tensors = self._split_tensor_list(self._state)[0]
        new_t, appends, val, batch_full = self._donation_safe_dispatch(
            "forward", lambda t, n: fwd(t, n, *args, **kwargs), tensors, inputs=(args, kwargs),
            jitted=fwd,
        )
        self._n_prev_dev = None  # forward does not return the incremented counter
        for k, v in new_t.items():
            self._state[k] = v
        for k, v in appends.items():
            self._append_list_state(k, v)
        self._update_count += 1
        self._computed = None
        rec = _observability._ACTIVE
        if rec is not None:
            rec.record_state_memory(self)
        self._last_batch_state = batch_full  # consumed by MetricCollection compute groups
        if val is None and not self._jittable_compute:
            val = self._compute(batch_full)
        return val

    __call__ = forward

    def _concat_state(self, state: Optional[StateDict] = None) -> StateDict:
        """State with host lists concatenated to single arrays (empty lists dropped to
        zero-length arrays where possible). All-numpy lists (compute_on_cpu offload,
        host metrics) concatenate on host — re-uploading to device here would defeat
        the offload's whole purpose (states too big for HBM) and add transfers."""
        state = self._state if state is None else state
        out: StateDict = {}
        for k, v in state.items():
            if isinstance(v, list):
                if len(v) == 0:
                    # numpy, not jnp: host metrics read this back immediately and a
                    # D2H readback flips tunneled TPU runtimes into sync dispatch;
                    # jitted consumers accept numpy inputs transparently
                    out[k] = np.zeros((0,), np.float32)
                elif all(isinstance(e, np.ndarray) for e in v):
                    out[k] = np.concatenate([np.atleast_1d(e) for e in v], axis=0)
                else:
                    out[k] = dim_zero_cat(v)
            else:
                out[k] = v
        return out

    def compute(self) -> Any:
        """Synced final value (reference metric.py:676-708)."""
        if self._update_count == 0 and not self._update_called_warned:
            rank_zero_warn(
                f"The ``compute`` method of metric {type(self).__name__} was called before the ``update`` method "
                "which may lead to errors, as metric states have not yet been updated.",
                UserWarning,
            )
            self._update_called_warned = True
        if self.compute_with_cache and self._computed is not None:
            return self._computed

        did_sync = False
        # an already-synced metric (sync_context, or a collection-level
        # coalesced pre-sync) computes on the synced state as-is; whoever
        # synced it owns the unsync
        if self.sync_on_compute and not self._is_synced and self.distributed_available_fn():
            self.sync()
            did_sync = True
        try:
            state = self._concat_state()
            rec = _observability._ACTIVE
            with _tracing.trace_span(f"{type(self).__name__}.compute"):
                if rec is None:
                    value = self._reliable_call("compute", lambda: self._compute(state))
                else:
                    t0 = _tracing.monotonic()
                    value = self._reliable_call("compute", lambda: self._compute(state))
                    rec.record_compute(self, rec.finish(value, t0))
        finally:
            if did_sync:
                self.unsync()
        if self.compute_with_cache:
            self._computed = value
        return value

    def reset(self) -> None:
        """Restore default states (reference metric.py:758)."""
        self._update_count = 0
        self._n_prev_dev = None
        self._computed = None
        for name, default in self._defaults.items():
            self._state[name] = [] if isinstance(default, list) else _fresh_leaf(default)
        self._is_synced = False
        self._cache = None

    # ------------------------------------------------------------------ sync

    def sync(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        distributed_available: Optional[Callable] = None,
        sync_config: Optional[Any] = None,
    ) -> None:
        """Replace local state with cross-process-reduced state (reference metric.py:573).

        ``sync_config`` (:class:`~torchmetrics_tpu.parallel.SyncConfig`) opts
        this sync into the quantized (bf16/int8) collective buckets; use ONE
        config instance per metric across repeated syncs so its error-feedback
        residuals fold correctly (docs/distributed.md)."""
        if self._is_synced and should_sync:
            raise TorchMetricsUserError("The Metric has already been synced.")
        is_dist = (distributed_available or self.distributed_available_fn)()
        if not should_sync or not is_dist:
            return
        self._cache = {k: (list(v) if isinstance(v, list) else v) for k, v in self._state.items()}
        rec = _observability._ACTIVE
        t0 = _tracing.monotonic() if rec is not None else 0.0
        bytes0 = rec.counters.value("sync_payload_bytes") if rec is not None else 0
        coll0 = rec.counters.value("sync_collectives") if rec is not None else 0
        coal0 = rec.counters.value("gathers_coalesced") if rec is not None else 0
        with _tracing.trace_span(f"{type(self).__name__}.sync"):
            synced = self._reliable_call(
                "sync",
                lambda: _sync.process_sync(
                    self._state,
                    self._reductions,
                    process_group=process_group or self.process_group,
                    dist_sync_fn=dist_sync_fn or self.dist_sync_fn,
                    sync_config=sync_config,
                ),
            )
        if rec is not None:
            # payload bytes / collective counts were accumulated inside
            # process_sync; the deltas are this sync's contribution
            rec.record_sync(
                self, rec.finish(synced, t0),
                rec.counters.value("sync_payload_bytes") - bytes0,
                collectives=rec.counters.value("sync_collectives") - coll0,
                coalesced_leaves=rec.counters.value("gathers_coalesced") - coal0,
            )
        rel = self._reliability
        if rel is not None and rel.validate_on_sync:
            # a corrupt contribution from any participant must not silently become
            # this process's state — StateCorruptionError leaves local state intact
            validate_state(self, synced, context=f"{type(self).__name__}.sync", check_finite=rel.check_finite)
        self._state = synced
        self._is_synced = True

    def unsync(self, should_unsync: bool = True) -> None:
        if not should_unsync:
            return
        if not self._is_synced:
            raise TorchMetricsUserError("The Metric has already been un-synced.")
        assert self._cache is not None
        self._state = self._cache
        self._cache = None
        self._is_synced = False

    class _SyncContext:
        def __init__(self, metric: "Metric", **kwargs: Any) -> None:
            self.metric = metric
            self.kwargs = kwargs

        def __enter__(self) -> None:
            self.metric.sync(**self.kwargs)

        def __exit__(self, *exc: Any) -> None:
            if self.metric._is_synced:
                self.metric.unsync()

    def sync_context(self, **kwargs: Any) -> "Metric._SyncContext":
        return Metric._SyncContext(self, **kwargs)

    # ------------------------------------------------------------- merge/clone

    def merge_state(self, incoming_state: Union[StateDict, "Metric"]) -> None:
        """Fold another metric's state into this one — no communication
        (reference metric.py:404). Pure pytree fold."""
        if isinstance(incoming_state, Metric):
            if type(incoming_state) is not type(self):
                raise ValueError(f"Expected incoming state to be of type {type(self).__name__}")
            incoming = incoming_state._state
        elif isinstance(incoming_state, dict):
            # state_dict()-style dicts carry an "_update_count" metadata entry;
            # strip it from the state fold and use it as the dict's merge weight
            metas = [v for k, v in incoming_state.items() if k.endswith("_update_count")]
            incoming = {
                k: v
                for k, v in incoming_state.items()
                if not k.endswith(("_update_count", "_saved_states"))
            }
            unknown = set(incoming) - set(self._state)
            if unknown:
                raise RuntimeError(f"Got unknown state keys {sorted(unknown)}")
        else:
            raise ValueError("Expected incoming state to be a dict or an instance of Metric")
        if self._is_synced:
            raise TorchMetricsUserError("The Metric shouldn't be synced when performing ``merge_state``.")
        rel = self._reliability
        if rel is not None and rel.validate_on_merge:
            # validate BOTH sides before folding, separately — merging the dicts
            # would let incoming keys shadow the local accumulator's leaves and a
            # corrupt accumulator would hide behind a clean-looking merged value
            validate_state(
                self,
                self._state,
                context=f"{type(self).__name__}.merge_state (local)",
                check_finite=rel.check_finite,
            )
            validate_state(
                self,
                incoming,
                context=f"{type(self).__name__}.merge_state (incoming)",
                check_finite=rel.check_finite,
            )
        if isinstance(incoming_state, Metric):
            incoming_count = incoming_state._update_count
        else:
            incoming_count = int(metas[0]) if metas else 1
        if self._has_custom_merge():
            merged = self._merge(
                {k: v for k, v in self._state.items()},
                {k: incoming[k] for k in incoming},
            )
        else:
            # weight "mean" states by each side's update count so chained merges stay
            # exact for any number of participants (a bare dict carries weight 1; a
            # state_dict()-style dict carries its saved "_update_count")
            merged = _sync.merge_states(
                {k: v for k, v in self._state.items()},
                {k: incoming[k] for k in incoming},
                self._reductions,
                weights=(float(self._update_count), float(incoming_count)),
            )
        for k, v in merged.items():
            self._state[k] = v
        # fold the incoming weight into the count so CHAINED merges stay exact for
        # "mean" states; the reference leaves the count untouched for dicts, but it
        # also doesn't weight means by count at all
        self._update_count += incoming_count
        self._n_prev_dev = None
        self._computed = None

    def clone(self) -> "Metric":
        return deepcopy(self)

    def __deepcopy__(self, memo: dict) -> "Metric":
        cls = self.__class__
        new = cls.__new__(cls)
        memo[id(self)] = new
        # state arrays must be value-copied: update() donates buffers, so an aliased
        # clone would delete the original's state on its first update
        copy_state = lambda d: {
            n: ([jnp.copy(x) for x in s] if isinstance(s, list) else jnp.copy(s)) for n, s in d.items()
        }
        for k, v in self.__dict__.items():
            if k in ("_jit_cache", "_aot_memo"):
                object.__setattr__(new, k, {})
            elif k == "_state":
                object.__setattr__(new, k, copy_state(v))
            elif k in ("_defaults", "_reductions", "_persistent"):
                object.__setattr__(new, k, dict(v))
            elif k == "_cache":
                object.__setattr__(new, k, None if v is None else copy_state(v))
            else:
                try:
                    object.__setattr__(new, k, deepcopy(v, memo))
                except Exception:
                    object.__setattr__(new, k, v)
        return new

    # --------------------------------------------------------------- persist

    def persistent(self, mode: bool = False) -> None:
        for key in self._persistent:
            self._persistent[key] = mode

    def state_dict(self, destination: Optional[dict] = None, prefix: str = "") -> dict:
        """States flagged persistent, as numpy (checkpoint-friendly; orbax takes the
        raw state pytree via ``metric._state`` directly). Reference metric.py:924-956."""
        destination = {} if destination is None else destination
        rec = _observability._ACTIVE
        wrote_any = False
        for name in self._defaults:
            if not self._persistent[name]:
                continue
            current = self._state[name]
            if rec is not None:
                # checkpointing legitimately reads device state back — count the
                # transfers (size from metadata, before the conversion happens)
                for leaf in current if isinstance(current, list) else (current,):
                    if isinstance(leaf, jax.Array):
                        rec.record_d2h("state_dict", leaf.size * leaf.dtype.itemsize, metric=self)
            if isinstance(current, list):
                destination[prefix + name] = [np.asarray(x) for x in current]
            else:
                destination[prefix + name] = np.asarray(current)
            wrote_any = True
        if wrote_any:
            # metadata, not states: `_update_count` lets load_state_dict restore the
            # updated/fresh distinction exactly (value equality with defaults is an
            # unreliable proxy — e.g. SumMetric().update(0.0) leaves the state at its
            # default); `_saved_states` records how many state leaves this save wrote,
            # so restore can tell a truncated file from a legitimate partial save
            # (mixed persistent/non-persistent states)
            destination[prefix + "_update_count"] = int(self._update_count)
            destination[prefix + "_saved_states"] = int(
                sum(1 for name in self._defaults if self._persistent[name])
            )
        return destination

    def load_state_dict(self, state_dict: dict, prefix: str = "", validate: bool = True) -> None:
        if validate:
            # structural guard (always on): a truncated checkpoint — lost keys or
            # partially-written arrays — raises StateCorruptionError instead of
            # silently loading garbage. Finiteness scans are opt-in via
            # ReliabilityConfig (a legitimately saved cat state may carry NaN).
            rel = self._reliability
            validate_restored(
                self,
                state_dict,
                prefix,
                check_finite=rel is not None and rel.validate_on_restore and rel.check_finite,
            )
        loaded = False
        for name in self._defaults:
            key = prefix + name
            if key in state_dict:
                v = state_dict[key]
                self._state[name] = [jnp.asarray(x) for x in v] if isinstance(v, list) else jnp.asarray(v)
                loaded = True
        if loaded:
            # restored checkpoints of an UPDATED metric count as updated (resume
            # path); a checkpoint saved before any update must not — compute()
            # keeps warning that no updates occurred instead of silently
            # returning the zero-state value. The saved `_update_count` metadata
            # decides exactly; older checkpoints without it fall back to a
            # value-vs-default comparison (imperfect: states can legitimately
            # equal the defaults after an update).
            meta_key = prefix + "_update_count"
            if meta_key in state_dict:
                # the checkpoint's count describes the loaded state exactly — adopt
                # it (not max: loading into a non-fresh metric REPLACES its states)
                self._update_count = int(state_dict[meta_key])
            else:
                def _differs(cur, default):
                    if isinstance(cur, list):
                        return len(cur) > 0
                    return not np.array_equal(np.asarray(cur), np.asarray(default))

                self._update_count = int(any(
                    _differs(self._state[name], self._defaults[name])
                    for name in self._state
                    if name in self._defaults
                ))
            # the on-device cached counter tracks the replaced state's history;
            # it must restart from the adopted count (update() re-seeds it)
            self._n_prev_dev = None
            self._computed = None

    def __getstate__(self) -> dict:
        d = dict(self.__dict__)
        d.pop("_jit_cache", None)
        d.pop("_aot_memo", None)  # loaded executables are process-local
        d["_state"] = {
            k: ([np.asarray(x) for x in v] if isinstance(v, list) else np.asarray(v)) for k, v in self._state.items()
        }
        d["_defaults"] = {k: (v if isinstance(v, list) else np.asarray(v)) for k, v in self._defaults.items()}
        d["_cache"] = None
        d["_computed"] = None
        d["dist_sync_fn"] = None  # callables don't survive pickling
        d["_fault_hook"] = None  # injection hooks are process-local by nature
        d.pop("_telemetry_id", None)  # telemetry identity is session-local
        return d

    def __setstate__(self, state: dict) -> None:
        self.__dict__.update(state)
        self._jit_cache = {}
        self._state = {
            k: ([jnp.asarray(x) for x in v] if isinstance(v, list) else jnp.asarray(v)) for k, v in self._state.items()
        }
        self.distributed_available_fn = _sync.distributed_available
        self.__dict__.setdefault("_reliability", None)
        self.__dict__.setdefault("_fault_hook", None)

    # ------------------------------------------------------------ device/dtype

    def to_device(self, device_or_sharding: Any) -> "Metric":
        """Move states (reference ``_apply`` device transfer, metric.py:867-917)."""
        put = lambda x: jax.device_put(x, device_or_sharding)
        for k, v in self._state.items():
            self._state[k] = [put(x) for x in v] if isinstance(v, list) else put(v)
        self._device = device_or_sharding
        self._n_prev_dev = None  # cached device counter stays on the old device otherwise
        return self

    def set_dtype(self, dst_type: Any) -> "Metric":
        """Cast float states (float()/half() style calls are deliberate no-ops in the
        reference; only ``set_dtype`` changes dtype, metric.py:823-865)."""
        def cast(x):
            x = jnp.asarray(x)
            return x.astype(dst_type) if jnp.issubdtype(x.dtype, jnp.floating) else x

        def cast_default(v):
            if isinstance(v, list) or isinstance(v, jax.Array):
                return cast(v) if isinstance(v, jax.Array) else v
            arr = np.asarray(v)
            return arr.astype(dst_type) if np.issubdtype(arr.dtype, np.floating) else arr

        for k, v in self._state.items():
            self._state[k] = [cast(x) for x in v] if isinstance(v, list) else cast(v)
        self._defaults = {k: cast_default(v) for k, v in self._defaults.items()}
        self._dtype = dst_type
        self._jit_cache.clear()
        self.__dict__.pop("_aot_memo", None)  # dtypes changed — loaded programs are stale
        return self

    @property
    def dtype(self):
        return self._dtype

    @property
    def device(self):
        return self._device

    @property
    def update_called(self) -> bool:
        return self._update_count > 0

    @property
    def update_count(self) -> int:
        return self._update_count

    @property
    def metric_state(self) -> StateDict:
        return {k: (list(v) if isinstance(v, list) else v) for k, v in self._state.items()}

    def state_memory(self) -> Dict[str, Any]:
        """Per-state device-memory footprint from array metadata — zero
        device→host traffic (safe under a disallow transfer guard and inside a
        hot loop). Tensor states report shape/dtype; list ("cat") states report
        element counts, the one axis that grows without bound between resets.

        Example:
            >>> import jax.numpy as jnp
            >>> from torchmetrics_tpu import CatMetric
            >>> metric = CatMetric()
            >>> metric.update(jnp.asarray([1.0, 2.0, 3.0]))
            >>> metric.state_memory()["total_bytes"]
            12
            >>> metric.state_memory()["states"]["value"]["elements"]
            1
        """
        return _obs_memory.state_memory(self._state)

    # ------------------------------------------------------- warm start (aot/)

    def _aot_program(self, tag: str) -> Tuple[Callable, Tuple[int, ...]]:
        """The jitted program behind one dispatch tag, as the AOT plane
        caches it: compiled WITHOUT buffer donation.

        The live dispatch path donates its tiny state buffers, but a
        deserialized executable's input-output aliasing is invisible to
        jax's Python-side donation bookkeeping — the old state array would
        keep owning the very buffer the output aliases, and its eventual
        garbage collection frees that memory underneath the live result
        (observed as nondeterministic state corruption). Metric states are
        sufficient statistics (bytes to KBs), so forgoing donation costs one
        tiny output allocation per warm dispatch; the large batch inputs
        were never donated. Returns ``(jitted, donate_spec)`` with an empty
        donate spec; the eager paths return their non-lowerable callable so
        ``precompile`` skips them."""
        if tag == "update":
            primary = self._get_update_fn()
        elif tag == "forward":
            primary = self._get_forward_fn()
        elif tag == "vupdate":
            primary = self._get_vupdate_fn()
        elif tag == "wupdate":
            primary = self._get_wupdate_fn()
        elif tag == "dupdate":
            primary = self._get_dupdate_fn()
        elif tag == "vcompute":
            primary = self._get_vcompute_fn()
        elif tag == "wdual":
            primary = self._get_wdual_fn()
        elif tag == "wstack" or tag == "vwupdate" or tag == "vwcompute":
            # geometry-parameterized windowed programs: built by their owning
            # plane (SlidingWindow / ServingEngine(window=)) before any dispatch
            primary = self._jit_cache.get(tag)
            if primary is None:
                raise TorchMetricsUserError(
                    f"the {tag!r} program is parameterized by its window geometry and is "
                    "built by its owner (SlidingWindow / ServingEngine(window=)) first"
                )
        elif tag == "mapeval" or tag == "escore":
            # re-homed evaluator programs: parameterized by metric config (mAP
            # capacity/classes geometry, embedder padding buckets) and built
            # lazily by the owning metric before its first dispatch
            primary = self._jit_cache.get(tag)
            if primary is None:
                raise TorchMetricsUserError(
                    f"the {tag!r} program is parameterized by its owner's configuration and is "
                    "built by the owning metric (DeviceMeanAveragePrecision / BERTScore) first"
                )
        else:
            raise ValueError(
                f"Unknown dispatch tag {tag!r}; expected 'update', 'forward', 'vupdate', "
                "'wupdate', 'wdual', 'wstack', 'vwupdate', 'vwcompute', 'dupdate', "
                "'vcompute', 'mapeval' or 'escore'"
            )
        raw = self._jit_cache.get(f"{tag}.raw")
        if raw is None or not hasattr(primary, "lower"):
            return primary, ()
        aot_key = f"{tag}.aot"
        if aot_key not in self._jit_cache:
            self._jit_cache[aot_key] = jax.jit(raw)
        return self._jit_cache[aot_key], ()

    def precompile(
        self,
        *example_inputs: Any,
        tags: Sequence[str] = ("update",),
        cache_dir: Optional[str] = None,
        force: bool = False,
        **example_kwargs: Any,
    ) -> Dict[str, Any]:
        """Compile this metric's dispatch program(s) for the given example
        input shapes AHEAD of traffic and publish the serialized executables
        into the AOT cache, so a freshly booted process serves its first
        update from a cache load instead of a multi-second compile.

        Example inputs may be concrete arrays, numpy arrays,
        ``jax.ShapeDtypeStruct`` placeholders, or Python scalars — only
        shape/dtype metadata is read; no example values influence the program
        or the cache key. Uses the active plane
        (:func:`torchmetrics_tpu.aot.enable`) or, for one-off population, an
        explicit ``cache_dir``. Returns ``{tag: report_row}``; a program whose
        entry already exists reports ``"cached"`` (``force=True`` rewrites).

        See ``docs/performance.md`` ("Cold start & warm start") and
        ``tools/warm_cache.py`` for the boot-time workflow.
        """
        if cache_dir is not None:
            # an explicit cache_dir always wins — a deploy hook populating a
            # bake-time cache must not silently write into whatever plane the
            # process happens to have active
            plane = _aot.AotPlane(_aot.AotConfig(cache_dir=cache_dir))
        else:
            plane = _aot._ACTIVE
            if plane is None:
                raise TorchMetricsUserError(
                    "precompile needs an active AOT plane — call "
                    "torchmetrics_tpu.aot.enable(cache_dir) first, or pass cache_dir=."
                )
        report: Dict[str, Any] = {}
        if not self._enable_jit:
            return {tag: {"status": "skipped", "reason": "jit disabled on this metric"} for tag in tags}
        # the same host-side formatting the real dispatch applies — the
        # precompiled signature must match what update()/forward() will key
        # on. ShapeDtypeStruct placeholders carry no values, so value-level
        # validation/formatting cannot run on them: placeholder calls skip
        # _prepare_inputs and must therefore be given POST-prepare shapes
        # (for most metrics prepare is identity or validation-only).
        has_placeholder = any(
            isinstance(leaf, jax.ShapeDtypeStruct)
            for leaf in jax.tree_util.tree_leaves((example_inputs, example_kwargs))
        )
        if has_placeholder:
            args, kwargs = example_inputs, example_kwargs
        else:
            args, kwargs = self._prepare_inputs(*example_inputs, **example_kwargs)
        tensors, _ = self._split_tensor_list(self._state)
        for tag in tags:
            fn, donate = self._aot_program(tag)
            if not hasattr(fn, "lower"):
                report[tag] = {"status": "skipped", "reason": "program not jitted (eager/host compute path)"}
                continue
            try:
                report[tag] = plane.precompile_program(
                    self, tag, fn, donate, tensors, args, kwargs, force=force
                )
            except _aot.keys.UnfingerprintableConfig as err:
                report[tag] = {"status": "skipped", "reason": f"uncacheable: {err}"}
        return report

    def prefetch_compiled(
        self,
        *example_inputs: Any,
        tags: Sequence[str] = ("update",),
        **example_kwargs: Any,
    ) -> Dict[str, Any]:
        """Load this metric's cached executables for the example signature
        into the in-process dispatch memo WITHOUT compiling on a miss.

        The read-only sibling of :meth:`precompile`: a hit deserializes the
        program and primes ``_aot_memo`` so the first real dispatch is served
        from memory (no disk probe on the traffic path); a miss is remembered
        exactly like a dispatch-time miss (the jit path owns that signature —
        and, under ``AotConfig(write_on_miss=True)``, the fresh compile will
        write through). Thread-safe against OTHER metrics prefetching
        concurrently — ``MetricCollection.precompile`` overlaps its members'
        deserializations on a thread pool. Returns ``{tag: row}``.
        """
        plane = _aot._ACTIVE
        if plane is None:
            raise TorchMetricsUserError(
                "prefetch_compiled needs an active AOT plane — call "
                "torchmetrics_tpu.aot.enable(cache_dir) first."
            )
        if not self._enable_jit:
            return {tag: {"status": "skipped", "reason": "jit disabled on this metric"} for tag in tags}
        has_placeholder = any(
            isinstance(leaf, jax.ShapeDtypeStruct)
            for leaf in jax.tree_util.tree_leaves((example_inputs, example_kwargs))
        )
        if has_placeholder:
            args, kwargs = example_inputs, example_kwargs
        else:
            args, kwargs = self._prepare_inputs(*example_inputs, **example_kwargs)
        tensors, _ = self._split_tensor_list(self._state)
        report: Dict[str, Any] = {}
        for tag in tags:
            fn, _donate = self._aot_program(tag)
            if not hasattr(fn, "lower"):
                report[tag] = {"status": "skipped", "reason": "program not jitted (eager/host compute path)"}
                continue
            slot = plane.lookup_dispatch(self, tag, tensors, (args, kwargs))
            if slot is not None and slot.compiled is not None:
                report[tag] = {
                    "status": "loaded", "codec": slot.codec,
                    "load_s": round(slot.load_s, 6), "bytes": slot.nbytes,
                }
            else:
                report[tag] = {"status": "miss"}
        return report

    # ------------------------------------------------------------ kwarg filter

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        """Keep only kwargs that this metric's ``_batch_state`` accepts
        (reference metric.py:992-1011; enables heterogeneous collections)."""
        sig = inspect.signature(self._batch_state)
        params = sig.parameters
        has_varkw = any(p.kind == p.VAR_KEYWORD for p in params.values())
        if has_varkw:
            return kwargs
        names = {
            n for n, p in params.items() if p.kind in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY)
        }
        return {k: v for k, v in kwargs.items() if k in names}

    # ---------------------------------------------------------------- dunder

    def __hash__(self) -> int:
        hash_vals = [type(self).__name__]
        for key in self._defaults:
            val = self._state[key]
            if isinstance(val, list):
                hash_vals.extend(id(v) for v in val)
            else:
                hash_vals.append(id(val))
        return hash(tuple(hash_vals))

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"

    def __abs__(self): return CompositionalMetric(jnp.abs, self, None)
    def __add__(self, other): return CompositionalMetric(jnp.add, self, other)
    def __and__(self, other): return CompositionalMetric(jnp.bitwise_and, self, other)
    def __eq__(self, other): return CompositionalMetric(jnp.equal, self, other)  # type: ignore[override]
    def __floordiv__(self, other): return CompositionalMetric(jnp.floor_divide, self, other)
    def __ge__(self, other): return CompositionalMetric(jnp.greater_equal, self, other)
    def __gt__(self, other): return CompositionalMetric(jnp.greater, self, other)
    def __le__(self, other): return CompositionalMetric(jnp.less_equal, self, other)
    def __lt__(self, other): return CompositionalMetric(jnp.less, self, other)
    def __matmul__(self, other): return CompositionalMetric(jnp.matmul, self, other)
    def __mod__(self, other): return CompositionalMetric(jnp.mod, self, other)
    def __mul__(self, other): return CompositionalMetric(jnp.multiply, self, other)
    def __ne__(self, other): return CompositionalMetric(jnp.not_equal, self, other)  # type: ignore[override]
    def __neg__(self): return CompositionalMetric(lambda x: -x, self, None)
    def __or__(self, other): return CompositionalMetric(jnp.bitwise_or, self, other)
    def __pos__(self): return CompositionalMetric(jnp.abs, self, None)
    def __pow__(self, other): return CompositionalMetric(jnp.power, self, other)
    def __radd__(self, other): return CompositionalMetric(jnp.add, other, self)
    def __rand__(self, other): return CompositionalMetric(jnp.bitwise_and, other, self)
    def __rfloordiv__(self, other): return CompositionalMetric(jnp.floor_divide, other, self)
    def __rmatmul__(self, other): return CompositionalMetric(jnp.matmul, other, self)
    def __rmod__(self, other): return CompositionalMetric(jnp.mod, other, self)
    def __rmul__(self, other): return CompositionalMetric(jnp.multiply, other, self)
    def __ror__(self, other): return CompositionalMetric(jnp.bitwise_or, other, self)
    def __rpow__(self, other): return CompositionalMetric(jnp.power, other, self)
    def __rsub__(self, other): return CompositionalMetric(jnp.subtract, other, self)
    def __rtruediv__(self, other): return CompositionalMetric(jnp.true_divide, other, self)
    def __rxor__(self, other): return CompositionalMetric(jnp.bitwise_xor, other, self)
    def __sub__(self, other): return CompositionalMetric(jnp.subtract, self, other)
    def __truediv__(self, other): return CompositionalMetric(jnp.true_divide, self, other)
    def __xor__(self, other): return CompositionalMetric(jnp.bitwise_xor, self, other)
    def __invert__(self): return CompositionalMetric(jnp.bitwise_not, self, None)

    def __getitem__(self, idx) -> "CompositionalMetric":
        return CompositionalMetric(lambda x: x[idx], self, None)

    # ---------------------------------------------------------------- plotting

    def plot(self, *args: Any, **kwargs: Any):
        from .utilities.plot import plot_single_or_multi_val

        val = args[0] if args else self.compute()
        return plot_single_or_multi_val(
            val,
            higher_is_better=self.higher_is_better,
            lower_bound=self.plot_lower_bound,
            upper_bound=self.plot_upper_bound,
            legend_name=self.plot_legend_name,
            name=type(self).__name__,
            ax=kwargs.get("ax"),
        )


class HostMetric(Metric):
    """Base for metrics whose ``update`` must run host-side — ragged per-image shapes
    (detection), string inputs (text), or third-party host callbacks (audio).

    Subclasses implement ``_host_batch_state(*inputs) -> dict`` returning, per state,
    either one array to append (concat states — already concatenated over the batch's
    items) or a tensor contribution to fold. ``_compute`` receives the usual
    concatenated state. ``forward`` computes the batch value from the batch
    contribution alone (no double-update — reference metric.py:319's cache/restore
    dance is unnecessary because the contribution is already materialized).
    """

    _jittable_compute = False

    def precompile(self, *example_inputs: Any, tags: Sequence[str] = ("update",), **kwargs: Any) -> Dict[str, Any]:
        """Host metrics dispatch eagerly — there is no jitted program to
        cache. A no-op report keeps ``MetricCollection.precompile`` total
        over heterogeneous collections."""
        return {
            tag: {"status": "skipped", "reason": "host-side metric — no jitted dispatch program"}
            for tag in tags
        }

    def prefetch_compiled(self, *example_inputs: Any, tags: Sequence[str] = ("update",), **kwargs: Any) -> Dict[str, Any]:
        """No jitted program — nothing to deserialize (see :meth:`precompile`)."""
        return {
            tag: {"status": "skipped", "reason": "host-side metric — no jitted dispatch program"}
            for tag in tags
        }

    def _host_batch_state(self, *args: Any, **kwargs: Any) -> StateDict:
        raise NotImplementedError

    def _batch_state(self, *args: Any, **kwargs: Any) -> StateDict:  # pragma: no cover
        return self._host_batch_state(*args, **kwargs)

    def _fold_batch(self, bs: StateDict) -> None:
        appends = {k: v for k, v in bs.items() if k in self._list_state_names}
        tensors = {k: v for k, v in bs.items() if k not in appends}
        if self._has_custom_merge():
            current = {k: v for k, v in self._state.items() if k not in self._list_state_names}
            merged = self._merge(current, tensors)
        else:
            merged = {
                k: _sync.pairwise_merge(
                    self._reductions.get(k), self._state[k], v, weights=(float(self._update_count), 1.0)
                )
                for k, v in tensors.items()
            }
        for k, v in merged.items():
            prev = self._state.get(k)
            self._state[k] = jnp.asarray(v).astype(prev.dtype) if hasattr(prev, "dtype") else v
        for k, v in appends.items():
            self._append_list_state(k, v)
        self._update_count += 1
        self._computed = None
        rec = _observability._ACTIVE
        if rec is not None:
            rec.record_state_memory(self)

    def update(self, *args: Any, **kwargs: Any) -> None:
        if self._is_synced:
            raise TorchMetricsUserError(
                "The Metric shouldn't be synced when performing ``update``. "
                "HINT: Did you forget to call ``unsync`` ?"
            )
        args, kwargs = self._prepare_inputs(*args, **kwargs)
        # retry wraps only the batch-state computation (the expensive/dispatchy
        # part, e.g. third-party host callbacks); the fold below is pure local
        # assignment and must not be double-applied by a retry
        bs = self._reliable_call("update", lambda: self._host_batch_state(*args, **kwargs))
        self._fold_batch(bs)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        if self._is_synced:
            raise TorchMetricsUserError("The Metric shouldn't be synced when performing ``forward``.")
        if self.dist_sync_on_step:
            self.update(*args, **kwargs)
            self._computed = None
            val = self.compute()
            self._computed = None
            return val
        args, kwargs = self._prepare_inputs(*args, **kwargs)
        bs = self._reliable_call("forward", lambda: self._host_batch_state(*args, **kwargs))
        batch_full = dict(self.init_state())
        for k, v in bs.items():
            if k in self._list_state_names:
                batch_full[k] = [v]
            else:
                batch_full[k] = v
        batch_concat = self._concat_state(batch_full)
        self._fold_batch(bs)
        self._last_batch_state = batch_concat
        return self._compute(batch_concat)

    __call__ = forward


class CompositionalMetric(Metric):
    """Lazy operator tree over metrics/constants (reference metric.py:1188-1311)."""

    def __init__(self, operator: Callable, metric_a: Any, metric_b: Any) -> None:
        super().__init__()
        self.op = operator
        self.metric_a = metric_a if isinstance(metric_a, Metric) else (None if metric_a is None else jnp.asarray(metric_a))
        self.metric_b = metric_b if isinstance(metric_b, Metric) else (None if metric_b is None else jnp.asarray(metric_b))
        self._op_a_raw = metric_a
        self._op_b_raw = metric_b

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        return kwargs

    def precompile(
        self,
        *example_inputs: Any,
        tags: Sequence[str] = ("update",),
        cache_dir: Optional[str] = None,
        force: bool = False,
        **example_kwargs: Any,
    ) -> Dict[str, Any]:
        """Warm both operands — the composition itself has no program. Example
        kwargs route through each operand's kwarg filter, exactly like the
        composed ``update`` does, so the cached signatures match what real
        traffic dispatches."""
        report: Dict[str, Any] = {}
        for side, operand in (("metric_a", self.metric_a), ("metric_b", self.metric_b)):
            if isinstance(operand, Metric):
                report[side] = operand.precompile(
                    *example_inputs, tags=tags, cache_dir=cache_dir, force=force,
                    **operand._filter_kwargs(**example_kwargs),
                )
        return report

    def prefetch_compiled(
        self, *example_inputs: Any, tags: Sequence[str] = ("update",), **example_kwargs: Any
    ) -> Dict[str, Any]:
        """Prefetch both operands' cached programs (the composition has none)."""
        report: Dict[str, Any] = {}
        for side, operand in (("metric_a", self.metric_a), ("metric_b", self.metric_b)):
            if isinstance(operand, Metric):
                report[side] = operand.prefetch_compiled(
                    *example_inputs, tags=tags, **operand._filter_kwargs(**example_kwargs),
                )
        return report

    def update(self, *args: Any, **kwargs: Any) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.update(*args, **self.metric_a._filter_kwargs(**kwargs))
        if isinstance(self.metric_b, Metric):
            self.metric_b.update(*args, **self.metric_b._filter_kwargs(**kwargs))
        self._update_count += 1
        self._computed = None

    def compute(self) -> Any:
        val_a = self.metric_a.compute() if isinstance(self.metric_a, Metric) else self.metric_a
        val_b = self.metric_b.compute() if isinstance(self.metric_b, Metric) else self.metric_b
        if val_b is None:
            return self.op(val_a)
        return self.op(val_a, val_b)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        val_a = (
            self.metric_a.forward(*args, **self.metric_a._filter_kwargs(**kwargs))
            if isinstance(self.metric_a, Metric)
            else self.metric_a
        )
        val_b = (
            self.metric_b.forward(*args, **self.metric_b._filter_kwargs(**kwargs))
            if isinstance(self.metric_b, Metric)
            else self.metric_b
        )
        self._update_count += 1
        if val_a is None:
            return None
        if val_b is None:
            if self._op_b_raw is None:
                return self.op(val_a)
            return None
        return self.op(val_a, val_b)

    __call__ = forward

    def reset(self) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.reset()
        if isinstance(self.metric_b, Metric):
            self.metric_b.reset()
        self._update_count = 0
        self._n_prev_dev = None
        self._computed = None

    def persistent(self, mode: bool = False) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.persistent(mode=mode)
        if isinstance(self.metric_b, Metric):
            self.metric_b.persistent(mode=mode)

    def __repr__(self) -> str:
        _op_metrics = f"(\n  {self.op.__name__ if hasattr(self.op, '__name__') else 'op'}(\n    {self.metric_a!r},\n    {self.metric_b!r}\n  )\n)"
        return self.__class__.__name__ + _op_metrics

    def __hash__(self) -> int:
        return object.__hash__(self)
