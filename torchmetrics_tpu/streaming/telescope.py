"""Telescoping multi-resolution fold over a mergeable stream.

The dual-form sliding-window algebra (``window.py``) answers "the metric over
the last W updates" in O(1) memory *per window*; retaining MANY windows — the
last 10 seconds at 1s resolution, the last minute at 10s, the last hour at
1m, the last day at 1h — naively costs O(sum of window lengths) blocks. The
telescoping fold keeps it at O(levels): each level holds a bounded ring of
closed blocks at its own span, and every block that falls off a level has
already been folded into the (coarser) level above, so old history loses
resolution instead of existing twice or vanishing.

The only requirement on the folded value is a commutative, associative
``merge`` — the integer-vector addition contract the telemetry counter and
histogram rollups already ride (the default merge is exact elementwise sum
of equal-length sequences). That makes this module the retention structure
for the telemetry history plane (``observability/timeseries.py``) today and
for per-tenant metric states (ROADMAP "telescoping multi-resolution
windows") later.

Determinism: the fold is a pure function of the fed ``(t, value)`` sequence —
no wall clock, no randomness — so soak runs driving it from the injected
virtual clock produce byte-identical retained blocks run-to-run.

Stdlib-only (no jax import): loadable by file path from tools and the bench
driver without initializing a runtime.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple


def sum_merge(a: Sequence[Any], b: Sequence[Any]) -> Any:
    """Default merge: exact elementwise sum of two equal-length vectors (or
    plain ``a + b`` for scalars) — never mutates its inputs."""
    if isinstance(a, (int, float)):
        return a + b
    if len(a) != len(b):
        raise ValueError(f"cannot merge vectors of length {len(a)} and {len(b)}")
    return type(a)(x + y for x, y in zip(a, b))


class _Level:
    __slots__ = ("span", "keep", "blocks", "open_start", "open_value")

    def __init__(self, span: float, keep: int) -> None:
        self.span = float(span)
        self.keep = int(keep)
        self.blocks: List[Tuple[float, Any]] = []  # closed, time-ordered
        self.open_start: Optional[float] = None
        self.open_value: Any = None


class TelescopingFold:
    """Bounded multi-resolution retention of a mergeable value stream.

    ``spans`` are the per-level block widths in seconds, strictly increasing
    (default 1s → 10s → 1m → 1h). ``keep[i]`` bounds how many CLOSED blocks
    level ``i`` retains; the default keeps exactly enough fine blocks to tile
    one block of the next level (so the finest view always covers the span
    the next level summarizes) and 24 blocks at the top. When a level-``i``
    block closes it is folded into level ``i+1``'s open block AND appended to
    level ``i``'s ring — recent time stays fine-grained, old time stays
    queryable at coarser resolution, total memory stays
    ``O(sum(keep))`` = O(levels) for constant per-level ``keep``.
    """

    def __init__(
        self,
        spans: Sequence[float] = (1.0, 10.0, 60.0, 3600.0),
        keep: Optional[Sequence[int]] = None,
        merge: Callable[[Any, Any], Any] = sum_merge,
    ) -> None:
        spans = tuple(float(s) for s in spans)
        if not spans:
            raise ValueError("TelescopingFold needs at least one level span")
        if any(b <= a for a, b in zip(spans, spans[1:])):
            raise ValueError(f"level spans must be strictly increasing, got {spans}")
        if keep is None:
            keep = tuple(
                max(2, int(round(spans[i + 1] / spans[i]))) for i in range(len(spans) - 1)
            ) + (24,)
        keep = tuple(int(k) for k in keep)
        if len(keep) != len(spans):
            raise ValueError(f"keep has {len(keep)} entries for {len(spans)} levels")
        if any(k < 1 for k in keep):
            raise ValueError(f"every level must keep at least one block, got {keep}")
        self.spans: Tuple[float, ...] = spans
        self.keep: Tuple[int, ...] = keep
        self._merge = merge
        self._levels: List[_Level] = [_Level(s, k) for s, k in zip(spans, keep)]
        self.folds = 0  # closed-block folds, across all levels, since construction

    # ------------------------------------------------------------------ feed

    def feed(self, t: float, value: Any) -> int:
        """Fold one sample at time ``t`` into the hierarchy; returns how many
        blocks this feed CLOSED (0 on the common in-block path). ``t`` must be
        non-decreasing for the block boundaries to mean anything; a late
        sample is folded into the current open block rather than dropped."""
        before = self.folds
        self._feed(0, float(t), value)
        return self.folds - before

    def _feed(self, i: int, t: float, value: Any) -> None:
        lvl = self._levels[i]
        start = math.floor(t / lvl.span) * lvl.span
        if lvl.open_start is None or start == lvl.open_start:
            if lvl.open_start is None:
                lvl.open_start, lvl.open_value = start, value
            else:
                lvl.open_value = self._merge(lvl.open_value, value)
            return
        if start < lvl.open_start:  # out-of-order sample: keep it, coarsely
            lvl.open_value = self._merge(lvl.open_value, value)
            return
        # the open block closes: retain it here, fold it one level up
        closed_start, closed_value = lvl.open_start, lvl.open_value
        lvl.blocks.append((closed_start, closed_value))
        self.folds += 1
        if i + 1 < len(self._levels):
            self._feed(i + 1, closed_start, closed_value)
        if len(lvl.blocks) > lvl.keep:
            del lvl.blocks[: len(lvl.blocks) - lvl.keep]
        lvl.open_start, lvl.open_value = start, value

    # --------------------------------------------------------------- queries

    def _level_blocks(self, i: int) -> List[Tuple[float, float, Any]]:
        lvl = self._levels[i]
        out = [(s, s + lvl.span, v) for s, v in lvl.blocks]
        if lvl.open_start is not None:
            out.append((lvl.open_start, lvl.open_start + lvl.span, lvl.open_value))
        return out

    def blocks(self, level: int = 0) -> List[Tuple[float, float, Any]]:
        """Retained ``(start, end, value)`` blocks of one level, time-ordered;
        the still-open block rides last."""
        if not 0 <= level < len(self._levels):
            raise IndexError(f"level {level} out of range (have {len(self._levels)})")
        return self._level_blocks(level)

    def at(self, t: float) -> Optional[Tuple[int, float, float, Any]]:
        """The FINEST retained block covering time ``t`` as
        ``(level, start, end, value)``, or ``None`` when ``t`` predates every
        retained boundary (history telescoped past it) or postdates the open
        blocks."""
        for i in range(len(self._levels)):
            for start, end, value in reversed(self._level_blocks(i)):
                if start <= t < end:
                    return (i, start, end, value)
                if end <= t:
                    break  # blocks are time-ordered: nothing earlier covers t
        return None

    def range(self, t0: float, t1: float, level: int = 0) -> List[Tuple[float, float, Any]]:
        """Blocks of ``level`` overlapping ``[t0, t1)``, time-ordered."""
        return [(s, e, v) for s, e, v in self.blocks(level) if s < t1 and e > t0]

    def block_count(self) -> int:
        """Total retained blocks (closed + open) — the O(levels) memory pin:
        bounded by ``sum(keep) + len(spans)`` regardless of how much time has
        been fed through."""
        return sum(len(lvl.blocks) + (lvl.open_start is not None) for lvl in self._levels)

    def summary(self) -> Dict[str, Any]:
        return {
            "spans": list(self.spans),
            "keep": list(self.keep),
            "folds": self.folds,
            "blocks": self.block_count(),
        }
