"""Windowed and decayed metric transforms over infinite streams.

Every metric in this runtime accumulates forever: state is a sufficient
statistic of the WHOLE stream, which is the right shape for an eval epoch and
the wrong shape for monitoring traffic — "accuracy over the last 10k
predictions" and "error rate with a 1-hour halflife" are windowed questions a
forever-accumulator cannot answer without replaying history. The two
transforms here answer them with O(1) work per update and bounded state,
following the O(1)-state streaming-accumulator discipline of compiler-first
caching stacks (arXiv:2603.09555):

- :class:`SlidingWindow` — the metric over exactly the last ``window``
  updates. The state is a RING of ``window`` bucket states (one stacked
  device pytree, each bucket one update's isolated contribution); every
  update is ONE donated XLA call (``Metric._get_wupdate_fn``) that scatters
  the batch state into the next slot — no unbounded ``cat``, no per-update
  host round-trip, no O(window) work until ``compute()`` folds the buckets
  through the metric's own merge semantics.
- :class:`ExponentialDecay` — the metric over the whole stream with
  exponentially discounted history (``halflife`` in updates). No ring at
  all: the decay factor folds into the sum/count/mean leaves AT UPDATE TIME
  (``Metric._get_dupdate_fn``), so the state stays exactly one copy of the
  metric's own state plus one weight scalar.

Both dispatch through ``Metric._donation_safe_dispatch`` under their own tags
(``wupdate`` / ``dupdate``), so the reliability retry/rollback plane, the
telemetry counters/events/histograms, and the AOT warm-start cache apply to
windowed traffic unchanged. The wrappers are stream-local by construction:
``merge_state`` across ranks has no defined update order and raises (same
contract as :class:`~torchmetrics_tpu.wrappers.Running`); fleet-wide windowed
values come from syncing the window FOLD, or from the serving engine's
stacked plane.

See ``docs/streaming.md`` for the window semantics and the decay math.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _observability
from ..metric import (
    DECAY_WEIGHT_KEY,
    WINDOW_COUNT_KEY,
    WINDOW_CURSOR_KEY,
    WINDOW_TIERS,
    HostMetric,
    Metric,
    _dual_fold,
    _stack_fold,
    window_defaults,
    window_stack_geometry,
    window_tier,
)
from ..observability import memory as _obs_memory
from ..parallel import sync as _sync
from ..utilities.exceptions import TorchMetricsUserError
from ..utilities.prints import rank_zero_warn

StateDict = Dict[str, Any]

_RING_RESERVED = (WINDOW_CURSOR_KEY, WINDOW_COUNT_KEY)


def _check_base(base: Metric, transform: str) -> None:
    if not isinstance(base, Metric):
        raise TorchMetricsUserError(
            f"{transform} wraps a torchmetrics_tpu.Metric, got {type(base).__name__}"
        )
    if isinstance(base, HostMetric):
        raise TorchMetricsUserError(
            f"{transform} needs a jitted batch-state core; {type(base).__name__} computes its "
            "batch state on host (text/detection/audio paths)."
        )
    if type(base)._batch_state is Metric._batch_state:
        raise TorchMetricsUserError(
            f"{type(base).__name__} has no pure _batch_state core to window "
            "(compositions/wrappers: wrap the operands instead)."
        )
    if not base._enable_jit:
        raise TorchMetricsUserError(f"{transform} requires a jit-enabled metric (jit=True).")


def _mask_rows(mask: jax.Array, ndim: int) -> jax.Array:
    """Broadcast a ``(B,)`` slot mask against ``(B, *state_shape)`` buckets."""
    return mask.reshape(mask.shape + (1,) * (ndim - 1))


class SlidingWindow(Metric):
    """Metric value over the last ``window`` updates of a stream.

    The representation is TIERED, selected automatically from the metric's
    reduce-tags (``tier="auto"``; see :func:`torchmetrics_tpu.metric.
    window_tier` and the graftlint admissibility matrix):

    - ``"dual"`` (sum/mean/None tags) — a constant-size PAIR of block
      accumulators (running current block + expiring previous block): state
      cost independent of the window length, no ring, no roll-cursor scatter.
      The window boundary advances in hops of ``window`` updates, so the
      value is exactly the metric over the trailing :meth:`covered_updates`
      updates, with ``window <= covered < 2*window`` once warm.
    - ``"two_stack"`` (adds max/min/callable semigroup folds) — a DABA-style
      paned two-stack (front suffix-fold stack + back pane-fold stack +
      flip): window-independent memory (``2*depth + 2`` accumulators),
      O(1)-amortized updates, and a tighter hop of one pane
      (``window <= covered < window + 2*pane``). ``pane=1`` degenerates to
      EXACT per-update sliding at 2×window memory.
    - ``"ring"`` (custom ``_merge``, list/cat states — or forced) — the
      per-update bucket ring: exact trailing-``window`` at every step,
      O(window) state, one donated roll+scatter per update.

    All tiers satisfy the window-parity oracle: ``compute()`` equals a fresh
    metric fed exactly the trailing :meth:`covered_updates` batches
    (``covered == min(n, window)`` for the ring), fuzzed per tier in
    ``tests/test_streaming.py``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.streaming import SlidingWindow
        >>> from torchmetrics_tpu.aggregation import SumMetric
        >>> metric = SlidingWindow(SumMetric(), window=2)   # sum tags -> dual tier
        >>> for batch in [1.0, 2.0, 3.0, 4.0]:
        ...     metric.update(batch)
        >>> metric.covered_updates()                        # the exact trailing span
        2
        >>> float(metric.compute())
        7.0
        >>> exact = SlidingWindow(SumMetric(), window=2, tier="ring")
        >>> for batch in [1.0, 2.0, 3.0]:
        ...     exact.update(batch)
        >>> float(exact.compute())                          # per-update exact ring
        5.0
    """

    def __init__(self, base_metric: Metric, window: int, tier: str = "auto",
                 pane: Optional[int] = None) -> None:
        super().__init__()
        _check_base(base_metric, "SlidingWindow")
        if not (isinstance(window, int) and window > 0):
            raise ValueError(f"Expected argument `window` to be a positive integer but got {window}")
        for name, fx in base_metric._reductions.items():
            if fx == "cat" and name not in base_metric._list_state_names:
                raise TorchMetricsUserError(
                    f"{type(base_metric).__name__}.{name} is a 'cat'-reduced TENSOR state whose "
                    "shape grows per update — it cannot live in a fixed ring; keep cat data in "
                    "list states."
                )
        if tier not in ("auto",) + WINDOW_TIERS:
            raise ValueError(f"Expected `tier` to be 'auto' or one of {WINDOW_TIERS}, got {tier!r}")
        if tier == "auto":
            tier = window_tier(base_metric)
            if pane is not None and tier != "two_stack":
                # an explicit pane is a GRANULARITY request — it only means
                # anything in the paned representation, so it forces the
                # two-stack tier (dual-admissible metrics are always
                # two-stack-admissible; ring-only metrics fail loud below)
                base_metric._check_windowable("two_stack")
                tier = "two_stack"
        elif tier != "ring":
            base_metric._check_windowable(tier)  # forced tier: fail loud at construction
        if pane is not None and tier != "two_stack":
            raise ValueError(
                f"`pane` only applies to the two-stack tier, but tier={tier!r} was forced"
            )
        self.base_metric = base_metric
        self.window = int(window)
        self.tier = tier
        if tier == "two_stack":
            self.pane, self.depth = window_stack_geometry(self.window, pane)
        else:
            self.pane, self.depth = None, None
        self._ring: Optional[StateDict] = None  # ring tier only; lazy on first update
        self._append_ring: List[Optional[Dict[str, list]]] = []
        self._wstate: Optional[StateDict] = None  # dual/two-stack tiers; lazy
        self._wparam_arr = None  # device scalar: window (dual) / pane (two-stack)

    # ------------------------------------------------------------------ ring

    def _init_ring(self) -> None:
        base = self.base_metric
        defaults_t, _ = base._split_tensor_list(base.init_state())
        ring: StateDict = {
            k: jnp.repeat(jnp.asarray(v)[None], self.window, axis=0)
            for k, v in defaults_t.items()
        }
        ring[WINDOW_COUNT_KEY] = jnp.zeros((self.window,), jnp.float32)
        ring[WINDOW_CURSOR_KEY] = jnp.zeros((), jnp.int32)
        self._ring = ring
        self._append_ring = [None] * self.window

    def _slot_order(self) -> List[int]:
        """Live slots, oldest update first (host mirror of the device cursor)."""
        filled = min(self._update_count, self.window)
        return [(self._update_count - filled + i) % self.window for i in range(filled)]

    # ------------------------------------------------------------- lifecycle

    def _dispatch_tiered(self, args: tuple, kwargs: dict) -> None:
        """One dual/two-stack windowed update: a single donated fused XLA
        call under the ``wdual``/``wstack`` dispatch tag."""
        base = self.base_metric
        if self._wstate is None:
            self._wstate = window_defaults(base, self.window, self.tier, self.pane)
        if self._wparam_arr is None:
            # traced scalar input (like dupdate's decay): one executable —
            # and one AOT cache entry — serves every window/pane length
            wparam = self.window if self.tier == "dual" else self.pane
            self._wparam_arr = jax.device_put(np.float32(wparam))
        warr = self._wparam_arr
        if self.tier == "dual":
            fn = base._get_wdual_fn()
            self._wstate = base._donation_safe_dispatch(
                "wdual", lambda t, n: fn(t, n, warr, *args, **kwargs), self._wstate,
                inputs=((warr,) + args, kwargs), jitted=fn, owner=self._wstate,
            )
        else:
            fn = base._get_wstack_fn(self.depth)
            self._wstate = base._donation_safe_dispatch(
                "wstack", lambda t, n: fn(t, n, warr, *args, **kwargs), self._wstate,
                inputs=((warr,) + args, kwargs), jitted=fn, owner=self._wstate,
            )

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Fold this batch's contribution into the windowed state (one
        donated XLA call under the tier's dispatch tag — ``wdual``/
        ``wstack``/``wupdate``)."""
        if self._is_synced:
            raise TorchMetricsUserError(
                "The Metric shouldn't be synced when performing ``update``. "
                "HINT: Did you forget to call ``unsync`` ?"
            )
        base = self.base_metric
        args, kwargs = base._prepare_inputs(*args, **kwargs)
        if self.tier == "ring":
            if self._ring is None:
                self._init_ring()
            fn = base._get_wupdate_fn()
            slot = self._update_count % self.window
            new_ring, appends = base._donation_safe_dispatch(
                "wupdate", lambda t, n: fn(t, n, *args, **kwargs), self._ring,
                inputs=(args, kwargs), jitted=fn, owner=self._ring,
            )
            self._ring = new_ring
            if base._list_state_names:
                # bounded host-side ring of list ("cat") contributions: the slot's
                # previous occupant expires with the overwrite, exactly like the
                # device buckets — window memory never grows past `window` updates
                self._append_ring[slot] = {k: [v] for k, v in appends.items()}
        else:
            self._dispatch_tiered(args, kwargs)
        self._update_count += 1
        self._computed = None
        rec = _observability._ACTIVE
        if rec is not None:
            n = self._update_count
            hop = self.window if self.tier != "two_stack" else self.pane
            rec.record_window_roll(
                base, self.window, min(n, self.window),
                wrapped=n % self.window == 0,
                tier=self.tier,
                rotated=self.tier != "ring" and n % hop == 0,
            )

    def covered_updates(self) -> int:
        """How many trailing updates the current value folds — the span the
        window-parity oracle compares against. Exactly ``min(n, window)`` for
        the ring; the constant-memory tiers advance the window boundary in
        hops (``window`` for dual, one pane for two-stack), so once warm
        ``window <= covered < window + hop``."""
        n = self._update_count
        if self.tier == "dual":
            return (self.window if n >= self.window else 0) + n % self.window
        if self.tier == "two_stack":
            full_panes, cc = divmod(n, self.pane)
            return min(full_panes, self.depth) * self.pane + cc
        return min(n, self.window)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Fold the batch in AND return this batch's own value (the batch
        contribution computed alone — no double update). Like the ring
        tier's bucket read, the batch value is computed eagerly off the hot
        path; the windowed update itself stays one donated XLA call."""
        self.update(*args, **kwargs)
        if self.tier == "ring":
            return self._bucket_value((self._update_count - 1) % self.window)
        base = self.base_metric
        args, kwargs = base._prepare_inputs(*args, **kwargs)
        bs = base._batch_state(*args, **kwargs)
        batch_full = dict(base.init_state())
        batch_full.update({k: jnp.asarray(v) for k, v in bs.items()})
        return base._compute(base._concat_state(batch_full))

    __call__ = forward

    def _bucket_value(self, slot: int) -> Any:
        base = self.base_metric
        batch = dict(base.init_state())
        for k, v in self._ring.items():
            if k not in _RING_RESERVED:
                batch[k] = v[slot]
        if base._list_state_names:
            bucket = self._append_ring[slot] or {}
            for name in base._list_state_names:
                batch[name] = list(bucket.get(name, []))
        return base._compute(base._concat_state(batch))

    # --------------------------------------------------------------- folding

    def window_state(self) -> StateDict:
        """The trailing window folded into one compute-ready state dict —
        exactly the state a fresh metric fed the last :meth:`covered_updates`
        batches would hold (list states stay host lists; ``_concat_state``
        applies downstream)."""
        base = self.base_metric
        defaults = base.init_state()
        if self.tier != "ring":
            if self._wstate is None:
                return defaults
            defaults_t, _ = base._split_tensor_list(defaults)
            if self.tier == "dual":
                return _dual_fold(dict(base._reductions), defaults_t, self._wstate)
            return _stack_fold(
                dict(base._reductions), defaults_t, self.depth, self._wstate,
                jnp.float32(self.pane),
            )
        if self._ring is None:
            return defaults
        order = self._slot_order()
        states = {k: v for k, v in self._ring.items() if k not in _RING_RESERVED}
        out: StateDict = {}
        if base._has_custom_merge():
            # sequential fold through the metric's OWN merge, in stream order
            # — bitwise the per-update fold a plain metric would have run
            acc = {k: jnp.asarray(defaults[k]) for k in states}
            for slot in order:
                bucket = {k: v[slot] for k, v in states.items()}
                merged = base._merge(dict(acc), bucket)
                acc = {
                    k: jnp.asarray(v).astype(states[k].dtype) if k in states else v
                    for k, v in merged.items()
                }
            out.update(acc)
        else:
            mask = self._ring[WINDOW_COUNT_KEY] > 0
            for k, v in states.items():
                fx = base._reductions.get(k)
                d = jnp.asarray(defaults[k])
                if fx is None:
                    out[k] = d  # fx=None keeps the local (default) value, as update does
                elif callable(fx):
                    acc = d
                    for slot in order:
                        acc = _sync.pairwise_merge(fx, acc, v[slot])
                    out[k] = acc
                elif fx == "sum":
                    m = _mask_rows(mask, v.ndim)
                    out[k] = (d + jnp.where(m, v, jnp.zeros_like(v)).sum(axis=0)).astype(v.dtype)
                elif fx == "mean":
                    m = _mask_rows(mask, v.ndim)
                    n = mask.sum()
                    mean = (v * m.astype(v.dtype)).sum(axis=0) / jnp.maximum(n, 1.0).astype(v.dtype)
                    out[k] = jnp.where(n > 0, mean, d).astype(v.dtype)
                elif fx == "max":
                    out[k] = jnp.maximum(d, jnp.where(_mask_rows(mask, v.ndim), v, d).max(axis=0))
                elif fx == "min":
                    out[k] = jnp.minimum(d, jnp.where(_mask_rows(mask, v.ndim), v, d).min(axis=0))
                else:  # pragma: no cover — construction rejects tensor "cat"
                    raise TorchMetricsUserError(f"Unsupported reduction {fx!r} in a window fold")
        for name in base._list_state_names:
            rows: list = []
            for slot in order:
                bucket = self._append_ring[slot] or {}
                rows.extend(bucket.get(name, []))
            out[name] = rows
        return out

    def compute(self) -> Any:
        if self._update_count == 0 and not self._update_called_warned:
            rank_zero_warn(
                f"The ``compute`` method of metric {type(self).__name__} was called before the "
                "``update`` method which may lead to errors, as metric states have not yet been updated.",
                UserWarning,
            )
            self._update_called_warned = True
        if self.compute_with_cache and self._computed is not None:
            return self._computed
        base = self.base_metric
        value = base._compute(base._concat_state(self.window_state()))
        if self.compute_with_cache:
            self._computed = value
        return value

    def reset(self) -> None:
        self._ring = None
        self._append_ring = []
        self._wstate = None
        self._update_count = 0
        self._computed = None
        self._is_synced = False
        self._cache = None

    # ------------------------------------------------------------- contracts

    def merge_state(self, incoming_state: Any) -> None:
        """A sliding window is a property of ONE update stream (same contract
        as ``wrappers.Running``): merging two ranks' windows has no defined
        update order, so this raises instead of silently interleaving."""
        raise TorchMetricsUserError(
            "SlidingWindow holds a stream-local window of the last updates; merging windows "
            "across ranks has no defined update order. Sync the window FOLD instead: "
            "compute per-rank, or feed window_state() into the sync planes."
        )

    def sync(self, dist_sync_fn: Any = None, process_group: Any = None,
             should_sync: bool = True, distributed_available: Any = None) -> None:
        """The wrapper's registered ``_state`` is EMPTY (the ring is the real
        state), so the inherited sync would 'succeed' while shipping nothing
        and then brick ``update()`` behind ``_is_synced`` — raise instead,
        mirroring :meth:`merge_state` (no-op when nothing would sync, exactly
        like ``Metric.sync``'s unavailable path)."""
        is_dist = (distributed_available or self.distributed_available_fn)()
        if not should_sync or not is_dist:
            return
        raise TorchMetricsUserError(
            "SlidingWindow is stream-local and cannot cross-process sync; sync the window "
            "FOLD instead (feed window_state() into the sync planes, or compute per-rank)."
        )

    def state_memory(self) -> Dict[str, Any]:
        """Windowed-state footprint (metadata only, zero D2H) — for the dual
        and two-stack tiers the invariant an operator checks is
        window-INDEPENDENCE (a 100k window costs the same bytes as a 1k one);
        for the ring it is bounded-by-window growth."""
        if self.tier != "ring":
            state = self._wstate
            if state is None:
                # report the layout's cost even before traffic — as avals
                # (eval_shape), so the metadata-only claim holds: no device
                # buffers are materialized just to be counted
                state = jax.eval_shape(
                    lambda: window_defaults(self.base_metric, self.window, self.tier, self.pane)
                )
            return _obs_memory.state_memory(dict(state))
        return _obs_memory.state_memory(dict(self._ring or {}))

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        return self.base_metric._filter_kwargs(**kwargs)

    def __repr__(self) -> str:
        return f"SlidingWindow({self.base_metric!r}, window={self.window}, tier={self.tier!r})"


class ExponentialDecay(Metric):
    """Metric over the whole stream with exponentially discounted history.

    ``halflife`` is measured in UPDATES: a batch ``h`` updates old carries
    half the weight of the current one (``decay = 2**(-1/halflife)``; pass
    ``decay`` directly to pin the factor). State stays O(1): the factor folds
    into the accumulating leaves at update time —

    - ``sum`` leaves:   ``s_n = d * s_{n-1} + x_n``  (so ``s_n = Σ d^k x_{n-k}``),
    - ``mean`` leaves:  weighted mean against the decayed update count
      ``w_n = d * w_{n-1} + 1`` (so ratios like accuracy become the
      exponentially weighted average of their batch values),
    - ``max``/``min``/``None`` leaves keep their plain merge (an extremum
      has no meaningful discount).

    Integer sum/mean leaves are promoted to float32 at construction —
    discounted counts are fractional by nature.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.streaming import ExponentialDecay
        >>> from torchmetrics_tpu.aggregation import SumMetric
        >>> metric = ExponentialDecay(SumMetric(), decay=0.5)
        >>> for batch in [1.0, 1.0, 1.0]:
        ...     metric.update(batch)
        >>> float(metric.compute())
        1.75
    """

    def __init__(
        self,
        base_metric: Metric,
        halflife: Optional[float] = None,
        decay: Optional[float] = None,
    ) -> None:
        super().__init__()
        _check_base(base_metric, "ExponentialDecay")
        if (halflife is None) == (decay is None):
            raise ValueError("Pass exactly one of `halflife` (in updates) or `decay` (per-update factor).")
        if halflife is not None:
            if not halflife > 0:
                raise ValueError(f"Expected `halflife` > 0, got {halflife}")
            decay = float(2.0 ** (-1.0 / float(halflife)))
        if not 0.0 < decay < 1.0:
            raise ValueError(f"Expected `decay` in (0, 1), got {decay}")
        if base_metric._list_state_names:
            raise TorchMetricsUserError(
                f"{type(base_metric).__name__} holds dynamic-length concat states; exponential "
                "decay over an unbounded concatenation is undefined."
            )
        if base_metric._has_custom_merge():
            raise TorchMetricsUserError(
                f"{type(base_metric).__name__} overrides _merge; a decay factor cannot be "
                "folded into an unknown merge safely."
            )
        for name, fx in base_metric._reductions.items():
            if callable(fx) or fx == "cat":
                raise TorchMetricsUserError(
                    f"{type(base_metric).__name__}.{name} uses reduction {fx!r}, which has no "
                    "defined exponential discount; only sum/mean/max/min/None states decay."
                )
        self.base_metric = base_metric
        self.halflife = float(halflife) if halflife is not None else None
        self.decay = float(decay)
        self._dstate: Optional[StateDict] = None
        self._decay_arr = None  # lazy device scalar (traced input, never donated)

    def _init_dstate(self) -> None:
        base = self.base_metric
        defaults_t, _ = base._split_tensor_list(base.init_state())
        st: StateDict = {}
        for k, v in defaults_t.items():
            v = jnp.asarray(v)
            if base._reductions.get(k) in ("sum", "mean") and not jnp.issubdtype(v.dtype, jnp.floating):
                v = v.astype(jnp.float32)  # discounted counts are fractional
            st[k] = v
        st[DECAY_WEIGHT_KEY] = jnp.zeros((), jnp.float32)
        self._dstate = st

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Fold this batch in with the decay applied (one donated XLA call
        under the ``dupdate`` dispatch tag)."""
        if self._is_synced:
            raise TorchMetricsUserError(
                "The Metric shouldn't be synced when performing ``update``. "
                "HINT: Did you forget to call ``unsync`` ?"
            )
        base = self.base_metric
        args, kwargs = base._prepare_inputs(*args, **kwargs)
        if self._dstate is None:
            self._init_dstate()
        if self._decay_arr is None:
            self._decay_arr = jnp.asarray(np.float32(self.decay))
        fn = base._get_dupdate_fn()
        decay = self._decay_arr
        self._dstate = base._donation_safe_dispatch(
            "dupdate", lambda t, n: fn(t, n, decay, *args, **kwargs), self._dstate,
            inputs=((decay,) + args, kwargs), jitted=fn, owner=self._dstate,
        )
        self._update_count += 1
        self._computed = None

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Fold the batch in and return the post-update decayed value (the
        streaming dashboard reading, not the batch-only value)."""
        self.update(*args, **kwargs)
        return self.compute()

    __call__ = forward

    def compute(self) -> Any:
        if self._update_count == 0 and not self._update_called_warned:
            rank_zero_warn(
                f"The ``compute`` method of metric {type(self).__name__} was called before the "
                "``update`` method which may lead to errors, as metric states have not yet been updated.",
                UserWarning,
            )
            self._update_called_warned = True
        if self.compute_with_cache and self._computed is not None:
            return self._computed
        base = self.base_metric
        if self._dstate is None:
            state = {k: v for k, v in base.init_state().items()}
        else:
            state = {k: v for k, v in self._dstate.items() if k != DECAY_WEIGHT_KEY}
        value = base._compute(state)
        if self.compute_with_cache:
            self._computed = value
        return value

    @property
    def decayed_count(self) -> Any:
        """The discounted update count ``Σ d^k`` (device scalar; ``0.0``
        before the first update) — the weight "mean" states fold against."""
        if self._dstate is None:
            return jnp.zeros((), jnp.float32)
        return self._dstate[DECAY_WEIGHT_KEY]

    def reset(self) -> None:
        self._dstate = None
        self._update_count = 0
        self._computed = None
        self._is_synced = False
        self._cache = None

    def merge_state(self, incoming_state: Any) -> None:
        """Decayed state is a property of ONE update stream: folding two
        ranks' discounted histories has no defined interleaving order."""
        raise TorchMetricsUserError(
            "ExponentialDecay holds a stream-local discounted history; merging across ranks "
            "has no defined update order. Compute per-rank instead."
        )

    def sync(self, dist_sync_fn: Any = None, process_group: Any = None,
             should_sync: bool = True, distributed_available: Any = None) -> None:
        """See :meth:`SlidingWindow.sync` — the registered ``_state`` is
        empty, so the inherited sync would ship nothing and trap updates."""
        is_dist = (distributed_available or self.distributed_available_fn)()
        if not should_sync or not is_dist:
            return
        raise TorchMetricsUserError(
            "ExponentialDecay is stream-local and cannot cross-process sync; compute "
            "per-rank instead."
        )

    def state_memory(self) -> Dict[str, Any]:
        return _obs_memory.state_memory(dict(self._dstate or {}))

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        return self.base_metric._filter_kwargs(**kwargs)

    def __repr__(self) -> str:
        if self.halflife is not None:
            return f"ExponentialDecay({self.base_metric!r}, halflife={self.halflife})"
        return f"ExponentialDecay({self.base_metric!r}, decay={self.decay})"
