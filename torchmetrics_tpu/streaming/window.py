"""Windowed and decayed metric transforms over infinite streams.

Every metric in this runtime accumulates forever: state is a sufficient
statistic of the WHOLE stream, which is the right shape for an eval epoch and
the wrong shape for monitoring traffic — "accuracy over the last 10k
predictions" and "error rate with a 1-hour halflife" are windowed questions a
forever-accumulator cannot answer without replaying history. The two
transforms here answer them with O(1) work per update and bounded state,
following the O(1)-state streaming-accumulator discipline of compiler-first
caching stacks (arXiv:2603.09555):

- :class:`SlidingWindow` — the metric over exactly the last ``window``
  updates. The state is a RING of ``window`` bucket states (one stacked
  device pytree, each bucket one update's isolated contribution); every
  update is ONE donated XLA call (``Metric._get_wupdate_fn``) that scatters
  the batch state into the next slot — no unbounded ``cat``, no per-update
  host round-trip, no O(window) work until ``compute()`` folds the buckets
  through the metric's own merge semantics.
- :class:`ExponentialDecay` — the metric over the whole stream with
  exponentially discounted history (``halflife`` in updates). No ring at
  all: the decay factor folds into the sum/count/mean leaves AT UPDATE TIME
  (``Metric._get_dupdate_fn``), so the state stays exactly one copy of the
  metric's own state plus one weight scalar.

Both dispatch through ``Metric._donation_safe_dispatch`` under their own tags
(``wupdate`` / ``dupdate``), so the reliability retry/rollback plane, the
telemetry counters/events/histograms, and the AOT warm-start cache apply to
windowed traffic unchanged. The wrappers are stream-local by construction:
``merge_state`` across ranks has no defined update order and raises (same
contract as :class:`~torchmetrics_tpu.wrappers.Running`); fleet-wide windowed
values come from syncing the window FOLD, or from the serving engine's
stacked plane.

See ``docs/streaming.md`` for the window semantics and the decay math.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _observability
from ..metric import DECAY_WEIGHT_KEY, WINDOW_COUNT_KEY, WINDOW_CURSOR_KEY, HostMetric, Metric
from ..observability import memory as _obs_memory
from ..parallel import sync as _sync
from ..utilities.exceptions import TorchMetricsUserError
from ..utilities.prints import rank_zero_warn

StateDict = Dict[str, Any]

_RING_RESERVED = (WINDOW_CURSOR_KEY, WINDOW_COUNT_KEY)


def _check_base(base: Metric, transform: str) -> None:
    if not isinstance(base, Metric):
        raise TorchMetricsUserError(
            f"{transform} wraps a torchmetrics_tpu.Metric, got {type(base).__name__}"
        )
    if isinstance(base, HostMetric):
        raise TorchMetricsUserError(
            f"{transform} needs a jitted batch-state core; {type(base).__name__} computes its "
            "batch state on host (text/detection/audio paths)."
        )
    if type(base)._batch_state is Metric._batch_state:
        raise TorchMetricsUserError(
            f"{type(base).__name__} has no pure _batch_state core to window "
            "(compositions/wrappers: wrap the operands instead)."
        )
    if not base._enable_jit:
        raise TorchMetricsUserError(f"{transform} requires a jit-enabled metric (jit=True).")


def _mask_rows(mask: jax.Array, ndim: int) -> jax.Array:
    """Broadcast a ``(B,)`` slot mask against ``(B, *state_shape)`` buckets."""
    return mask.reshape(mask.shape + (1,) * (ndim - 1))


class SlidingWindow(Metric):
    """Metric value over exactly the last ``window`` updates of a stream.

    Ring semantics: bucket ``i`` holds update ``i``'s isolated state
    contribution; an update past the window overwrites the expired bucket in
    place (one donated scatter — O(1) per update, O(window) state, zero
    growth). ``compute()`` folds the live buckets through the metric's own
    merge machinery, so the value is exactly what a fresh metric fed only the
    trailing ``window`` batches would report (the window-parity oracle
    ``tests/test_streaming.py`` pins across metric families).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.streaming import SlidingWindow
        >>> from torchmetrics_tpu.aggregation import SumMetric
        >>> metric = SlidingWindow(SumMetric(), window=2)
        >>> for batch in [1.0, 2.0, 3.0]:
        ...     metric.update(batch)
        >>> float(metric.compute())
        5.0
    """

    def __init__(self, base_metric: Metric, window: int) -> None:
        super().__init__()
        _check_base(base_metric, "SlidingWindow")
        if not (isinstance(window, int) and window > 0):
            raise ValueError(f"Expected argument `window` to be a positive integer but got {window}")
        for name, fx in base_metric._reductions.items():
            if fx == "cat" and name not in base_metric._list_state_names:
                raise TorchMetricsUserError(
                    f"{type(base_metric).__name__}.{name} is a 'cat'-reduced TENSOR state whose "
                    "shape grows per update — it cannot live in a fixed ring; keep cat data in "
                    "list states."
                )
        self.base_metric = base_metric
        self.window = int(window)
        self._ring: Optional[StateDict] = None  # lazy: built on first update
        self._append_ring: List[Optional[Dict[str, list]]] = []

    # ------------------------------------------------------------------ ring

    def _init_ring(self) -> None:
        base = self.base_metric
        defaults_t, _ = base._split_tensor_list(base.init_state())
        ring: StateDict = {
            k: jnp.repeat(jnp.asarray(v)[None], self.window, axis=0)
            for k, v in defaults_t.items()
        }
        ring[WINDOW_COUNT_KEY] = jnp.zeros((self.window,), jnp.float32)
        ring[WINDOW_CURSOR_KEY] = jnp.zeros((), jnp.int32)
        self._ring = ring
        self._append_ring = [None] * self.window

    def _slot_order(self) -> List[int]:
        """Live slots, oldest update first (host mirror of the device cursor)."""
        filled = min(self._update_count, self.window)
        return [(self._update_count - filled + i) % self.window for i in range(filled)]

    # ------------------------------------------------------------- lifecycle

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Roll this batch's contribution into the next ring slot (one
        donated XLA call under the ``wupdate`` dispatch tag)."""
        if self._is_synced:
            raise TorchMetricsUserError(
                "The Metric shouldn't be synced when performing ``update``. "
                "HINT: Did you forget to call ``unsync`` ?"
            )
        base = self.base_metric
        args, kwargs = base._prepare_inputs(*args, **kwargs)
        if self._ring is None:
            self._init_ring()
        fn = base._get_wupdate_fn()
        slot = self._update_count % self.window
        new_ring, appends = base._donation_safe_dispatch(
            "wupdate", lambda t, n: fn(t, n, *args, **kwargs), self._ring,
            inputs=(args, kwargs), jitted=fn, owner=self._ring,
        )
        self._ring = new_ring
        if base._list_state_names:
            # bounded host-side ring of list ("cat") contributions: the slot's
            # previous occupant expires with the overwrite, exactly like the
            # device buckets — window memory never grows past `window` updates
            self._append_ring[slot] = {k: [v] for k, v in appends.items()}
        self._update_count += 1
        self._computed = None
        rec = _observability._ACTIVE
        if rec is not None:
            rec.record_window_roll(
                base, self.window, min(self._update_count, self.window),
                wrapped=self._update_count % self.window == 0,
            )

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Roll the batch in AND return this batch's own value (the newest
        bucket computed alone — no double update)."""
        self.update(*args, **kwargs)
        return self._bucket_value((self._update_count - 1) % self.window)

    __call__ = forward

    def _bucket_value(self, slot: int) -> Any:
        base = self.base_metric
        batch = dict(base.init_state())
        for k, v in self._ring.items():
            if k not in _RING_RESERVED:
                batch[k] = v[slot]
        if base._list_state_names:
            bucket = self._append_ring[slot] or {}
            for name in base._list_state_names:
                batch[name] = list(bucket.get(name, []))
        return base._compute(base._concat_state(batch))

    # --------------------------------------------------------------- folding

    def window_state(self) -> StateDict:
        """The trailing window folded into one compute-ready state dict —
        exactly the state a fresh metric fed the last ``window`` batches
        would hold (list states stay host lists; ``_concat_state`` applies
        downstream)."""
        base = self.base_metric
        defaults = base.init_state()
        if self._ring is None:
            return defaults
        order = self._slot_order()
        states = {k: v for k, v in self._ring.items() if k not in _RING_RESERVED}
        out: StateDict = {}
        if base._has_custom_merge():
            # sequential fold through the metric's OWN merge, in stream order
            # — bitwise the per-update fold a plain metric would have run
            acc = {k: jnp.asarray(defaults[k]) for k in states}
            for slot in order:
                bucket = {k: v[slot] for k, v in states.items()}
                merged = base._merge(dict(acc), bucket)
                acc = {
                    k: jnp.asarray(v).astype(states[k].dtype) if k in states else v
                    for k, v in merged.items()
                }
            out.update(acc)
        else:
            mask = self._ring[WINDOW_COUNT_KEY] > 0
            for k, v in states.items():
                fx = base._reductions.get(k)
                d = jnp.asarray(defaults[k])
                if fx is None:
                    out[k] = d  # fx=None keeps the local (default) value, as update does
                elif callable(fx):
                    acc = d
                    for slot in order:
                        acc = _sync.pairwise_merge(fx, acc, v[slot])
                    out[k] = acc
                elif fx == "sum":
                    m = _mask_rows(mask, v.ndim)
                    out[k] = (d + jnp.where(m, v, jnp.zeros_like(v)).sum(axis=0)).astype(v.dtype)
                elif fx == "mean":
                    m = _mask_rows(mask, v.ndim)
                    n = mask.sum()
                    mean = (v * m.astype(v.dtype)).sum(axis=0) / jnp.maximum(n, 1.0).astype(v.dtype)
                    out[k] = jnp.where(n > 0, mean, d).astype(v.dtype)
                elif fx == "max":
                    out[k] = jnp.maximum(d, jnp.where(_mask_rows(mask, v.ndim), v, d).max(axis=0))
                elif fx == "min":
                    out[k] = jnp.minimum(d, jnp.where(_mask_rows(mask, v.ndim), v, d).min(axis=0))
                else:  # pragma: no cover — construction rejects tensor "cat"
                    raise TorchMetricsUserError(f"Unsupported reduction {fx!r} in a window fold")
        for name in base._list_state_names:
            rows: list = []
            for slot in order:
                bucket = self._append_ring[slot] or {}
                rows.extend(bucket.get(name, []))
            out[name] = rows
        return out

    def compute(self) -> Any:
        if self._update_count == 0 and not self._update_called_warned:
            rank_zero_warn(
                f"The ``compute`` method of metric {type(self).__name__} was called before the "
                "``update`` method which may lead to errors, as metric states have not yet been updated.",
                UserWarning,
            )
            self._update_called_warned = True
        if self.compute_with_cache and self._computed is not None:
            return self._computed
        base = self.base_metric
        value = base._compute(base._concat_state(self.window_state()))
        if self.compute_with_cache:
            self._computed = value
        return value

    def reset(self) -> None:
        self._ring = None
        self._append_ring = []
        self._update_count = 0
        self._computed = None
        self._is_synced = False
        self._cache = None

    # ------------------------------------------------------------- contracts

    def merge_state(self, incoming_state: Any) -> None:
        """A sliding window is a property of ONE update stream (same contract
        as ``wrappers.Running``): merging two ranks' windows has no defined
        update order, so this raises instead of silently interleaving."""
        raise TorchMetricsUserError(
            "SlidingWindow holds a stream-local window of the last updates; merging windows "
            "across ranks has no defined update order. Sync the window FOLD instead: "
            "compute per-rank, or feed window_state() into the sync planes."
        )

    def sync(self, dist_sync_fn: Any = None, process_group: Any = None,
             should_sync: bool = True, distributed_available: Any = None) -> None:
        """The wrapper's registered ``_state`` is EMPTY (the ring is the real
        state), so the inherited sync would 'succeed' while shipping nothing
        and then brick ``update()`` behind ``_is_synced`` — raise instead,
        mirroring :meth:`merge_state` (no-op when nothing would sync, exactly
        like ``Metric.sync``'s unavailable path)."""
        is_dist = (distributed_available or self.distributed_available_fn)()
        if not should_sync or not is_dist:
            return
        raise TorchMetricsUserError(
            "SlidingWindow is stream-local and cannot cross-process sync; sync the window "
            "FOLD instead (feed window_state() into the sync planes, or compute per-rank)."
        )

    def state_memory(self) -> Dict[str, Any]:
        """Ring footprint (metadata only, zero D2H) — the bounded-by-window
        invariant an operator checks instead of the cat-growth sentinel."""
        return _obs_memory.state_memory(dict(self._ring or {}))

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        return self.base_metric._filter_kwargs(**kwargs)

    def __repr__(self) -> str:
        return f"SlidingWindow({self.base_metric!r}, window={self.window})"


class ExponentialDecay(Metric):
    """Metric over the whole stream with exponentially discounted history.

    ``halflife`` is measured in UPDATES: a batch ``h`` updates old carries
    half the weight of the current one (``decay = 2**(-1/halflife)``; pass
    ``decay`` directly to pin the factor). State stays O(1): the factor folds
    into the accumulating leaves at update time —

    - ``sum`` leaves:   ``s_n = d * s_{n-1} + x_n``  (so ``s_n = Σ d^k x_{n-k}``),
    - ``mean`` leaves:  weighted mean against the decayed update count
      ``w_n = d * w_{n-1} + 1`` (so ratios like accuracy become the
      exponentially weighted average of their batch values),
    - ``max``/``min``/``None`` leaves keep their plain merge (an extremum
      has no meaningful discount).

    Integer sum/mean leaves are promoted to float32 at construction —
    discounted counts are fractional by nature.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.streaming import ExponentialDecay
        >>> from torchmetrics_tpu.aggregation import SumMetric
        >>> metric = ExponentialDecay(SumMetric(), decay=0.5)
        >>> for batch in [1.0, 1.0, 1.0]:
        ...     metric.update(batch)
        >>> float(metric.compute())
        1.75
    """

    def __init__(
        self,
        base_metric: Metric,
        halflife: Optional[float] = None,
        decay: Optional[float] = None,
    ) -> None:
        super().__init__()
        _check_base(base_metric, "ExponentialDecay")
        if (halflife is None) == (decay is None):
            raise ValueError("Pass exactly one of `halflife` (in updates) or `decay` (per-update factor).")
        if halflife is not None:
            if not halflife > 0:
                raise ValueError(f"Expected `halflife` > 0, got {halflife}")
            decay = float(2.0 ** (-1.0 / float(halflife)))
        if not 0.0 < decay < 1.0:
            raise ValueError(f"Expected `decay` in (0, 1), got {decay}")
        if base_metric._list_state_names:
            raise TorchMetricsUserError(
                f"{type(base_metric).__name__} holds dynamic-length concat states; exponential "
                "decay over an unbounded concatenation is undefined."
            )
        if base_metric._has_custom_merge():
            raise TorchMetricsUserError(
                f"{type(base_metric).__name__} overrides _merge; a decay factor cannot be "
                "folded into an unknown merge safely."
            )
        for name, fx in base_metric._reductions.items():
            if callable(fx) or fx == "cat":
                raise TorchMetricsUserError(
                    f"{type(base_metric).__name__}.{name} uses reduction {fx!r}, which has no "
                    "defined exponential discount; only sum/mean/max/min/None states decay."
                )
        self.base_metric = base_metric
        self.halflife = float(halflife) if halflife is not None else None
        self.decay = float(decay)
        self._dstate: Optional[StateDict] = None
        self._decay_arr = None  # lazy device scalar (traced input, never donated)

    def _init_dstate(self) -> None:
        base = self.base_metric
        defaults_t, _ = base._split_tensor_list(base.init_state())
        st: StateDict = {}
        for k, v in defaults_t.items():
            v = jnp.asarray(v)
            if base._reductions.get(k) in ("sum", "mean") and not jnp.issubdtype(v.dtype, jnp.floating):
                v = v.astype(jnp.float32)  # discounted counts are fractional
            st[k] = v
        st[DECAY_WEIGHT_KEY] = jnp.zeros((), jnp.float32)
        self._dstate = st

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Fold this batch in with the decay applied (one donated XLA call
        under the ``dupdate`` dispatch tag)."""
        if self._is_synced:
            raise TorchMetricsUserError(
                "The Metric shouldn't be synced when performing ``update``. "
                "HINT: Did you forget to call ``unsync`` ?"
            )
        base = self.base_metric
        args, kwargs = base._prepare_inputs(*args, **kwargs)
        if self._dstate is None:
            self._init_dstate()
        if self._decay_arr is None:
            self._decay_arr = jnp.asarray(np.float32(self.decay))
        fn = base._get_dupdate_fn()
        decay = self._decay_arr
        self._dstate = base._donation_safe_dispatch(
            "dupdate", lambda t, n: fn(t, n, decay, *args, **kwargs), self._dstate,
            inputs=((decay,) + args, kwargs), jitted=fn, owner=self._dstate,
        )
        self._update_count += 1
        self._computed = None

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Fold the batch in and return the post-update decayed value (the
        streaming dashboard reading, not the batch-only value)."""
        self.update(*args, **kwargs)
        return self.compute()

    __call__ = forward

    def compute(self) -> Any:
        if self._update_count == 0 and not self._update_called_warned:
            rank_zero_warn(
                f"The ``compute`` method of metric {type(self).__name__} was called before the "
                "``update`` method which may lead to errors, as metric states have not yet been updated.",
                UserWarning,
            )
            self._update_called_warned = True
        if self.compute_with_cache and self._computed is not None:
            return self._computed
        base = self.base_metric
        if self._dstate is None:
            state = {k: v for k, v in base.init_state().items()}
        else:
            state = {k: v for k, v in self._dstate.items() if k != DECAY_WEIGHT_KEY}
        value = base._compute(state)
        if self.compute_with_cache:
            self._computed = value
        return value

    @property
    def decayed_count(self) -> Any:
        """The discounted update count ``Σ d^k`` (device scalar; ``0.0``
        before the first update) — the weight "mean" states fold against."""
        if self._dstate is None:
            return jnp.zeros((), jnp.float32)
        return self._dstate[DECAY_WEIGHT_KEY]

    def reset(self) -> None:
        self._dstate = None
        self._update_count = 0
        self._computed = None
        self._is_synced = False
        self._cache = None

    def merge_state(self, incoming_state: Any) -> None:
        """Decayed state is a property of ONE update stream: folding two
        ranks' discounted histories has no defined interleaving order."""
        raise TorchMetricsUserError(
            "ExponentialDecay holds a stream-local discounted history; merging across ranks "
            "has no defined update order. Compute per-rank instead."
        )

    def sync(self, dist_sync_fn: Any = None, process_group: Any = None,
             should_sync: bool = True, distributed_available: Any = None) -> None:
        """See :meth:`SlidingWindow.sync` — the registered ``_state`` is
        empty, so the inherited sync would ship nothing and trap updates."""
        is_dist = (distributed_available or self.distributed_available_fn)()
        if not should_sync or not is_dist:
            return
        raise TorchMetricsUserError(
            "ExponentialDecay is stream-local and cannot cross-process sync; compute "
            "per-rank instead."
        )

    def state_memory(self) -> Dict[str, Any]:
        return _obs_memory.state_memory(dict(self._dstate or {}))

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        return self.base_metric._filter_kwargs(**kwargs)

    def __repr__(self) -> str:
        if self.halflife is not None:
            return f"ExponentialDecay({self.base_metric!r}, halflife={self.halflife})"
        return f"ExponentialDecay({self.base_metric!r}, decay={self.decay})"
