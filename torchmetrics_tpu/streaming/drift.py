"""Drift evaluators over infinite streams.

A windowed metric answers "what is the value NOW"; a drift monitor answers
"did the stream CHANGE" — the question a monitoring service actually pages
on. :class:`DriftMonitor` compares two windowed views of one update stream:

- the **test window**: a :class:`~torchmetrics_tpu.streaming.SlidingWindow`
  over the last ``test_window`` updates (the "now");
- the **reference window**: a tumbling block of ``reference_window`` updates
  — the stream accumulates into a plain clone of the metric, and every time
  the block fills, its compute freezes as the new reference and the block
  resets ("drift vs. the previous hour" when an hour is ``reference_window``
  updates).

``evaluate()`` computes both views, scores their divergence (max absolute —
or relative — elementwise difference across the computed value's leaves),
and feeds the health plane: the score lands in the SLO expression namespace
as ``drift(name)`` (so declarative rules can page on sustained drift), the
``drift_evals``/``drift_breaches`` counters tick, and a breach rides the
``alert`` event kind exactly like an SLO rule breach. Evaluation reads the
computed values back to host (a deliberate D2H) — it runs every
``eval_every`` updates, never inside the jitted roll itself, so the update
hot path stays transfer-free.
"""

from __future__ import annotations

import collections
from typing import Any, Deque, Dict, Optional

import numpy as np

from .. import observability as _observability
from ..metric import Metric
from ..utilities.exceptions import TorchMetricsUserError
from .window import SlidingWindow

_MODES = ("abs", "rel")


def _leaf_scores(test_value: Any, ref_value: Any, mode: str) -> float:
    """Max divergence across the computed value's leaves (host floats)."""
    import jax

    t_leaves = jax.tree_util.tree_leaves(test_value)
    r_leaves = jax.tree_util.tree_leaves(ref_value)
    if len(t_leaves) != len(r_leaves):
        raise TorchMetricsUserError(
            "test and reference computes produced different value structures; "
            "drift scoring needs a stable compute output shape."
        )
    worst = 0.0
    for t, r in zip(t_leaves, r_leaves):
        t = np.asarray(t, np.float64)
        r = np.asarray(r, np.float64)
        diff = np.abs(t - r)
        if mode == "rel":
            diff = diff / np.maximum(np.abs(r), 1e-12)
        finite = diff[np.isfinite(diff)]
        if finite.size:
            worst = max(worst, float(finite.max()))
    return worst


class DriftMonitor:
    """Windowed drift evaluator: current vs. previous-block metric value.

    Args:
        metric: the metric template (cloned twice — the monitor never touches
            the caller's object). Must satisfy :class:`SlidingWindow`'s
            requirements (jitted batch-state core).
        reference_window: tumbling block length in updates; each full block's
            compute becomes the next reference value.
        test_window: sliding window length of the "now" view.
        threshold: drift score past which an evaluation counts as a breach.
        mode: ``"abs"`` (max absolute difference, the default) or ``"rel"``
            (relative to the reference magnitude).
        name: identity in the SLO namespace / alert stream
            (default ``drift_<ClassName>``).
        eval_every: auto-evaluate every this many updates once a reference
            exists (default: ``test_window``); ``0`` disables auto-evaluation
            (call :meth:`evaluate` yourself).
        severity: carried on breach alerts (``info``/``warning``/``critical``).
    """

    def __init__(
        self,
        metric: Metric,
        reference_window: int = 512,
        test_window: int = 128,
        threshold: float = 0.05,
        mode: str = "abs",
        name: Optional[str] = None,
        eval_every: Optional[int] = None,
        severity: str = "warning",
    ) -> None:
        if not (isinstance(reference_window, int) and reference_window > 0):
            raise ValueError(f"Expected `reference_window` to be a positive integer, got {reference_window}")
        if not (isinstance(test_window, int) and test_window > 0):
            raise ValueError(f"Expected `test_window` to be a positive integer, got {test_window}")
        if mode not in _MODES:
            raise ValueError(f"Expected `mode` to be one of {_MODES}, got {mode!r}")
        if threshold < 0:
            raise ValueError(f"Expected `threshold` >= 0, got {threshold}")
        self.reference_window = reference_window
        self.test_window = test_window
        self.threshold = float(threshold)
        self.mode = mode
        self.name = name or f"drift_{type(metric).__name__}"
        self.eval_every = test_window if eval_every is None else int(eval_every)
        self.severity = severity
        # drift is stream-local: neither view may sync mid-stream
        test_base = metric.clone()
        test_base.sync_on_compute = False
        self.test = SlidingWindow(test_base, test_window)
        self._block = metric.clone()
        self._block.sync_on_compute = False
        self._block.reset()
        self.reference_value: Any = None
        self._since_eval = 0
        self.last: Optional[Dict[str, Any]] = None
        self.breached = False
        self.history: Deque[Dict[str, Any]] = collections.deque(maxlen=256)

    # -------------------------------------------------------------- lifecycle

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Feed one batch to both views; rolls the reference block when it
        fills and auto-evaluates on the ``eval_every`` cadence."""
        self.test.update(*args, **kwargs)
        self._block.update(*args, **kwargs)
        if self._block._update_count >= self.reference_window:
            self.reference_value = self._block.compute()
            self._block.reset()
        self._since_eval += 1
        if (
            self.eval_every
            and self.reference_value is not None
            and self._since_eval >= self.eval_every
        ):
            self.evaluate()

    def evaluate(self) -> Optional[Dict[str, Any]]:
        """Score the test window against the current reference (``None``
        until the first reference block completes). Feeds the health plane
        when a telemetry session is active."""
        self._since_eval = 0
        if self.reference_value is None:
            return None
        test_value = self.test.compute()
        score = _leaf_scores(test_value, self.reference_value, self.mode)
        self.breached = score > self.threshold
        self.last = {
            "name": self.name,
            "score": score,
            "threshold": self.threshold,
            "breached": self.breached,
            "mode": self.mode,
        }
        self.history.append(dict(self.last))
        rec = _observability._ACTIVE
        if rec is not None:
            rec.record_drift(
                self.name, score, self.breached, self.threshold, severity=self.severity
            )
        return self.last

    def reset(self) -> None:
        """Forget both views AND the reference (a fresh stream)."""
        self.test.reset()
        self._block.reset()
        self.reference_value = None
        self._since_eval = 0
        self.last = None
        self.breached = False
        self.history.clear()

    def __repr__(self) -> str:
        return (
            f"DriftMonitor({self.name!r}, reference_window={self.reference_window}, "
            f"test_window={self.test_window}, threshold={self.threshold})"
        )
