"""Streaming plane: windowed/decayed metrics over infinite streams.

Forever-accumulating metrics answer epoch questions; monitoring traffic asks
windowed ones. This package holds the O(1)-per-update stream transforms —

- :class:`SlidingWindow` — the metric over the last ``window`` updates,
  represented by a TIER selected from the metric's reduce-tags
  (:func:`window_tier`): a constant-memory dual pair (sum/mean), a
  DABA-style paned two-stack (max/min/callable semigroups), or the exact
  per-update bucket ring (custom merges, cat states) — one donated XLA call
  per update in every tier, no unbounded ``cat``;
- :class:`ExponentialDecay` — the metric with exponentially discounted
  history (decay folded into sum/count/mean leaves at update time);
- :class:`DriftMonitor` — current-window vs. previous-block divergence,
  wired into the SLO/alert engine (``drift(name)`` namespace entries,
  breaches ride the ``alert`` event kind)

- :class:`TelescopingFold` — the telescoping multi-resolution retention
  fold (``telescope.py``, stdlib-only): bounded per-level rings of closed
  blocks, each level folding into the coarser one above — O(levels) memory
  for "the last 10s at 1s, the last hour at 1m, the last day at 1h". The
  telemetry history plane (``observability/timeseries.py``, ``/historyz``)
  rides it today; per-tenant telescoped metric states are the ROADMAP
  follow-on

— plus their sync-side counterpart,
:class:`~torchmetrics_tpu.parallel.AsyncSyncHandle` (``parallel/``), the
double-buffered background sync ``MetricCollection.sync(async_=True)`` and
``ServingEngine.sync_async`` launch so the previous window's collective set
overlaps the current window's updates.

See ``docs/streaming.md``.
"""

from .telescope import TelescopingFold  # stdlib-only: import before the jax-backed tiers
from ..metric import window_tier
from .drift import DriftMonitor
from .window import ExponentialDecay, SlidingWindow

__all__ = ["DriftMonitor", "ExponentialDecay", "SlidingWindow", "TelescopingFold", "window_tier"]
