from .mesh import DEFAULT_AXIS, batch_sharding, make_2d_mesh, make_data_mesh, replicated
from .sync import (
    distributed_available,
    gather_all_arrays,
    merge_states,
    pairwise_merge,
    process_sync,
    reduce_over_axis,
    reduce_states,
)

__all__ = [
    "DEFAULT_AXIS",
    "batch_sharding",
    "distributed_available",
    "gather_all_arrays",
    "make_2d_mesh",
    "make_data_mesh",
    "merge_states",
    "pairwise_merge",
    "process_sync",
    "reduce_over_axis",
    "reduce_states",
    "replicated",
]
