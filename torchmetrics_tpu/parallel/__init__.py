from . import coalesce, quantize
from .async_sync import AsyncSyncHandle
from .coalesce import (
    CoalesceFallback,
    clear_dead_ranks,
    coalesced_process_sync,
    collective_counts,
    quantized_payload_model,
    reduce_many,
)
from .mesh import (
    DEFAULT_AXIS,
    DEFAULT_TENANT_AXIS,
    batch_sharding,
    make_2d_mesh,
    make_data_mesh,
    replicated,
    shard_map,
    tenant_sharding,
)
from .quantize import SyncConfig
from .sync import (
    distributed_available,
    gather_all_arrays,
    merge_states,
    pairwise_merge,
    process_sync,
    reduce_over_axis,
    reduce_states,
    reduce_states_per_leaf,
)

__all__ = [
    "AsyncSyncHandle",
    "CoalesceFallback",
    "DEFAULT_AXIS",
    "DEFAULT_TENANT_AXIS",
    "SyncConfig",
    "batch_sharding",
    "coalesce",
    "clear_dead_ranks",
    "coalesced_process_sync",
    "collective_counts",
    "distributed_available",
    "gather_all_arrays",
    "make_2d_mesh",
    "make_data_mesh",
    "merge_states",
    "pairwise_merge",
    "process_sync",
    "quantize",
    "quantized_payload_model",
    "reduce_many",
    "reduce_over_axis",
    "reduce_states",
    "reduce_states_per_leaf",
    "replicated",
    "shard_map",
    "tenant_sharding",
]
