"""Quantized sync plane — opt-in compressed collective buckets.

The coalesced sync plane (``parallel/coalesce.py``) already collapses a sync
to one collective per dtype bucket, but each bucket still ships full-width
f32/f64 payloads. EQuARX (arXiv:2506.17615) shows quantized all-reduce
recovers 2x+ collective bandwidth at negligible quality loss; this module is
that compression tier for the host-driven cross-process plane (the in-graph
psum plane stays exact — device collectives would need a custom quantized
all-reduce kernel, out of scope here):

- **bf16 codec**: eligible f32/f64 leaves cast to bfloat16 on the wire
  (2x / 4x), dequantized back after the gather. Relative error <= 2^-8 per
  element (8 explicit mantissa bits, round-to-nearest).
- **int8 codec**: eligible leaves block-quantized to uint8 with per-block
  affine ``(scale, zero_point)`` metadata (4x / 8x). Blocks are allocated
  from a per-bucket slot pool and NEVER cross leaf boundaries, so each
  leaf's worst-case error is ``max_block (scale/2)`` over its own blocks —
  ``scale = block_range / 255``, absolute error <= ``range/510``.

**Metadata rides the metadata collective.** Per-leaf codec announcements
pack into the existing leaf records and per-bucket scale/zero vectors ride a
quant section of the same up-front metadata gather — a quantized sync
launches exactly as many collectives as an exact one. Each rank ships its
OWN announced encoding and every rank decodes rank ``r``'s segment with rank
``r``'s announced codes/scales, so eligibility decisions never need
cross-rank agreement (a rank whose data blows the error budget ships exact
while its peers compress).

**Eligibility — the exact path is forced for**: integer/bool/bf16/f16
leaves (count states must stay bitwise; sub-f32 floats are already compact),
custom-callable ``_merge`` leaves and ``fx=None`` keep-local leaves, leaves
below :attr:`SyncConfig.min_leaf_bytes` (scale metadata would cost more than
it saves), leaves whose single-block worst-case error exceeds the caller's
per-tag :attr:`SyncConfig.error_budget`, and world-of-one syncs (a lossy
round-trip with nobody to ship to would be pure error — pinned by test).

**Error feedback**: for additive reduction tags (``sum``/``mean``) the
quantization residual ``r_t = x'_t - dequant(quant(x'_t))`` of each sync is
carried and added to the next sync's payload (``x'_{t+1} = x_{t+1} +
r_t``), so repeated-sync drift stays bounded by ONE quantization step
instead of accumulating: ``sum_t dequant_t = sum_t x_t + r_0 - r_N`` — the
classic error-feedback telescoping bound. Residuals commit only after every
bucket of a sync gathered successfully; a transient failure (``FlakyGather``),
an exhausted retry budget, or a per-leaf ``CoalesceFallback`` leaves the
residual buffers untouched, so a failed sync can never double-apply feedback.

See docs/distributed.md, "Quantized synchronization".
"""

from __future__ import annotations

import dataclasses
import math
import threading
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# wire codec codes (packed into the leaf records' kind slot, see coalesce.py)
CODEC_NONE = 0
CODEC_BF16 = 1
CODEC_INT8 = 2
CODEC_NAMES: Dict[str, int] = {"none": CODEC_NONE, "bf16": CODEC_BF16, "int8": CODEC_INT8}
_CODE_TO_NAME = {v: k for k, v in CODEC_NAMES.items()}

# reduction tags whose leaves may compress at all, and the subset that carries
# error-feedback residuals (feedback telescopes only through ADDITIVE folds)
ELIGIBLE_TAGS = ("sum", "mean", "max", "min", "cat")
FEEDBACK_TAGS = ("sum", "mean")

# dtypes the codecs apply to (everything else is forced exact)
_ELIGIBLE_DTYPES = (jnp.dtype(jnp.float32), jnp.dtype(jnp.float64))

# the metadata quant section carries ONE record per dtype in this tuple —
# a fixed layout, so the metadata vector length is rank-invariant even when
# empty list leaves hide a dtype on some ranks (the real process_allgather
# requires equal row shapes; a variable section would break the collective,
# not just the validation)
QUANT_SECTION_DTYPES = _ELIGIBLE_DTYPES

# reserved (scale, zero) slot pairs per dtype record: the int8 block pool a
# bucket's quantized leaves allocate from (every quantized leaf needs at
# least one block, so at most this many leaves per bucket compress — the
# smallest candidates beyond it ship exact)
BUCKET_SCALE_SLOTS = 64

# spill-codec block cap (per leaf; the spill format is self-describing)
MIN_SCALE_SLOTS = 16

# mirrored by metric.QUANT_RESIDUAL_KEY for the graftlint reserved-key
# registry (pinned equal by test) — residual store keys and any future
# state-dict-resident residual leaves live under this prefix
RESIDUAL_KEY_PREFIX = "__quant_err:"

_BF16_REL_ERR = 2.0 ** -8  # conservative per-element bound of the bf16 cast


@dataclasses.dataclass
class SyncConfig:
    """Opt-in compression knobs for one logical sync target (a metric or a
    collection). The instance owns the error-feedback residual store, so use
    one config per target — sharing an instance across unrelated syncs would
    cross-apply residuals — and call :meth:`clear_residuals` when the target
    rotates epochs (``reset()``): a residual is debt owed for PREVIOUS
    payloads, and folding it into a fresh epoch's first sync biases that sync
    by up to one quantization step of the old data.

    Args:
        codec: ``"none"`` (exact — the default), ``"bf16"``, or ``"int8"``.
        error_feedback: carry quantization residuals across repeated syncs of
            additive (``sum``/``mean``) leaves (see the module docstring).
        error_budget: optional per-tag map (``{"sum": 1e-3}``) of the maximum
            acceptable per-element absolute quantization error; a leaf whose
            worst-case bound exceeds its tag's budget ships exact. Missing
            tags have no budget (always eligible).
        min_leaf_bytes: leaves smaller than this ship exact — scale metadata
            would cost more than the compression saves.
    """

    codec: str = "none"
    error_feedback: bool = True
    error_budget: Optional[Mapping[str, float]] = None
    min_leaf_bytes: int = 64

    def __post_init__(self) -> None:
        if self.codec not in CODEC_NAMES:
            raise ValueError(
                f"codec must be one of {sorted(CODEC_NAMES)}, got {self.codec!r}"
            )
        if self.min_leaf_bytes < 0:
            raise ValueError(f"min_leaf_bytes must be >= 0, got {self.min_leaf_bytes}")
        # (state_idx, leaf_name) -> np.ndarray residual, guarded for the async
        # double-buffer worker which commits from its background thread
        self._residuals: Dict[str, np.ndarray] = {}
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.codec != "none"

    @property
    def codec_code(self) -> int:
        return CODEC_NAMES[self.codec]

    # ------------------------------------------------------ residual store

    def _residual_key(self, state_idx: int, name: str) -> str:
        return f"{RESIDUAL_KEY_PREFIX}{state_idx}:{name}"

    def residual(self, state_idx: int, name: str) -> Optional[np.ndarray]:
        with self._lock:
            r = self._residuals.get(self._residual_key(state_idx, name))
            return None if r is None else np.array(r)

    def _commit_residuals(self, updates: Dict[str, np.ndarray]) -> None:
        with self._lock:
            self._residuals.update(updates)

    def residual_norm(self) -> float:
        """L2 norm over every stored residual — the ``quant_error_feedback_norm``
        gauge (how much shipped value is currently "owed" to future syncs)."""
        with self._lock:
            total = 0.0
            for r in self._residuals.values():
                total += float(np.sum(np.square(np.asarray(r, np.float64))))
            return math.sqrt(total)

    def clear_residuals(self) -> None:
        with self._lock:
            self._residuals.clear()

    # residual arrays and locks must not ride pickles (a SyncConfig is a knob
    # object; residuals are session-local transport state)
    def __getstate__(self) -> Dict[str, Any]:
        state = dict(self.__dict__)
        state["_residuals"] = {}
        state.pop("_lock", None)
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__dict__.update(state)
        self._residuals = {}
        self._lock = threading.Lock()


# ---------------------------------------------------------------------------
# shared block-quantization kernels (sync plane + tenant-spill codec)
# ---------------------------------------------------------------------------


def codec_width(code: int, itemsize: int) -> int:
    """Wire bytes per element for a leaf announced under ``code``."""
    if code == CODEC_BF16:
        return 2
    if code == CODEC_INT8:
        return 1
    return itemsize


def allocate_blocks(counts: Sequence[int], slots: int) -> List[int]:
    """Deterministic per-leaf block allocation from a bucket's slot pool:
    every leaf gets at least one block (blocks never cross leaf boundaries —
    that is what keeps each leaf's error bound independent of its bucket
    neighbours), extra slots go to bigger leaves by largest remainder, and no
    leaf gets more blocks than elements. Encoder and decoder both run this on
    the announced counts, so the scale vector needs no extra framing."""
    n = len(counts)
    if n == 0:
        return []
    blocks = [1] * n
    remaining = slots - n
    total = sum(counts)
    if remaining > 0 and total > 0:
        want = [c * remaining / total for c in counts]
        base = [int(w) for w in want]
        blocks = [b + w for b, w in zip(blocks, base)]
        leftover = remaining - sum(base)
        order = sorted(range(n), key=lambda i: (-(want[i] - base[i]), i))
        for i in order[:leftover]:
            blocks[i] += 1
    return [min(b, c) if c else 1 for b, c in zip(blocks, counts)]


def _block_edges(count: int, n_blocks: int) -> int:
    """Padded block length (edge-padded so padding never widens a range)."""
    return -(-count // n_blocks)  # ceil


def block_quantize(flat: Any, n_blocks: int) -> Tuple[Any, np.ndarray, np.ndarray]:
    """Affine uint8 block quantization of a flat float vector. Returns the
    unpadded uint8 payload plus host ``(scale, zero)`` f32 vectors (one entry
    per block — these are the bytes that ride the metadata collective)."""
    x = jnp.ravel(jnp.asarray(flat))
    count = int(x.shape[0])
    bl = _block_edges(count, n_blocks)
    pad = n_blocks * bl - count
    xp = jnp.pad(x, (0, pad), mode="edge").reshape(n_blocks, bl)
    mn = xp.min(axis=1)
    mx = xp.max(axis=1)
    scale = jnp.where(mx > mn, (mx - mn) / 255.0, jnp.ones_like(mn)).astype(jnp.float32)
    q = jnp.clip(
        jnp.round((xp - mn[:, None]) / scale[:, None].astype(xp.dtype)), 0, 255
    ).astype(jnp.uint8)
    return q.ravel()[:count], np.asarray(scale, np.float32), np.asarray(mn, np.float32)


def block_dequantize(
    q_flat: Any, scale: np.ndarray, zero: np.ndarray, count: int, dtype: Any
) -> Any:
    """Inverse of :func:`block_quantize` (scale/zero are f32, so f64 leaves
    dequantize with f32-precision offsets — dominated by the block error)."""
    n_blocks = len(scale)
    bl = _block_edges(count, n_blocks)
    pad = n_blocks * bl - count
    qp = jnp.pad(jnp.asarray(q_flat).astype(jnp.float32), (0, pad)).reshape(n_blocks, bl)
    x = qp * jnp.asarray(scale)[:, None] + jnp.asarray(zero)[:, None]
    return x.ravel()[:count].astype(dtype)


def int8_error_bound(flat: Any) -> float:
    """Worst-case per-element absolute error of int8-quantizing ``flat`` with
    a SINGLE block — the monotone upper bound the eligibility check uses
    (more blocks can only shrink per-block ranges)."""
    x = jnp.ravel(jnp.asarray(flat))
    if int(x.shape[0]) == 0:
        return 0.0
    return float((x.max() - x.min()) / 255.0) / 2.0


def bf16_error_bound(flat: Any) -> float:
    """Worst-case per-element absolute error of the bf16 cast."""
    x = jnp.ravel(jnp.asarray(flat))
    if int(x.shape[0]) == 0:
        return 0.0
    return float(jnp.abs(x).max()) * _BF16_REL_ERR


def to_bytes(arr: Any) -> Any:
    """Bitwise view of any array as a flat uint8 vector (device op — exact
    leaves inside a byte-stream bucket round-trip bit-for-bit)."""
    x = jnp.asarray(arr)
    if x.dtype == jnp.bool_:
        return x.ravel().astype(jnp.uint8)
    if x.dtype.itemsize == 1:
        return jax.lax.bitcast_convert_type(x.ravel(), jnp.uint8)
    return jax.lax.bitcast_convert_type(x.ravel(), jnp.uint8).ravel()


def from_bytes(seg: Any, count: int, dtype: Any) -> Any:
    """Inverse of :func:`to_bytes` for a ``count``-element vector."""
    dt = jnp.dtype(dtype)
    u8 = jnp.asarray(seg).astype(jnp.uint8)
    if dt == jnp.dtype(jnp.bool_):
        return u8[:count].astype(jnp.bool_)
    if dt.itemsize == 1:
        return jax.lax.bitcast_convert_type(u8[:count], dt)
    return jax.lax.bitcast_convert_type(u8.reshape(count, dt.itemsize), dt)


# ---------------------------------------------------------------------------
# per-sync encode context (built by coalesce.py before the metadata gather)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _LeafEnc:
    """One leaf's local encode decision."""

    code: int  # announced codec (CODEC_NONE when ineligible)
    feedback: bool = False
    x_eff: Any = None  # flat payload with residual folded in (quantized leaves)
    new_residual: Optional[np.ndarray] = None  # committed only on sync success


class QuantContext:
    """Everything one rank announces and ships for one quantized sync: the
    per-leaf codec decisions, per-bucket block allocations and scale vectors,
    and the candidate residual updates (committed only after every bucket of
    the sync gathered successfully)."""

    def __init__(self, config: SyncConfig, leaves: Sequence[Any]) -> None:
        self.config = config
        self.leaves = leaves
        self.encs: List[_LeafEnc] = [self._decide(leaf) for leaf in leaves]
        # bucket layout mirrors coalesce: dtype -> leaf indices in
        # first-appearance order over leaves with data
        self.bucket_order: List[Any] = []
        self.bucket_leaves: Dict[Any, List[int]] = {}
        for i, leaf in enumerate(leaves):
            if leaf.array is None:
                continue
            dt = jnp.dtype(leaf.array.dtype)
            if dt not in self.bucket_leaves:
                self.bucket_order.append(dt)
                self.bucket_leaves[dt] = []
            self.bucket_leaves[dt].append(i)
        # per-bucket int8 blocks/scales over the announced-quantized leaves
        self.bucket_blocks: Dict[Any, List[int]] = {}
        self.bucket_scales: Dict[Any, np.ndarray] = {}
        self.bucket_zeros: Dict[Any, np.ndarray] = {}
        self.payloads: Dict[int, Any] = {}  # leaf idx -> wire uint8 payload
        for dt in self.bucket_order:
            self._encode_bucket(dt)

    # ------------------------------------------------------------ decisions

    def _decide(self, leaf: Any) -> _LeafEnc:
        cfg = self.config
        arr = leaf.array
        if arr is None:
            return _LeafEnc(CODEC_NONE)
        fx = leaf.fx
        tag = fx if isinstance(fx, str) else None
        if tag not in ELIGIBLE_TAGS:
            return _LeafEnc(CODEC_NONE)  # custom _merge / fx=None: exact
        dt = jnp.dtype(arr.dtype)
        if dt not in _ELIGIBLE_DTYPES:
            return _LeafEnc(CODEC_NONE)  # ints/bool/bf16/f16: exact
        if int(arr.size) == 0 or int(arr.size) * dt.itemsize < cfg.min_leaf_bytes:
            return _LeafEnc(CODEC_NONE)  # nothing to compress / under the floor
        feedback = cfg.error_feedback and tag in FEEDBACK_TAGS
        x = jnp.ravel(jnp.asarray(arr))
        if feedback:
            r = cfg.residual(leaf.state_idx, leaf.name)
            if r is not None and r.shape == (int(x.shape[0]),):
                x = x + jnp.asarray(r, x.dtype)
        budget = (cfg.error_budget or {}).get(tag)
        if budget is not None:
            bound = (
                int8_error_bound(x) if cfg.codec == "int8" else bf16_error_bound(x)
            )
            if bound > budget:
                return _LeafEnc(CODEC_NONE)
        return _LeafEnc(cfg.codec_code, feedback=feedback, x_eff=x)

    # ------------------------------------------------------------- encoding

    def _encode_bucket(self, dt: Any) -> None:
        cfg = self.config
        quant_lis = [li for li in self.bucket_leaves[dt] if self.encs[li].code != CODEC_NONE]
        if cfg.codec == "int8" and len(quant_lis) > BUCKET_SCALE_SLOTS:
            # more candidates than the int8 block pool holds: the smallest
            # leaves ship exact (deterministic demotion — peers see it via
            # the per-leaf codec announcements, nothing to agree on). bf16
            # needs no scale slots, so it never demotes.
            by_size = sorted(
                quant_lis, key=lambda li: (-int(self.encs[li].x_eff.shape[0]), li)
            )
            for li in by_size[BUCKET_SCALE_SLOTS:]:
                self.encs[li] = _LeafEnc(CODEC_NONE)
            quant_lis = by_size[:BUCKET_SCALE_SLOTS]
            quant_lis.sort()
        if not quant_lis:
            self.bucket_blocks[dt] = []
            self.bucket_scales[dt] = np.zeros((0,), np.float32)
            self.bucket_zeros[dt] = np.zeros((0,), np.float32)
            return
        if cfg.codec == "bf16":
            for li in quant_lis:
                enc = self.encs[li]
                y = enc.x_eff.astype(jnp.bfloat16)
                self.payloads[li] = to_bytes(y)
                if enc.feedback:
                    enc.new_residual = np.asarray(
                        enc.x_eff - y.astype(enc.x_eff.dtype), np.float32
                    )
            self.bucket_blocks[dt] = []
            self.bucket_scales[dt] = np.zeros((0,), np.float32)
            self.bucket_zeros[dt] = np.zeros((0,), np.float32)
            return
        counts = [int(self.encs[li].x_eff.shape[0]) for li in quant_lis]
        blocks = allocate_blocks(counts, BUCKET_SCALE_SLOTS)
        scales: List[np.ndarray] = []
        zeros: List[np.ndarray] = []
        for li, nb in zip(quant_lis, blocks):
            enc = self.encs[li]
            q, s, z = block_quantize(enc.x_eff, nb)
            self.payloads[li] = q
            scales.append(s)
            zeros.append(z)
            if enc.feedback:
                deq = block_dequantize(q, s, z, int(enc.x_eff.shape[0]), enc.x_eff.dtype)
                enc.new_residual = np.asarray(enc.x_eff - deq, np.float32)
        self.bucket_blocks[dt] = blocks
        self.bucket_scales[dt] = np.concatenate(scales) if scales else np.zeros((0,), np.float32)
        self.bucket_zeros[dt] = np.concatenate(zeros) if zeros else np.zeros((0,), np.float32)

    def leaf_code(self, li: int) -> int:
        return self.encs[li].code

    # -------------------------------------------------------------- commit

    def commit(self, world: int) -> Dict[str, Any]:
        """Install the candidate residuals (the sync succeeded end to end) and
        return the local compression stats. World-of-one syncs never shipped a
        compressed byte, so nothing commits."""
        stats = {"leaves_quantized": 0, "feedback_leaves": 0}
        if world <= 1:
            return stats
        updates: Dict[str, np.ndarray] = {}
        for leaf, enc in zip(self.leaves, self.encs):
            if enc.code == CODEC_NONE:
                continue
            stats["leaves_quantized"] += 1
            if enc.new_residual is not None:
                updates[self.config._residual_key(leaf.state_idx, leaf.name)] = enc.new_residual
                stats["feedback_leaves"] += 1
        if updates:
            self.config._commit_residuals(updates)
        return stats


def f32_bits(values: np.ndarray) -> np.ndarray:
    """f32 vector -> int32 bit patterns (the metadata vector is int32)."""
    return np.asarray(values, np.float32).view(np.int32)


def bits_f32(values: Sequence[int]) -> np.ndarray:
    """Inverse of :func:`f32_bits` (tolerates the decoder's int64 upcast)."""
    return np.asarray(list(values), np.int64).astype(np.int32).view(np.float32)


# ---------------------------------------------------------------------------
# tenant-spill codec (serving/engine.py LRU spill payloads)
# ---------------------------------------------------------------------------

_SPILL_MARK = "__codec__"


def _np_block_quantize(x: np.ndarray, n_blocks: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pure-numpy mirror of :func:`block_quantize` — the spill path exists to
    relieve device pressure, so its codec must never bounce the just-read
    host rows back through the accelerator."""
    x = np.ravel(x)
    count = x.size
    bl = _block_edges(count, n_blocks)
    xp = np.pad(x, (0, n_blocks * bl - count), mode="edge").reshape(n_blocks, bl)
    mn = xp.min(axis=1)
    mx = xp.max(axis=1)
    rng = mx - mn
    scale = np.where(rng > 0, rng / 255.0, 1.0).astype(np.float32)
    q = np.clip(
        np.round((xp - mn[:, None]) / scale[:, None].astype(xp.dtype)), 0, 255
    ).astype(np.uint8)
    return q.ravel()[:count], scale, mn.astype(np.float32)


def _np_block_dequantize(
    q: np.ndarray, scale: np.ndarray, zero: np.ndarray, count: int, dtype: Any
) -> np.ndarray:
    n_blocks = len(scale)
    bl = _block_edges(count, n_blocks)
    qp = np.pad(
        np.ravel(q).astype(np.float32), (0, n_blocks * bl - count)
    ).reshape(n_blocks, bl)
    x = qp * scale[:, None] + zero[:, None]
    return x.ravel()[:count].astype(dtype)


def encode_spill_state(state: Dict[str, Any], codec: str) -> Dict[str, Any]:
    """Compress one spilled tenant's host state rows — pure numpy, no device
    round-trip. Float32/float64 leaves compress under ``codec``; everything
    else (int/bool counts, sub-f32 floats) stays raw — count states must
    survive spill/readmit bitwise. Each spill→readmit cycle is one bounded
    quantization round-trip (no error feedback: spill is storage, not an
    additive fold)."""
    if codec == "none":
        return dict(state)
    out: Dict[str, Any] = {}
    for name, value in state.items():
        arr = np.asarray(value)
        if arr.dtype not in (np.float32, np.float64) or arr.size < 32:
            # tiny leaves: the scale/shape envelope would cost more than the
            # quantization saves (and zero-size leaves have nothing to save)
            out[name] = arr
            continue
        if codec == "bf16":
            out[name] = {
                _SPILL_MARK: "bf16",
                "q": arr.astype(np.dtype(jnp.bfloat16)),  # ml_dtypes numpy cast
                "dtype": arr.dtype.str,
                "shape": arr.shape,
            }
        else:  # int8
            n_blocks = min(MIN_SCALE_SLOTS, max(1, arr.size // 64 or 1))
            q, s, z = _np_block_quantize(arr, n_blocks)
            out[name] = {
                _SPILL_MARK: "int8",
                "q": q,
                "scale": s,
                "zero": z,
                "dtype": arr.dtype.str,
                "shape": arr.shape,
            }
    return out


def decode_spill_state(state: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Decompress a (possibly codec-encoded) spilled state back to raw host
    arrays (pure numpy). Raw states pass through untouched, so every reader
    handles both."""
    out: Dict[str, np.ndarray] = {}
    for name, value in state.items():
        if isinstance(value, dict) and _SPILL_MARK in value:
            dtype = np.dtype(value["dtype"])
            shape = tuple(value["shape"])
            if value[_SPILL_MARK] == "bf16":
                out[name] = np.asarray(value["q"]).astype(dtype).reshape(shape)
            else:
                count = int(np.prod(shape)) if shape else 1
                out[name] = _np_block_dequantize(
                    value["q"], value["scale"], value["zero"], count, dtype
                ).reshape(shape)
        else:
            out[name] = np.asarray(value)
    return out


def spill_state_bytes(state: Dict[str, Any]) -> int:
    """Host bytes a (possibly encoded) spilled state actually occupies —
    metadata only (shape x itemsize of what is stored, scales included)."""
    total = 0
    for value in state.values():
        if isinstance(value, dict) and _SPILL_MARK in value:
            for part in ("q", "scale", "zero"):
                arr = value.get(part)
                if arr is not None:
                    a = np.asarray(arr)
                    total += int(a.size) * a.dtype.itemsize
        else:
            a = np.asarray(value)
            total += int(a.size) * a.dtype.itemsize
    return total
