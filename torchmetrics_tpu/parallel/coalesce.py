"""Coalesced sync plane — bucketed single-collective state synchronization.

The per-leaf sync model (SURVEY §2.12, ``parallel/sync.py``) launches one
collective per state leaf: a ``MetricCollection`` with K members × L leaves pays
K·L collectives per sync, each with full launch latency. At metric-state scale
the payloads are tiny (a handful of scalars and small vectors), so sync cost is
dominated by per-leaf dispatch, not bytes — the classic case for bucketing many
small cross-replica reductions into few large collectives (DrJAX, EQuARX).

This module coalesces both host-driven planes and the in-graph plane:

- **In-graph** (:func:`reduce_many`): all fixed-shape leaves of one or many
  state dicts are raveled and concatenated into per-(reduction-class × dtype)
  flat buckets — one ``lax.psum`` for sum/mean buckets (mean divides by the
  static axis size afterwards), one ``lax.pmax``/``pmin``, and one
  ``lax.all_gather`` per dtype for cat/custom leaves (each leaf's slice is
  reshaped back to ``(world, *shape)`` so cat concatenation and custom
  reductions see exactly what the per-leaf collective produced).

- **Cross-process** (:func:`coalesced_process_sync`): ONE up-front
  shape-metadata gather describes every leaf of every participating metric
  (replacing the per-leaf shape round-trip inside ``gather_all_arrays``),
  then ONE padded ``process_allgather`` per dtype bucket ships all leaves of
  that dtype at once — uneven cat lengths across ranks are absorbed by the
  metadata-driven padding/trimming. The per-leaf **merge semantics are
  preserved exactly**: the gathered flat rows are split back into the same
  per-(rank, leaf) arrays the per-leaf plane would have produced and folded
  through the same ``_fold_gathered``/list-filter logic, so bucketed results
  are bitwise identical to per-leaf results. Weighted-mean weight states are
  ordinary ``"sum"`` leaves and ride the same sum bucket as their values.

**Per-leaf fallback**: when the gathered metadata cannot be decoded
consistently (e.g. an injected ``dist_sync_fn`` that rewrites payload values,
or ranks disagreeing on the leaf table), :class:`CoalesceFallback` is raised
and the caller re-runs the per-leaf plane. The decision is made from the
*gathered* rows, which every rank sees identically, so a real fleet always
falls back in lockstep — collectives never desynchronize. Transient infra
errors are NOT converted to fallbacks; they propagate to the retry layer
(``FlakyGather`` + ``RetryPolicy`` behave exactly as on the per-leaf plane,
and no state is mutated until every bucket has gathered, so a faulty bucketed
gather leaves the caller at its last good state).

**Fleet-counter piggyback**: the metadata collective reserves a fixed section
for the telemetry counters vector (:data:`~torchmetrics_tpu.observability.
counters.COUNTER_FIELDS`, shipped as 31-bit halves like
``gather_metadata_vector``). Every coalesced sync therefore refreshes a
process-local mailbox of per-rank counter rows for free;
``observability.gather_counters`` consumes it so a fleet
``summary(fleet=True)`` after a sync adds zero extra collectives.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _observability
from ..observability.counters import COUNTER_FIELDS
from ..observability.histograms import FLEET_VECTOR_LEN as _HIST_VEC_LEN

Array = jax.Array
Reduction = Union[str, Callable, None]

_MAX_RANK = 8
# shared dtype table (parallel/sync.py aliases this as _GATHER_DTYPES)
GATHER_DTYPES = (
    jnp.float32, jnp.float64, jnp.int32, jnp.int64,
    jnp.bfloat16, jnp.float16, jnp.uint8, jnp.bool_,
)

_MAGIC = 0x436F414C  # "CoAL"
# v2: the reserved telemetry tail grew a fixed histogram section (per-kind
# latency/size totals — observability/histograms.py) after the counter halves
# v3: the counter vector gained the aot_cache_* fields and the histogram
# section the aot_load kind (PR 6) — both tails grew, so mixed-version ranks
# must fail validation rather than misparse each other's rows
# v4: the counter vector gained the serving-engine fields (serve_* /
# tenant_*) — same mixed-version rule
# v5: the counter vector gained the streaming-plane fields (window_rolls /
# async_sync* / drift_* / serve_rejected). Mixed-version ranks fail row
# validation (CoalesceFallback → lockstep per-leaf sync) and deposit NO
# mailbox rows, so fleet rollups degrade to a fresh collective / local
# rollup instead of misdecoding another version's half-packed layout
# v6: tiered windows — the counter vector gained window_rotations and the
# fleet histogram vector gained the wdual/wstack dispatch kinds
_VERSION = 6
_HEADER_LEN = 4  # [magic, version, n_leaves, n_counter_fields]
_LEAF_REC_LEN = 2 + _MAX_RANK + 1  # [dtype_code, ndim, d0..d7, kind]
_KIND_TENSOR = 0
_KIND_LIST = 1

# dtype sentinels inside the metadata collective (mirrors gather_all_arrays:
# announcing problems IN the collective keeps every rank unblocked, then all
# ranks raise the same error together)
_CODE_EMPTY = -1  # zero-update list state: no data, dtype unknown on this rank
_CODE_UNSUPPORTED = -2
_CODE_RANK_OVERFLOW = -3
_CODE_DIM_OVERFLOW = -4  # a dimension does not fit the int32 metadata encoding


class CoalesceFallback(Exception):
    """Internal control flow: the gathered metadata could not be decoded into a
    consistent world plan — the caller must re-run the per-leaf plane. Never
    raised for transient infra errors (those propagate to the retry layer)."""


# ---------------------------------------------------------------------------
# leaf table
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Leaf:
    state_idx: int
    name: str
    fx: Reduction
    is_list: bool
    array: Optional[Any]  # list states pre-concatenated; None == no data
    original: Any


def _dtype_code(arr: Any) -> int:
    dt = jnp.dtype(arr.dtype)
    for i, cand in enumerate(GATHER_DTYPES):
        if dt == jnp.dtype(cand):
            return i
    return _CODE_UNSUPPORTED


def _prepare_leaves(
    states: Sequence[Dict[str, Any]], reductions_list: Sequence[Mapping[str, Reduction]]
) -> List[_Leaf]:
    """Ordered leaf table over one or many state dicts. List ("cat") states are
    pre-concatenated exactly like the per-leaf plane does before gathering."""
    leaves: List[_Leaf] = []
    for si, (state, reds) in enumerate(zip(states, reductions_list)):
        for name, value in state.items():
            fx = reds.get(name)
            if isinstance(value, list):
                arr = (
                    jnp.concatenate([jnp.atleast_1d(jnp.asarray(v)) for v in value], axis=0)
                    if value
                    else None
                )
                leaves.append(_Leaf(si, name, fx, True, arr, value))
            else:
                leaves.append(_Leaf(si, name, fx, False, jnp.asarray(value), value))
    return leaves


def build_local_metadata(
    states: Sequence[Dict[str, Any]],
    reductions_list: Sequence[Mapping[str, Reduction]],
    counters_vector: Optional[Sequence[int]] = None,
    hist_vector: Optional[Sequence[int]] = None,
) -> np.ndarray:
    """This rank's metadata row: leaf shapes/dtypes plus the (always-reserved)
    telemetry counters + histogram sections, as one int32 vector. Fixed length
    across ranks for a given leaf table — the collective needs no shape
    negotiation."""
    return _encode_metadata(_prepare_leaves(states, reductions_list), counters_vector, hist_vector)


def _pack_halves(dest: np.ndarray, values: Sequence[int]) -> None:
    """31-bit (hi, lo) int32 halves — same encoding as
    ``gather_metadata_vector`` (int64 would silently downcast under jax's
    default x64-disabled config)."""
    vals = [int(v) for v in values]
    dest[0::2] = [v >> 31 for v in vals]
    dest[1::2] = [v & 0x7FFFFFFF for v in vals]


def unpack_halves(halves: Sequence[int]) -> List[int]:
    """Inverse of :func:`_pack_halves` — the single decode both piggyback row
    kinds and ``gather_metadata_vector`` share."""
    return [(int(hi) << 31) | int(lo) for hi, lo in zip(halves[0::2], halves[1::2])]


def _encode_metadata(
    leaves: Sequence[_Leaf],
    counters_vector: Optional[Sequence[int]],
    hist_vector: Optional[Sequence[int]] = None,
) -> np.ndarray:
    n_fields = len(COUNTER_FIELDS)
    vec = np.zeros(
        _HEADER_LEN + len(leaves) * _LEAF_REC_LEN + 2 * n_fields + 2 * _HIST_VEC_LEN,
        np.int32,
    )
    vec[0], vec[1], vec[2], vec[3] = _MAGIC, _VERSION, len(leaves), n_fields
    for i, leaf in enumerate(leaves):
        rec = vec[_HEADER_LEN + i * _LEAF_REC_LEN :]
        if leaf.array is None:
            rec[0], rec[1] = _CODE_EMPTY, 1  # zero-length; peers decide the rest
        else:
            arr = leaf.array
            if arr.ndim > _MAX_RANK:
                rec[0], rec[1] = _CODE_RANK_OVERFLOW, 1
            elif any(s >= 1 << 31 for s in arr.shape):
                # announced INSIDE the collective (like the other sentinels):
                # a local pre-gather fallback would desynchronize the fleet —
                # this way every rank sees the overflow and falls back together
                rec[0], rec[1] = _CODE_DIM_OVERFLOW, 1
            else:
                rec[0] = _dtype_code(arr)
                rec[1] = arr.ndim
                for d, s in enumerate(arr.shape):
                    rec[2 + d] = s
        rec[2 + _MAX_RANK] = _KIND_LIST if leaf.is_list else _KIND_TENSOR
    tail_at = _HEADER_LEN + len(leaves) * _LEAF_REC_LEN
    if counters_vector is not None:
        vals = [int(v) for v in counters_vector]
        if len(vals) != n_fields:
            raise ValueError(f"counters vector must have {n_fields} entries, got {len(vals)}")
        _pack_halves(vec[tail_at : tail_at + 2 * n_fields], vals)
    if hist_vector is not None:
        vals = [int(v) for v in hist_vector]
        if len(vals) != _HIST_VEC_LEN:
            raise ValueError(f"histogram vector must have {_HIST_VEC_LEN} entries, got {len(vals)}")
        _pack_halves(vec[tail_at + 2 * n_fields :], vals)
    return vec


# ---------------------------------------------------------------------------
# world plan (decoded from the gathered metadata rows)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _LeafPlan:
    dtype: Any  # resolved np/jnp dtype; None == every rank empty (leaf skipped)
    dims: List[Tuple[int, ...]]  # per-rank shapes (empty ranks: zero-length)
    counts: List[int]  # per-rank element counts


@dataclasses.dataclass
class _WorldPlan:
    world: int
    leaf_plans: List[_LeafPlan]
    buckets: "Dict[Any, List[int]]"  # dtype -> leaf indices, first-appearance order
    counter_rows: List[List[int]]  # per-rank counters decoded from the piggyback
    hist_rows: List[List[int]]  # per-rank fleet histogram vectors, same piggyback


def _decode_rows(rows: Sequence[Any], n_leaves: int) -> List[np.ndarray]:
    decoded = []
    expect_len = _HEADER_LEN + n_leaves * _LEAF_REC_LEN + 2 * len(COUNTER_FIELDS) + 2 * _HIST_VEC_LEN
    for row in rows:
        arr = np.asarray(row).ravel()
        if arr.size != expect_len or not np.issubdtype(arr.dtype, np.integer):
            raise CoalesceFallback("metadata row has unexpected length/dtype")
        if int(arr[0]) != _MAGIC or int(arr[1]) != _VERSION or int(arr[2]) != n_leaves:
            raise CoalesceFallback("metadata row failed validation")
        decoded.append(arr.astype(np.int64))
    return decoded


def _plan_from_rows(rows: Sequence[Any], leaves: Sequence[_Leaf]) -> _WorldPlan:
    decoded = _decode_rows(rows, len(leaves))
    world = len(decoded)
    leaf_plans: List[_LeafPlan] = []
    buckets: Dict[Any, List[int]] = {}
    for i, leaf in enumerate(leaves):
        recs = [row[_HEADER_LEN + i * _LEAF_REC_LEN :][: _LEAF_REC_LEN] for row in decoded]
        kinds = {int(r[2 + _MAX_RANK]) for r in recs}
        if kinds != {_KIND_LIST if leaf.is_list else _KIND_TENSOR}:
            raise CoalesceFallback("ranks disagree on the leaf kind table")
        codes = sorted({int(r[0]) for r in recs})
        if _CODE_DIM_OVERFLOW in codes:
            # the per-leaf plane's int64 shape vector CAN express this — fall
            # back (lockstep: every rank sees the sentinel in the same rows)
            raise CoalesceFallback("a leaf dimension does not fit the metadata encoding")
        if _CODE_RANK_OVERFLOW in codes:
            raise ValueError(f"coalesced sync supports rank <= {_MAX_RANK} state leaves")
        known = [c for c in codes if c >= 0]
        if _CODE_UNSUPPORTED in codes:
            raise ValueError(
                f"coalesced sync got an unsupported dtype on at least one process; supported: "
                f"{[str(jnp.dtype(d)) for d in GATHER_DTYPES]}"
            )
        if len(known) > 1:
            raise ValueError(
                "coalesced sync requires the same dtype on every process, got "
                f"{[str(jnp.dtype(GATHER_DTYPES[c])) for c in known]}"
            )
        if not known:  # every rank empty: leaf keeps its local value
            leaf_plans.append(_LeafPlan(None, [(0,)] * world, [0] * world))
            continue
        if any(not 0 <= c < len(GATHER_DTYPES) for c in known):
            raise CoalesceFallback("metadata row carries an invalid dtype code")
        dtype = jnp.dtype(GATHER_DTYPES[known[0]])
        ndims = {int(r[1]) for r in recs if int(r[0]) >= 0}
        if len(ndims) > 1:
            raise ValueError(
                f"coalesced sync requires equal ranks across processes, got {sorted(ndims)}"
            )
        ndim = ndims.pop()
        if not 0 <= ndim <= _MAX_RANK:
            raise CoalesceFallback("metadata row carries an invalid ndim")
        template = next(
            tuple(int(d) for d in r[2 : 2 + ndim]) for r in recs if int(r[0]) >= 0
        )
        dims: List[Tuple[int, ...]] = []
        for r in recs:
            if int(r[0]) >= 0:
                shape = tuple(int(d) for d in r[2 : 2 + ndim])
                if any(d < 0 for d in shape):
                    raise CoalesceFallback("metadata row carries a negative dimension")
                dims.append(shape)
            else:  # empty contributor: zero length, peers' trailing dims
                dims.append((0,) + template[1:] if ndim else ())
        # empty contributors hold zero elements regardless of trailing dims
        counts = [
            0 if int(r[0]) < 0 else (int(np.prod(d)) if d else 1)
            for r, d in zip(recs, dims)
        ]
        leaf_plans.append(_LeafPlan(dtype, dims, counts))
        buckets.setdefault(dtype, []).append(i)
    counter_rows = []
    hist_rows = []
    tail_at = _HEADER_LEN + len(leaves) * _LEAF_REC_LEN
    hist_at = tail_at + 2 * len(COUNTER_FIELDS)
    for row in decoded:
        counter_rows.append(unpack_halves(row[tail_at:hist_at]))
        hist_rows.append(unpack_halves(row[hist_at:]))
    return _WorldPlan(
        world=world, leaf_plans=leaf_plans, buckets=buckets,
        counter_rows=counter_rows, hist_rows=hist_rows,
    )


def build_bucket_payload(
    states: Sequence[Dict[str, Any]],
    reductions_list: Sequence[Mapping[str, Reduction]],
    bucket_index: int,
    metadata_rows: Sequence[Any],
) -> Array:
    """This rank's padded flat payload for bucket ``bucket_index`` under the
    gathered ``metadata_rows`` — the replay API that lets a test fake simulate
    each rank of a world deterministically."""
    leaves = _prepare_leaves(states, reductions_list)
    plan = _plan_from_rows(metadata_rows, leaves)
    dtype = list(plan.buckets)[bucket_index]
    return _local_bucket_flat(leaves, plan, dtype)


def _local_bucket_flat(leaves: Sequence[_Leaf], plan: _WorldPlan, dtype: Any) -> Array:
    parts = []
    for li in plan.buckets[dtype]:
        leaf = leaves[li]
        if leaf.array is None:
            continue  # zero elements — nothing to ship
        parts.append(jnp.ravel(jnp.asarray(leaf.array)))
    flat = (
        jnp.concatenate(parts) if parts else jnp.zeros((0,), dtype)
    ).astype(dtype)
    totals = [
        sum(plan.leaf_plans[li].counts[r] for li in plan.buckets[dtype])
        for r in range(plan.world)
    ]
    pad = max(totals) - int(flat.shape[0])
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), dtype)])
    return flat


# ---------------------------------------------------------------------------
# cross-process coalesced sync (plane 2)
# ---------------------------------------------------------------------------


def process_rows(value: Any) -> List[Any]:
    """Per-process rows of one real ``process_allgather`` — normalized for the
    world of one, where process_allgather returns the input UNSTACKED (shared
    by both sync planes; the single place that knows this quirk)."""
    value = jnp.asarray(value)
    if jax.process_count() == 1:
        return [value]
    from jax.experimental import multihost_utils

    stacked = multihost_utils.process_allgather(value, tiled=False)
    return [stacked[i] for i in range(stacked.shape[0])]


def _make_gather(process_group: Any, dist_sync_fn: Optional[Callable]) -> Callable:
    if dist_sync_fn is not None:
        def gather(arr):
            return [jnp.asarray(r) for r in dist_sync_fn(jnp.asarray(arr), process_group)]

        return gather
    return process_rows


def coalesced_process_sync(
    states: Sequence[Dict[str, Any]],
    reductions_list: Sequence[Mapping[str, Reduction]],
    process_group: Any = None,
    dist_sync_fn: Optional[Callable] = None,
) -> List[Dict[str, Any]]:
    """Synchronize one or many state dicts across processes with one metadata
    collective plus one padded gather per dtype bucket.

    Returns new state dicts (inputs untouched — callers commit atomically, so
    any failure leaves every metric at its last good state). Raises
    :class:`CoalesceFallback` when the gathered metadata is unusable; the
    caller then re-runs the per-leaf plane.
    """
    from . import sync as _sync  # lazy: sync.py imports this module at top level

    leaves = _prepare_leaves(states, reductions_list)
    rec = _observability._ACTIVE
    counters_vec = None
    hist_vec = None
    if rec is not None and dist_sync_fn is None:
        counters_vec = rec.counters.counts_vector()
        hist_vec = rec.histograms.fleet_vector()
    meta = _encode_metadata(leaves, counters_vec, hist_vec)
    gather = _make_gather(process_group, dist_sync_fn)
    try:
        rows = gather(meta)  # collective #1: the single up-front shape/metadata gather
    except Exception as err:
        # an injected gather written against the documented per-leaf seam may
        # choke on the metadata vector (asserts on dtype/shape of state leaves)
        # — deterministic failures fall back to the per-leaf plane it was
        # written for. Transient errors (FlakyGather & friends) and anything
        # from a REAL collective still propagate to the retry layer: a local
        # fallback there would desynchronize the fleet / bypass retry.
        from ..reliability.retry import TRANSIENT, classify_exception

        if dist_sync_fn is not None and classify_exception(err) != TRANSIENT:
            raise CoalesceFallback(f"injected gather rejected the metadata vector: {err!r}") from err
        raise
    if rec is not None:  # launch-time counting: fallbacks keep their collectives
        rec.counters.record_sync_collectives(1)
    plan = _plan_from_rows(rows, leaves)
    if dist_sync_fn is None:
        _deposit_fleet_rows(plan, rec)
    per_leaf_gathered: List[Optional[List[Array]]] = [None] * len(leaves)
    for dtype, leaf_idxs in plan.buckets.items():
        flat = _local_bucket_flat(leaves, plan, dtype)
        rows_b = gather(flat)  # one collective serves every leaf of this dtype
        if rec is not None:
            rec.counters.record_sync_collectives(1)
            # payload-size distribution of the bucketed collective (metadata
            # math only) — the few-large-vs-many-small observable of coalescing
            rec.record_gather_payload(
                "coalesced", int(flat.size) * jnp.dtype(flat.dtype).itemsize
            )
        if len(rows_b) != plan.world:
            raise CoalesceFallback("bucket gather returned a different world size than the metadata")
        for r in range(plan.world):
            offset = 0
            row = jnp.asarray(rows_b[r])
            for li in leaf_idxs:
                lp = plan.leaf_plans[li]
                n = lp.counts[r]
                seg = row[offset : offset + n].reshape(lp.dims[r])
                offset += n
                if per_leaf_gathered[li] is None:
                    per_leaf_gathered[li] = []
                per_leaf_gathered[li].append(seg)
    outs = [dict(s) for s in states]
    for leaf, gathered in zip(leaves, per_leaf_gathered):
        if gathered is None:
            continue  # every rank empty: keep the local value (per-leaf semantics)
        if leaf.is_list:
            vals = [g for g in gathered if g.shape[0] > 0]
            outs[leaf.state_idx][leaf.name] = vals or leaf.original
        else:
            outs[leaf.state_idx][leaf.name] = _sync._fold_gathered(gathered, leaf.fx)
    if rec is not None:
        rec.counters.record_coalesced(sum(1 for g in per_leaf_gathered if g is not None))
    return outs


# ---------------------------------------------------------------------------
# fleet-counter piggyback mailbox
# ---------------------------------------------------------------------------

_FLEET_MAILBOX: Dict[str, Any] = {
    "session_epoch": None, "rows": None, "hist_rows": None, "local_index": None,
}


def _deposit_fleet_rows(plan: _WorldPlan, rec: Any) -> None:
    if rec is None:
        return
    # keyed on the session EPOCH, not id(rec): a dead recorder's id can be
    # reused by the next allocation, which would leak stale rows cross-session
    _FLEET_MAILBOX["session_epoch"] = getattr(rec, "_epoch", None)
    _FLEET_MAILBOX["rows"] = [list(r) for r in plan.counter_rows]
    _FLEET_MAILBOX["hist_rows"] = [list(r) for r in plan.hist_rows]
    _FLEET_MAILBOX["local_index"] = jax.process_index()


def _fleet_rows(field: str, row_len: int) -> Optional[Tuple[List[List[int]], int]]:
    """Shared mailbox-validity discipline for both piggybacked row kinds:
    rows exist, belong to the ACTIVE session's epoch, and have the expected
    vector length — else ``None`` (the caller launches a fresh collective)."""
    rec = _observability._ACTIVE
    if (
        rec is None
        or _FLEET_MAILBOX[field] is None
        or _FLEET_MAILBOX["session_epoch"] is None
        or _FLEET_MAILBOX["session_epoch"] != getattr(rec, "_epoch", None)
    ):
        return None
    rows = _FLEET_MAILBOX[field]
    if any(len(r) != row_len for r in rows):
        return None
    return [list(r) for r in rows], int(_FLEET_MAILBOX["local_index"])


def fleet_counter_rows() -> Optional[Tuple[List[List[int]], int]]:
    """Per-rank counter rows captured by the last coalesced sync's metadata
    collective, plus this process's index — or ``None`` when no coalesced sync
    ran under the currently active telemetry session. Remote rows are as of
    each rank's last sync (a rank without an active session contributes
    zeros); the consumer replaces the local row with a fresh snapshot."""
    return _fleet_rows("rows", len(COUNTER_FIELDS))


def fleet_histogram_rows() -> Optional[Tuple[List[List[int]], int]]:
    """Per-rank fleet histogram vectors captured by the last coalesced sync's
    metadata collective (same mailbox discipline as :func:`fleet_counter_rows`:
    keyed to the active session's epoch, local row to be refreshed by the
    consumer) — or ``None`` when no coalesced sync ran under this session."""
    return _fleet_rows("hist_rows", _HIST_VEC_LEN)


def clear_fleet_mailbox() -> None:
    _FLEET_MAILBOX.update(
        {"session_epoch": None, "rows": None, "hist_rows": None, "local_index": None}
    )


def gather_host_rows(
    vector: Any, process_group: Any = None, dist_sync_fn: Optional[Callable] = None
) -> List[np.ndarray]:
    """One-collective gather of a fixed-length host metadata vector (equal
    length on every rank by contract — no shape negotiation, unlike
    ``gather_all_arrays``' two-collective shape-then-payload dance)."""
    gather = _make_gather(process_group, dist_sync_fn)
    return [np.asarray(r) for r in gather(np.asarray(vector))]


# ---------------------------------------------------------------------------
# in-graph bucketed reduction (plane 1)
# ---------------------------------------------------------------------------

_NUMERIC_CLASS = {"sum": "sum", "mean": "sum", "max": "max", "min": "min"}
_NUMERIC_OP = {"sum": jax.lax.psum, "max": jax.lax.pmax, "min": jax.lax.pmin}


def reduce_many(
    pairs: Sequence[Tuple[Dict[str, Any], Mapping[str, Reduction]]],
    axis_name: Union[str, Sequence[str]],
) -> List[Dict[str, Any]]:
    """Reduce every leaf of one or many state dicts across a named mesh axis
    with one collective per (reduction-class × dtype) bucket. Call inside
    ``shard_map``; shapes are static so no metadata exchange is needed.

    Produces exactly what the per-leaf ``reduce_over_axis`` would: psum/pmax/
    pmin are elementwise, so reducing the concatenated flat bucket and slicing
    back is bitwise identical; cat/custom leaves are recovered from their
    all-gathered slice as ``(world, *shape)`` before tiling/applying ``fx``.
    """
    outs = [dict(s) for s, _ in pairs]
    numeric: Dict[Tuple[str, Any], List[Tuple[int, str, Any, Reduction]]] = {}
    gathered: Dict[Any, List[Tuple[int, str, Any, Reduction, str]]] = {}
    for pi, (state, reds) in enumerate(pairs):
        for name, value in state.items():
            fx = reds.get(name)
            if fx is None:
                continue  # passthrough (per-leaf semantics)
            if callable(fx):
                gathered.setdefault(jnp.asarray(value).dtype, []).append(
                    (pi, name, jnp.asarray(value), fx, "custom")
                )
            elif fx in _NUMERIC_CLASS:
                arr = jnp.asarray(value)
                numeric.setdefault((_NUMERIC_CLASS[fx], arr.dtype), []).append((pi, name, arr, fx))
            elif fx == "cat":
                arr = jnp.atleast_1d(jnp.asarray(value))
                gathered.setdefault(arr.dtype, []).append((pi, name, arr, fx, "cat"))
            else:
                raise ValueError(f"Unknown dist_reduce_fx: {fx!r}")
    axis_size = None
    for (cls, dtype), leaves in numeric.items():
        flat = jnp.concatenate([jnp.ravel(arr) for _, _, arr, _ in leaves])
        red = _NUMERIC_OP[cls](flat, axis_name)
        offset = 0
        for pi, name, arr, fx in leaves:
            n = int(np.prod(arr.shape)) if arr.shape else 1
            seg = red[offset : offset + n].reshape(arr.shape)
            offset += n
            if fx == "mean":
                if axis_size is None:
                    axis_size = jax.lax.psum(1, axis_name)  # static: constant-folded
                seg = seg / axis_size
            outs[pi][name] = seg
    for dtype, leaves in gathered.items():
        flat = jnp.concatenate([jnp.ravel(arr) for _, _, arr, _, _ in leaves])
        g = jax.lax.all_gather(flat, axis_name, axis=0, tiled=False)  # (world, L)
        world = g.shape[0]
        offset = 0
        for pi, name, arr, fx, mode in leaves:
            n = int(np.prod(arr.shape)) if arr.shape else 1
            seg = g[:, offset : offset + n].reshape((world,) + arr.shape)
            offset += n
            if mode == "cat":
                outs[pi][name] = seg.reshape((world * arr.shape[0],) + arr.shape[1:])
            else:
                outs[pi][name] = fx(seg)
    return outs


def collective_counts(
    states: Sequence[Dict[str, Any]], reductions_list: Sequence[Mapping[str, Reduction]]
) -> Dict[str, int]:
    """Static collective-count model for a sync of these states: how many
    collectives each plane launches, coalesced vs per-leaf (for benches/docs —
    no communication happens here)."""
    in_graph_buckets: set = set()
    process_buckets: set = set()
    n_leaves = 0
    per_leaf_in_graph = 0
    for state, reds in zip(states, reductions_list):
        for name, value in state.items():
            fx = reds.get(name)
            n_leaves += 1
            if isinstance(value, list):
                arr = jnp.asarray(value[0]) if value else None
            else:
                arr = jnp.asarray(value)
            if arr is not None:
                process_buckets.add(str(arr.dtype))
            if fx is None:
                continue
            per_leaf_in_graph += 1
            if callable(fx) or fx == "cat":
                in_graph_buckets.add(("gather", str(arr.dtype) if arr is not None else "?"))
            else:
                in_graph_buckets.add((_NUMERIC_CLASS[fx], str(arr.dtype)))
    return {
        "leaves": n_leaves,
        "in_graph_coalesced": len(in_graph_buckets),
        "in_graph_per_leaf": per_leaf_in_graph,
        "process_coalesced": 1 + len(process_buckets),  # metadata + one per dtype
        # gather_all_arrays pays a shape exchange + a payload gather per leaf
        "process_per_leaf": 2 * n_leaves,
    }
