"""Coalesced sync plane — bucketed single-collective state synchronization.

The per-leaf sync model (SURVEY §2.12, ``parallel/sync.py``) launches one
collective per state leaf: a ``MetricCollection`` with K members × L leaves pays
K·L collectives per sync, each with full launch latency. At metric-state scale
the payloads are tiny (a handful of scalars and small vectors), so sync cost is
dominated by per-leaf dispatch, not bytes — the classic case for bucketing many
small cross-replica reductions into few large collectives (DrJAX, EQuARX).

This module coalesces both host-driven planes and the in-graph plane:

- **In-graph** (:func:`reduce_many`): all fixed-shape leaves of one or many
  state dicts are raveled and concatenated into per-(reduction-class × dtype)
  flat buckets — one ``lax.psum`` for sum/mean buckets (mean divides by the
  static axis size afterwards), one ``lax.pmax``/``pmin``, and one
  ``lax.all_gather`` per dtype for cat/custom leaves (each leaf's slice is
  reshaped back to ``(world, *shape)`` so cat concatenation and custom
  reductions see exactly what the per-leaf collective produced).

- **Cross-process** (:func:`coalesced_process_sync`): ONE up-front
  shape-metadata gather describes every leaf of every participating metric
  (replacing the per-leaf shape round-trip inside ``gather_all_arrays``),
  then ONE padded ``process_allgather`` per dtype bucket ships all leaves of
  that dtype at once — uneven cat lengths across ranks are absorbed by the
  metadata-driven padding/trimming. The per-leaf **merge semantics are
  preserved exactly**: the gathered flat rows are split back into the same
  per-(rank, leaf) arrays the per-leaf plane would have produced and folded
  through the same ``_fold_gathered``/list-filter logic, so bucketed results
  are bitwise identical to per-leaf results. Weighted-mean weight states are
  ordinary ``"sum"`` leaves and ride the same sum bucket as their values.

**Per-leaf fallback**: when the gathered metadata cannot be decoded
consistently (e.g. an injected ``dist_sync_fn`` that rewrites payload values,
or ranks disagreeing on the leaf table), :class:`CoalesceFallback` is raised
and the caller re-runs the per-leaf plane. The decision is made from the
*gathered* rows, which every rank sees identically, so a real fleet always
falls back in lockstep — collectives never desynchronize. Transient infra
errors are NOT converted to fallbacks; they propagate to the retry layer
(``FlakyGather`` + ``RetryPolicy`` behave exactly as on the per-leaf plane,
and no state is mutated until every bucket has gathered, so a faulty bucketed
gather leaves the caller at its last good state).

**Fleet-counter piggyback**: the metadata collective reserves a fixed section
for the telemetry counters vector (:data:`~torchmetrics_tpu.observability.
counters.COUNTER_FIELDS`, shipped as 31-bit halves like
``gather_metadata_vector``). Every coalesced sync therefore refreshes a
process-local mailbox of per-rank counter rows for free;
``observability.gather_counters`` consumes it so a fleet
``summary(fleet=True)`` after a sync adds zero extra collectives.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _observability
from ..observability.counters import COUNTER_FIELDS
from ..observability.histograms import FLEET_VECTOR_LEN as _HIST_VEC_LEN
from . import quantize as _quantize

Array = jax.Array
Reduction = Union[str, Callable, None]

_MAX_RANK = 8
# shared dtype table (parallel/sync.py aliases this as _GATHER_DTYPES)
GATHER_DTYPES = (
    jnp.float32, jnp.float64, jnp.int32, jnp.int64,
    jnp.bfloat16, jnp.float16, jnp.uint8, jnp.bool_,
)

_MAGIC = 0x436F414C  # "CoAL"
# v2: the reserved telemetry tail grew a fixed histogram section (per-kind
# latency/size totals — observability/histograms.py) after the counter halves
# v3: the counter vector gained the aot_cache_* fields and the histogram
# section the aot_load kind (PR 6) — both tails grew, so mixed-version ranks
# must fail validation rather than misparse each other's rows
# v4: the counter vector gained the serving-engine fields (serve_* /
# tenant_*) — same mixed-version rule
# v5: the counter vector gained the streaming-plane fields (window_rolls /
# async_sync* / drift_* / serve_rejected). Mixed-version ranks fail row
# validation (CoalesceFallback → lockstep per-leaf sync) and deposit NO
# mailbox rows, so fleet rollups degrade to a fresh collective / local
# rollup instead of misdecoding another version's half-packed layout
# v6: tiered windows — the counter vector gained window_rotations and the
# fleet histogram vector gained the wdual/wstack dispatch kinds
# v7: quantized sync plane — the counter vector gained sync_bytes_saved /
# quantized_buckets, each leaf record's kind slot now packs the announced
# codec code in its upper bits (kind = slot & 1, codec = slot >> 1), and a
# quant section (per-bucket block-scale records, parallel/quantize.py) rides
# the metadata tail when the caller passed an enabled SyncConfig
# v8: durability plane — the header grew a per-rank liveness/epoch slot pair
# (alive flag + liveness epoch) and the counter vector the snapshot/journal/
# degraded-sync fields. An ALL-ZERO metadata row is a rank tombstone (a rank
# that died mid-collective contributes zeros to the gather): the plan marks
# it dead, the bucket folds cover the survivor quorum, and the sync completes
# degraded instead of hanging or folding the zero row as data
# v9: fleet failover plane — the counter vector gained the fleet controller
# fields (fleet_heartbeats / lease_expiries / host_failovers /
# tenant_migrations / migration_us). Same mixed-version rule: an older rank's
# shorter vector fails row validation rather than misaligning the new tail
# v10: causal trace plane — the counter vector gained flightrec_dumps (the
# flight recorder's postmortem artifact count rides the fleet rollup)
# v11: telemetry history plane — the counter vector gained history_folds
# (telescoped retention blocks closed) and burn_alerts (multi-window
# burn-rate pages); same mixed-version lockstep-fallback rule as every bump
_VERSION = 11
_HEADER_LEN = 6  # [magic, version, n_leaves, n_counter_fields, alive, epoch]
_LEAF_REC_LEN = 2 + _MAX_RANK + 1  # [dtype_code, ndim, d0..d7, kind|codec<<1]
_KIND_TENSOR = 0
_KIND_LIST = 1

# dtype sentinels inside the metadata collective (mirrors gather_all_arrays:
# announcing problems IN the collective keeps every rank unblocked, then all
# ranks raise the same error together)
_CODE_EMPTY = -1  # zero-update list state: no data, dtype unknown on this rank
_CODE_UNSUPPORTED = -2
_CODE_RANK_OVERFLOW = -3
_CODE_DIM_OVERFLOW = -4  # a dimension does not fit the int32 metadata encoding


class CoalesceFallback(Exception):
    """Internal control flow: the gathered metadata could not be decoded into a
    consistent world plan — the caller must re-run the per-leaf plane. Never
    raised for transient infra errors (those propagate to the retry layer)."""


# ---------------------------------------------------------------------------
# rank liveness (durability plane)
# ---------------------------------------------------------------------------

# this process's liveness epoch, announced in every metadata row. A process
# that restarts (warm-standby failover) bumps it, so peers can tell a rejoin
# from a rank that never died.
_LIVENESS: Dict[str, int] = {"epoch": 1}
# rank index -> consecutive degraded syncs it has been seen dead for. A rank
# present here whose metadata row comes back alive is a REJOIN: its
# accumulated state folds into that very sync (full-state gather), so
# reconciliation needs no transfer of missed deltas and can never double
# count — the fold always covers each survivor's total accumulator exactly
# once.
_DEAD_RANKS: Dict[int, int] = {}


def liveness_epoch() -> int:
    """This process's current liveness epoch (starts at 1)."""
    return _LIVENESS["epoch"]


def bump_liveness_epoch() -> int:
    """Announce a fresh liveness epoch (a restarted / failed-over process
    calls this so peers see its rows as a NEW incarnation)."""
    _LIVENESS["epoch"] += 1
    return _LIVENESS["epoch"]


def dead_ranks() -> Dict[int, int]:
    """Ranks currently tombstoned by the degraded-sync plane (rank index →
    consecutive degraded syncs seen dead)."""
    return dict(_DEAD_RANKS)


def clear_dead_ranks() -> None:
    """Forget all tombstones (test/soak-run isolation)."""
    _DEAD_RANKS.clear()


# ---------------------------------------------------------------------------
# leaf table
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Leaf:
    state_idx: int
    name: str
    fx: Reduction
    is_list: bool
    array: Optional[Any]  # list states pre-concatenated; None == no data
    original: Any


def _dtype_code_of(dt: Any) -> int:
    dt = jnp.dtype(dt)
    for i, cand in enumerate(GATHER_DTYPES):
        if dt == jnp.dtype(cand):
            return i
    return _CODE_UNSUPPORTED


def _dtype_code(arr: Any) -> int:
    return _dtype_code_of(arr.dtype)


def _prepare_leaves(
    states: Sequence[Dict[str, Any]], reductions_list: Sequence[Mapping[str, Reduction]]
) -> List[_Leaf]:
    """Ordered leaf table over one or many state dicts. List ("cat") states are
    pre-concatenated exactly like the per-leaf plane does before gathering."""
    leaves: List[_Leaf] = []
    for si, (state, reds) in enumerate(zip(states, reductions_list)):
        for name, value in state.items():
            fx = reds.get(name)
            if isinstance(value, list):
                arr = (
                    jnp.concatenate([jnp.atleast_1d(jnp.asarray(v)) for v in value], axis=0)
                    if value
                    else None
                )
                leaves.append(_Leaf(si, name, fx, True, arr, value))
            else:
                leaves.append(_Leaf(si, name, fx, False, jnp.asarray(value), value))
    return leaves


def build_local_metadata(
    states: Sequence[Dict[str, Any]],
    reductions_list: Sequence[Mapping[str, Reduction]],
    counters_vector: Optional[Sequence[int]] = None,
    hist_vector: Optional[Sequence[int]] = None,
    sync_config: Optional[Any] = None,
) -> np.ndarray:
    """This rank's metadata row: leaf shapes/dtypes plus the (always-reserved)
    telemetry counters + histogram sections — and, with an enabled
    ``sync_config``, the quant section announcing this rank's per-leaf codec
    decisions and per-bucket block scales — as one int32 vector. Fixed length
    across ranks for a given leaf table — the collective needs no shape
    negotiation."""
    leaves = _prepare_leaves(states, reductions_list)
    qctx = _make_qctx(leaves, sync_config)
    return _encode_metadata(leaves, counters_vector, hist_vector, qctx)


def _make_qctx(leaves: Sequence[_Leaf], sync_config: Optional[Any]) -> Optional[Any]:
    if sync_config is None or not getattr(sync_config, "enabled", False):
        return None
    return _quantize.QuantContext(sync_config, leaves)


def _pack_halves(dest: np.ndarray, values: Sequence[int]) -> None:
    """31-bit (hi, lo) int32 halves — same encoding as
    ``gather_metadata_vector`` (int64 would silently downcast under jax's
    default x64-disabled config)."""
    vals = [int(v) for v in values]
    dest[0::2] = [v >> 31 for v in vals]
    dest[1::2] = [v & 0x7FFFFFFF for v in vals]


def unpack_halves(halves: Sequence[int]) -> List[int]:
    """Inverse of :func:`_pack_halves` — the single decode both piggyback row
    kinds and ``gather_metadata_vector`` share."""
    return [(int(hi) << 31) | int(lo) for hi, lo in zip(halves[0::2], halves[1::2])]


def _quant_record_lens(qctx: Any) -> List[int]:
    """Quant-section record lengths — a FIXED layout: one record per dtype in
    ``quantize.QUANT_SECTION_DTYPES`` (``[codec, n_blocks]``, plus the
    reserved ``(scale, zero)`` slot pairs for int8), whether or not this rank
    currently holds leaves of that dtype. Lengths depend only on the codec
    (rank-agreed config), so the metadata vector length is rank-invariant
    even when empty list leaves hide a dtype on some ranks — the real
    ``process_allgather`` requires equal row shapes."""
    if qctx is None:
        return []
    per = 2 + (2 * _quantize.BUCKET_SCALE_SLOTS if qctx.config.codec == "int8" else 0)
    return [per] * len(_quantize.QUANT_SECTION_DTYPES)


def _encode_metadata(
    leaves: Sequence[_Leaf],
    counters_vector: Optional[Sequence[int]],
    hist_vector: Optional[Sequence[int]] = None,
    qctx: Optional[Any] = None,
) -> np.ndarray:
    n_fields = len(COUNTER_FIELDS)
    quant_lens = _quant_record_lens(qctx)
    quant_len = sum(quant_lens)
    vec = np.zeros(
        _HEADER_LEN + len(leaves) * _LEAF_REC_LEN + 2 * n_fields + 2 * _HIST_VEC_LEN
        + quant_len,
        np.int32,
    )
    vec[0], vec[1], vec[2], vec[3] = _MAGIC, _VERSION, len(leaves), n_fields
    # liveness slot pair (v8): a live rank always announces alive=1 plus its
    # epoch — an all-zero row can therefore ONLY be a dead rank's tombstone
    vec[4], vec[5] = 1, _LIVENESS["epoch"]
    for i, leaf in enumerate(leaves):
        rec = vec[_HEADER_LEN + i * _LEAF_REC_LEN :]
        if leaf.array is None:
            rec[0], rec[1] = _CODE_EMPTY, 1  # zero-length; peers decide the rest
        else:
            arr = leaf.array
            if arr.ndim > _MAX_RANK:
                rec[0], rec[1] = _CODE_RANK_OVERFLOW, 1
            elif any(s >= 1 << 31 for s in arr.shape):
                # announced INSIDE the collective (like the other sentinels):
                # a local pre-gather fallback would desynchronize the fleet —
                # this way every rank sees the overflow and falls back together
                rec[0], rec[1] = _CODE_DIM_OVERFLOW, 1
            else:
                rec[0] = _dtype_code(arr)
                rec[1] = arr.ndim
                for d, s in enumerate(arr.shape):
                    rec[2 + d] = s
        kind = _KIND_LIST if leaf.is_list else _KIND_TENSOR
        codec = qctx.leaf_code(i) if qctx is not None else 0
        rec[2 + _MAX_RANK] = kind | (codec << 1)
    tail_at = _HEADER_LEN + len(leaves) * _LEAF_REC_LEN
    if counters_vector is not None:
        vals = [int(v) for v in counters_vector]
        if len(vals) != n_fields:
            raise ValueError(f"counters vector must have {n_fields} entries, got {len(vals)}")
        _pack_halves(vec[tail_at : tail_at + 2 * n_fields], vals)
    if hist_vector is not None:
        vals = [int(v) for v in hist_vector]
        if len(vals) != _HIST_VEC_LEN:
            raise ValueError(f"histogram vector must have {_HIST_VEC_LEN} entries, got {len(vals)}")
        _pack_halves(vec[tail_at + 2 * n_fields : tail_at + 2 * n_fields + 2 * _HIST_VEC_LEN], vals)
    if qctx is not None:
        at = tail_at + 2 * n_fields + 2 * _HIST_VEC_LEN
        for dt, rec_len in zip(_quantize.QUANT_SECTION_DTYPES, quant_lens):
            vec[at] = qctx.config.codec_code
            blocks = qctx.bucket_blocks.get(jnp.dtype(dt), [])
            vec[at + 1] = sum(blocks)
            if qctx.config.codec == "int8":
                scales = qctx.bucket_scales.get(jnp.dtype(dt), np.zeros((0,), np.float32))
                zeros = qctx.bucket_zeros.get(jnp.dtype(dt), np.zeros((0,), np.float32))
                slots = _quantize.BUCKET_SCALE_SLOTS
                vec[at + 2 : at + 2 + len(scales)] = _quantize.f32_bits(scales)
                vec[at + 2 + slots : at + 2 + slots + len(zeros)] = _quantize.f32_bits(zeros)
            at += rec_len
    return vec


# ---------------------------------------------------------------------------
# world plan (decoded from the gathered metadata rows)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _LeafPlan:
    dtype: Any  # resolved np/jnp dtype; None == every rank empty (leaf skipped)
    dims: List[Tuple[int, ...]]  # per-rank shapes (empty ranks: zero-length)
    counts: List[int]  # per-rank element counts


@dataclasses.dataclass
class _QuantPlan:
    """Decoded quant announcements of every rank (parallel/quantize.py)."""

    codec: str  # the rank-agreed configured codec name
    leaf_codes: List[List[int]]  # [leaf][rank] announced codec code
    # dtype -> per-rank (n_blocks_used, scales f32, zeros f32)
    bucket_scales: Dict[Any, List[Tuple[int, np.ndarray, np.ndarray]]]


@dataclasses.dataclass
class _WorldPlan:
    world: int
    leaf_plans: List[_LeafPlan]
    buckets: "Dict[Any, List[int]]"  # dtype -> leaf indices, first-appearance order
    counter_rows: List[List[int]]  # per-rank counters decoded from the piggyback
    hist_rows: List[List[int]]  # per-rank fleet histogram vectors, same piggyback
    quant: Optional[_QuantPlan] = None
    # per-rank liveness (v8): False = the rank contributed an all-zero
    # tombstone row and the bucket folds skip its (zero) segments
    alive: List[bool] = dataclasses.field(default_factory=list)
    epochs: List[int] = dataclasses.field(default_factory=list)  # 0 for dead ranks


def _decode_rows(rows: Sequence[Any], n_leaves: int, quant_len: int = 0) -> List[Optional[np.ndarray]]:
    decoded: List[Optional[np.ndarray]] = []
    expect_len = (
        _HEADER_LEN + n_leaves * _LEAF_REC_LEN + 2 * len(COUNTER_FIELDS)
        + 2 * _HIST_VEC_LEN + quant_len
    )
    for row in rows:
        arr = np.asarray(row).ravel()
        if arr.size != expect_len or not np.issubdtype(arr.dtype, np.integer):
            raise CoalesceFallback("metadata row has unexpected length/dtype")
        if not arr.any():
            # a rank that died mid-collective contributes all zeros. This must
            # be recognized BEFORE magic validation: a fallback here would
            # re-run the per-leaf plane, which has no tombstone notion and
            # would fold the dead rank's zero payloads as data
            decoded.append(None)
            continue
        if int(arr[0]) != _MAGIC or int(arr[1]) != _VERSION or int(arr[2]) != n_leaves:
            raise CoalesceFallback("metadata row failed validation")
        if int(arr[4]) != 1 or int(arr[5]) < 1:
            raise CoalesceFallback("metadata row carries an invalid liveness slot")
        decoded.append(arr.astype(np.int64))
    if decoded and all(r is None for r in decoded):
        # no survivor quorum — nothing here can complete the sync
        raise CoalesceFallback("every rank's metadata row is a tombstone")
    return decoded


def _plan_from_rows(
    rows: Sequence[Any], leaves: Sequence[_Leaf], qctx: Optional[Any] = None
) -> _WorldPlan:
    quant_lens = _quant_record_lens(qctx)
    decoded = _decode_rows(rows, len(leaves), sum(quant_lens))
    world = len(decoded)
    alive = [row is not None for row in decoded]
    epochs = [0 if row is None else int(row[5]) for row in decoded]
    leaf_plans: List[_LeafPlan] = []
    buckets: Dict[Any, List[int]] = {}
    leaf_codes: List[List[int]] = []
    for i, leaf in enumerate(leaves):
        # a dead rank's leaves decode as EMPTY contributors (count 0, codec 0,
        # the leaf's own kind) so the padding totals and bucket offsets stay
        # well-defined; its zero bucket segments are skipped at fold time
        tomb = np.zeros((_LEAF_REC_LEN,), np.int64)
        tomb[0], tomb[1] = _CODE_EMPTY, 1
        tomb[2 + _MAX_RANK] = _KIND_LIST if leaf.is_list else _KIND_TENSOR
        recs = [
            tomb if row is None else row[_HEADER_LEN + i * _LEAF_REC_LEN :][: _LEAF_REC_LEN]
            for row in decoded
        ]
        kinds = {int(r[2 + _MAX_RANK]) & 1 for r in recs}
        leaf_codes.append([int(r[2 + _MAX_RANK]) >> 1 for r in recs])
        if kinds != {_KIND_LIST if leaf.is_list else _KIND_TENSOR}:
            raise CoalesceFallback("ranks disagree on the leaf kind table")
        # codec announcements must be ones this world could have produced: the
        # configured codec on a quant-capable with-data leaf, 0 everywhere
        # else — a corrupt (or buggy future-peer) row degrades to the exact
        # per-leaf plane in lockstep rather than mis-slicing a bucket
        cfg_code = qctx.config.codec_code if qctx is not None else 0
        for code, r in zip(leaf_codes[-1], recs):
            quantizable = (
                cfg_code != 0
                and int(r[0]) >= 0
                and int(r[0]) < len(GATHER_DTYPES)
                and jnp.dtype(GATHER_DTYPES[int(r[0])])
                in (jnp.dtype(jnp.float32), jnp.dtype(jnp.float64))
            )
            if code not in ((0, cfg_code) if quantizable else (0,)):
                raise CoalesceFallback("leaf record carries an impossible codec announcement")
        codes = sorted({int(r[0]) for r in recs})
        if _CODE_DIM_OVERFLOW in codes:
            # the per-leaf plane's int64 shape vector CAN express this — fall
            # back (lockstep: every rank sees the sentinel in the same rows)
            raise CoalesceFallback("a leaf dimension does not fit the metadata encoding")
        if _CODE_RANK_OVERFLOW in codes:
            raise ValueError(f"coalesced sync supports rank <= {_MAX_RANK} state leaves")
        known = [c for c in codes if c >= 0]
        if _CODE_UNSUPPORTED in codes:
            raise ValueError(
                f"coalesced sync got an unsupported dtype on at least one process; supported: "
                f"{[str(jnp.dtype(d)) for d in GATHER_DTYPES]}"
            )
        if len(known) > 1:
            raise ValueError(
                "coalesced sync requires the same dtype on every process, got "
                f"{[str(jnp.dtype(GATHER_DTYPES[c])) for c in known]}"
            )
        if not known:  # every rank empty: leaf keeps its local value
            leaf_plans.append(_LeafPlan(None, [(0,)] * world, [0] * world))
            continue
        if any(not 0 <= c < len(GATHER_DTYPES) for c in known):
            raise CoalesceFallback("metadata row carries an invalid dtype code")
        dtype = jnp.dtype(GATHER_DTYPES[known[0]])
        ndims = {int(r[1]) for r in recs if int(r[0]) >= 0}
        if len(ndims) > 1:
            raise ValueError(
                f"coalesced sync requires equal ranks across processes, got {sorted(ndims)}"
            )
        ndim = ndims.pop()
        if not 0 <= ndim <= _MAX_RANK:
            raise CoalesceFallback("metadata row carries an invalid ndim")
        template = next(
            tuple(int(d) for d in r[2 : 2 + ndim]) for r in recs if int(r[0]) >= 0
        )
        dims: List[Tuple[int, ...]] = []
        for r in recs:
            if int(r[0]) >= 0:
                shape = tuple(int(d) for d in r[2 : 2 + ndim])
                if any(d < 0 for d in shape):
                    raise CoalesceFallback("metadata row carries a negative dimension")
                dims.append(shape)
            else:  # empty contributor: zero length, peers' trailing dims
                dims.append((0,) + template[1:] if ndim else ())
        # empty contributors hold zero elements regardless of trailing dims
        counts = [
            0 if int(r[0]) < 0 else (int(np.prod(d)) if d else 1)
            for r, d in zip(recs, dims)
        ]
        leaf_plans.append(_LeafPlan(dtype, dims, counts))
        buckets.setdefault(dtype, []).append(i)
    counter_rows = []
    hist_rows = []
    tail_at = _HEADER_LEN + len(leaves) * _LEAF_REC_LEN
    hist_at = tail_at + 2 * len(COUNTER_FIELDS)
    quant_at = hist_at + 2 * _HIST_VEC_LEN
    for row in decoded:
        if row is None:  # dead ranks contribute zero telemetry (like no session)
            counter_rows.append([0] * len(COUNTER_FIELDS))
            hist_rows.append([0] * _HIST_VEC_LEN)
            continue
        counter_rows.append(unpack_halves(row[tail_at:hist_at]))
        hist_rows.append(unpack_halves(row[hist_at:quant_at]))
    quant = None
    if qctx is not None:
        # fixed section layout: one record per QUANT_SECTION_DTYPES entry on
        # every rank, so decode walks the same offsets the encoder wrote
        bucket_scales: Dict[Any, List[Tuple[int, np.ndarray, np.ndarray]]] = {}
        slots = _quantize.BUCKET_SCALE_SLOTS if qctx.config.codec == "int8" else 0
        rec_len = 2 + 2 * slots
        for row in decoded:
            if row is None:  # dead rank: no quantized segments to decode
                for dt in _quantize.QUANT_SECTION_DTYPES:
                    bucket_scales.setdefault(jnp.dtype(dt), []).append(
                        (0, np.zeros((0,), np.float32), np.zeros((0,), np.float32))
                    )
                continue
            at = quant_at
            for dt in _quantize.QUANT_SECTION_DTYPES:
                code = int(row[at])
                if code not in (0, qctx.config.codec_code):
                    raise CoalesceFallback("quant record carries an unknown codec code")
                n_blocks = int(row[at + 1])
                if not 0 <= n_blocks <= slots:  # bf16 records carry no blocks
                    raise CoalesceFallback("quant record carries an invalid block count")
                if slots:
                    scales = _quantize.bits_f32(row[at + 2 : at + 2 + n_blocks])
                    zeros = _quantize.bits_f32(row[at + 2 + slots : at + 2 + slots + n_blocks])
                else:
                    scales = np.zeros((0,), np.float32)
                    zeros = np.zeros((0,), np.float32)
                bucket_scales.setdefault(jnp.dtype(dt), []).append((n_blocks, scales, zeros))
                at += rec_len
            if at != row.size:
                raise CoalesceFallback("quant section does not match the fixed layout")
        quant = _QuantPlan(
            codec=qctx.config.codec, leaf_codes=leaf_codes, bucket_scales=bucket_scales
        )
    return _WorldPlan(
        world=world, leaf_plans=leaf_plans, buckets=buckets,
        counter_rows=counter_rows, hist_rows=hist_rows, quant=quant,
        alive=alive, epochs=epochs,
    )


def build_bucket_payload(
    states: Sequence[Dict[str, Any]],
    reductions_list: Sequence[Mapping[str, Reduction]],
    bucket_index: int,
    metadata_rows: Sequence[Any],
    sync_config: Optional[Any] = None,
) -> Array:
    """This rank's padded flat payload for bucket ``bucket_index`` under the
    gathered ``metadata_rows`` — the replay API that lets a test fake simulate
    each rank of a world deterministically. With an enabled ``sync_config``
    the payload is the quantized byte stream the real rank would ship
    (deterministic: the scales match what ``build_local_metadata`` announced,
    as long as the config's residual store is unchanged in between)."""
    leaves = _prepare_leaves(states, reductions_list)
    qctx = _make_qctx(leaves, sync_config)
    plan = _plan_from_rows(metadata_rows, leaves, qctx)
    dtype = list(plan.buckets)[bucket_index]
    if _bucket_quantized(plan, dtype):
        return _local_bucket_bytes(leaves, plan, dtype, qctx)
    return _local_bucket_flat(leaves, plan, dtype)


def _local_bucket_flat(leaves: Sequence[_Leaf], plan: _WorldPlan, dtype: Any) -> Array:
    parts = []
    for li in plan.buckets[dtype]:
        leaf = leaves[li]
        if leaf.array is None:
            continue  # zero elements — nothing to ship
        parts.append(jnp.ravel(jnp.asarray(leaf.array)))
    flat = (
        jnp.concatenate(parts) if parts else jnp.zeros((0,), dtype)
    ).astype(dtype)
    totals = [
        sum(plan.leaf_plans[li].counts[r] for li in plan.buckets[dtype])
        for r in range(plan.world)
    ]
    pad = max(totals) - int(flat.shape[0])
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), dtype)])
    return flat


def _bucket_quantized(plan: _WorldPlan, dtype: Any) -> bool:
    """Whether this bucket ships as a quantized byte stream: some rank
    announced a codec for one of its leaves — and there is more than one rank
    (a world-of-one sync skips the codec entirely; a lossy round-trip with
    nobody to ship to would be pure error)."""
    if plan.quant is None or plan.world <= 1:
        return False
    return any(
        code != 0
        for li in plan.buckets[dtype]
        for code in plan.quant.leaf_codes[li]
    )


def _bucket_byte_totals(plan: _WorldPlan, dtype: Any) -> List[int]:
    """Per-rank wire bytes of a quantized bucket (metadata math only)."""
    itemsize = jnp.dtype(dtype).itemsize
    totals = []
    for r in range(plan.world):
        total = 0
        for li in plan.buckets[dtype]:
            code = plan.quant.leaf_codes[li][r]
            total += plan.leaf_plans[li].counts[r] * _quantize.codec_width(code, itemsize)
        totals.append(total)
    return totals


def _local_bucket_bytes(
    leaves: Sequence[_Leaf], plan: _WorldPlan, dtype: Any, qctx: Any
) -> Array:
    """This rank's byte-stream payload for a quantized bucket: exact leaves
    as raw bitcast bytes (bit-for-bit), quantized leaves as their codec
    payloads, padded with zeros to the world's max byte total."""
    parts = []
    for li in plan.buckets[dtype]:
        leaf = leaves[li]
        if leaf.array is None:
            continue
        code = qctx.leaf_code(li)
        if code == 0:
            parts.append(_quantize.to_bytes(jnp.asarray(leaf.array).astype(dtype)))
        else:
            parts.append(qctx.payloads[li])
    flat = jnp.concatenate(parts) if parts else jnp.zeros((0,), jnp.uint8)
    pad = max(_bucket_byte_totals(plan, dtype)) - int(flat.shape[0])
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.uint8)])
    return flat


def _decode_bucket_rows(
    plan: _WorldPlan, dtype: Any, rows_b: Sequence[Any]
) -> List[List[Optional[Array]]]:
    """Per-(rank, leaf) arrays of one quantized bucket: each rank's segment
    decodes under that rank's OWN announced codes and scales (exact segments
    bitcast back bit-for-bit, int8 segments through the rank's block scales
    split by the same deterministic allocation its encoder ran)."""
    itemsize = jnp.dtype(dtype).itemsize
    leaf_idxs = plan.buckets[dtype]
    out: List[List[Optional[Array]]] = [[] for _ in leaf_idxs]
    for r in range(plan.world):
        if not plan.alive[r]:
            continue  # tombstoned rank: its row is zeros, the quorum folds on
        row = jnp.asarray(rows_b[r])
        if row.dtype != jnp.uint8:
            row = row.astype(jnp.uint8)
        # rank r's int8 block allocation over ITS announced-quantized leaves,
        # from the same fixed slot pool its encoder drew on
        n_blocks_r, scales_r, zeros_r = plan.quant.bucket_scales[dtype][r]
        q_counts = [
            plan.leaf_plans[li].counts[r]
            for li in leaf_idxs
            if plan.quant.leaf_codes[li][r] == _quantize.CODEC_INT8
        ]
        blocks = _quantize.allocate_blocks(q_counts, _quantize.BUCKET_SCALE_SLOTS)
        if q_counts and sum(blocks) != n_blocks_r:
            raise CoalesceFallback("quant scales do not match the announced block count")
        offset = 0
        scale_off = 0
        qi = 0
        for j, li in enumerate(leaf_idxs):
            lp = plan.leaf_plans[li]
            n = lp.counts[r]
            code = plan.quant.leaf_codes[li][r]
            width = _quantize.codec_width(code, itemsize)
            seg = row[offset : offset + n * width]
            offset += n * width
            if code == _quantize.CODEC_BF16:
                arr = _quantize.from_bytes(seg, n, jnp.bfloat16).astype(dtype)
            elif code == _quantize.CODEC_INT8:
                nb = blocks[qi]
                arr = _quantize.block_dequantize(
                    seg,
                    scales_r[scale_off : scale_off + nb],
                    zeros_r[scale_off : scale_off + nb],
                    n,
                    dtype,
                )
                scale_off += nb
                qi += 1
            else:
                arr = _quantize.from_bytes(seg, n, dtype)
            out[j].append(arr.reshape(lp.dims[r]))
    return out


# ---------------------------------------------------------------------------
# cross-process coalesced sync (plane 2)
# ---------------------------------------------------------------------------


def process_rows(value: Any) -> List[Any]:
    """Per-process rows of one real ``process_allgather`` — normalized for the
    world of one, where process_allgather returns the input UNSTACKED (shared
    by both sync planes; the single place that knows this quirk)."""
    value = jnp.asarray(value)
    if jax.process_count() == 1:
        return [value]
    from jax.experimental import multihost_utils

    stacked = multihost_utils.process_allgather(value, tiled=False)
    return [stacked[i] for i in range(stacked.shape[0])]


def _make_gather(process_group: Any, dist_sync_fn: Optional[Callable]) -> Callable:
    if dist_sync_fn is not None:
        def gather(arr):
            return [jnp.asarray(r) for r in dist_sync_fn(jnp.asarray(arr), process_group)]

        return gather
    return process_rows


def coalesced_process_sync(
    states: Sequence[Dict[str, Any]],
    reductions_list: Sequence[Mapping[str, Reduction]],
    process_group: Any = None,
    dist_sync_fn: Optional[Callable] = None,
    sync_config: Optional[Any] = None,
) -> List[Dict[str, Any]]:
    """Synchronize one or many state dicts across processes with one metadata
    collective plus one padded gather per dtype bucket.

    ``sync_config`` (:class:`~torchmetrics_tpu.parallel.quantize.SyncConfig`)
    opts eligible float buckets into the quantized byte-stream wire format —
    same collective count, compressed payloads, per-leaf codec/scale metadata
    riding the up-front metadata collective. Error-feedback residuals commit
    only after every bucket gathered, so transient failures and retries can
    never double-apply feedback.

    Returns new state dicts (inputs untouched — callers commit atomically, so
    any failure leaves every metric at its last good state). Raises
    :class:`CoalesceFallback` when the gathered metadata is unusable; the
    caller then re-runs the per-leaf plane (always exact — quantization only
    exists on the coalesced fast path).
    """
    from . import sync as _sync  # lazy: sync.py imports this module at top level

    leaves = _prepare_leaves(states, reductions_list)
    if sync_config is not None and dist_sync_fn is None and not _sync.distributed_available():
        # single process with real collectives: the world-of-one bypass would
        # discard the encoding anyway — skip the encode cost up front (replay
        # fakes keep their qctx; simulated worlds have world > 1)
        sync_config = None
    qctx = _make_qctx(leaves, sync_config)
    rec = _observability._ACTIVE
    counters_vec = None
    hist_vec = None
    if rec is not None and dist_sync_fn is None:
        counters_vec = rec.counters.counts_vector()
        hist_vec = rec.histograms.fleet_vector()
    meta = _encode_metadata(leaves, counters_vec, hist_vec, qctx)
    gather = _make_gather(process_group, dist_sync_fn)
    try:
        rows = gather(meta)  # collective #1: the single up-front shape/metadata gather
    except Exception as err:
        # an injected gather written against the documented per-leaf seam may
        # choke on the metadata vector (asserts on dtype/shape of state leaves)
        # — deterministic failures fall back to the per-leaf plane it was
        # written for. Transient errors (FlakyGather & friends) and anything
        # from a REAL collective still propagate to the retry layer: a local
        # fallback there would desynchronize the fleet / bypass retry.
        from ..reliability.retry import TRANSIENT, classify_exception

        if dist_sync_fn is not None and classify_exception(err) != TRANSIENT:
            raise CoalesceFallback(f"injected gather rejected the metadata vector: {err!r}") from err
        raise
    if rec is not None:  # launch-time counting: fallbacks keep their collectives
        rec.counters.record_sync_collectives(1)
    plan = _plan_from_rows(rows, leaves, qctx)
    if dist_sync_fn is None:
        _deposit_fleet_rows(plan, rec)
    per_leaf_gathered: List[Optional[List[Array]]] = [None] * len(leaves)
    quant_stats = {"buckets": 0, "raw_bytes": 0, "shipped_bytes": 0}
    for dtype, leaf_idxs in plan.buckets.items():
        quantized = _bucket_quantized(plan, dtype)
        if quantized:
            flat = _local_bucket_bytes(leaves, plan, dtype, qctx)
            quant_stats["buckets"] += 1
            quant_stats["shipped_bytes"] += int(flat.size)
            quant_stats["raw_bytes"] += max(
                sum(plan.leaf_plans[li].counts[r] for li in leaf_idxs)
                for r in range(plan.world)
            ) * jnp.dtype(dtype).itemsize
        else:
            flat = _local_bucket_flat(leaves, plan, dtype)
        rows_b = gather(flat)  # one collective serves every leaf of this dtype
        if rec is not None:
            rec.counters.record_sync_collectives(1)
            # payload-size distribution of the bucketed collective (metadata
            # math only) — the few-large-vs-many-small observable of coalescing
            rec.record_gather_payload(
                "coalesced", int(flat.size) * jnp.dtype(flat.dtype).itemsize
            )
        if len(rows_b) != plan.world:
            raise CoalesceFallback("bucket gather returned a different world size than the metadata")
        if quantized:
            decoded_bucket = _decode_bucket_rows(plan, dtype, rows_b)
            for j, li in enumerate(leaf_idxs):
                if per_leaf_gathered[li] is None:
                    per_leaf_gathered[li] = []
                per_leaf_gathered[li].extend(decoded_bucket[j])
            continue
        for r in range(plan.world):
            if not plan.alive[r]:
                continue  # tombstoned rank: its row is zeros, the quorum folds on
            offset = 0
            row = jnp.asarray(rows_b[r])
            for li in leaf_idxs:
                lp = plan.leaf_plans[li]
                n = lp.counts[r]
                seg = row[offset : offset + n].reshape(lp.dims[r])
                offset += n
                if per_leaf_gathered[li] is None:
                    per_leaf_gathered[li] = []
                per_leaf_gathered[li].append(seg)
    if qctx is not None:
        # every bucket gathered — the sync succeeded, residuals may commit
        # (a failure above left the store untouched, so retries re-quantize
        # from the same base instead of double-applying feedback)
        commit_stats = qctx.commit(plan.world)
        if rec is not None and quant_stats["buckets"]:
            meta_bytes = 4 * sum(_quant_record_lens(qctx))
            rec.record_quant(
                "coalesced_sync", sync_config.codec,
                buckets=quant_stats["buckets"],
                leaves=commit_stats["leaves_quantized"],
                raw_bytes=quant_stats["raw_bytes"],
                shipped_bytes=quant_stats["shipped_bytes"] + meta_bytes,
                feedback_norm=sync_config.residual_norm(),
            )
    outs = [dict(s) for s in states]
    for leaf, gathered in zip(leaves, per_leaf_gathered):
        if gathered is None:
            continue  # every rank empty: keep the local value (per-leaf semantics)
        if leaf.is_list:
            vals = [g for g in gathered if g.shape[0] > 0]
            outs[leaf.state_idx][leaf.name] = vals or leaf.original
        else:
            outs[leaf.state_idx][leaf.name] = _sync._fold_gathered(gathered, leaf.fx)
    if rec is not None:
        rec.counters.record_coalesced(sum(1 for g in per_leaf_gathered if g is not None))
    # liveness bookkeeping LAST — only a sync that fully committed may mark
    # ranks dead or reconcile a rejoin (a failed gather retries from scratch)
    dead = [r for r in range(plan.world) if not plan.alive[r]]
    rejoined = [r for r in range(plan.world) if plan.alive[r] and r in _DEAD_RANKS]
    for r in dead:
        _DEAD_RANKS[r] = _DEAD_RANKS.get(r, 0) + 1
    for r in rejoined:
        # the rejoined rank's full accumulator was part of THIS sync's gather,
        # so its missed contribution just reconciled — no double count possible
        _DEAD_RANKS.pop(r, None)
    if rec is not None:
        if dead:
            rec.record_degraded_sync("coalesced_sync", dead, plan.world)
        for r in rejoined:
            rec.record_rank_rejoin("coalesced_sync", r, plan.epochs[r])
    return outs


# ---------------------------------------------------------------------------
# fleet-counter piggyback mailbox
# ---------------------------------------------------------------------------

_FLEET_MAILBOX: Dict[str, Any] = {
    "session_epoch": None, "rows": None, "hist_rows": None, "local_index": None,
}


def _deposit_fleet_rows(plan: _WorldPlan, rec: Any) -> None:
    if rec is None:
        return
    # keyed on the session EPOCH, not id(rec): a dead recorder's id can be
    # reused by the next allocation, which would leak stale rows cross-session
    _FLEET_MAILBOX["session_epoch"] = getattr(rec, "_epoch", None)
    _FLEET_MAILBOX["rows"] = [list(r) for r in plan.counter_rows]
    _FLEET_MAILBOX["hist_rows"] = [list(r) for r in plan.hist_rows]
    _FLEET_MAILBOX["local_index"] = jax.process_index()


def _fleet_rows(field: str, row_len: int) -> Optional[Tuple[List[List[int]], int]]:
    """Shared mailbox-validity discipline for both piggybacked row kinds:
    rows exist, belong to the ACTIVE session's epoch, and have the expected
    vector length — else ``None`` (the caller launches a fresh collective)."""
    rec = _observability._ACTIVE
    if (
        rec is None
        or _FLEET_MAILBOX[field] is None
        or _FLEET_MAILBOX["session_epoch"] is None
        or _FLEET_MAILBOX["session_epoch"] != getattr(rec, "_epoch", None)
    ):
        return None
    rows = _FLEET_MAILBOX[field]
    if any(len(r) != row_len for r in rows):
        return None
    return [list(r) for r in rows], int(_FLEET_MAILBOX["local_index"])


def fleet_counter_rows() -> Optional[Tuple[List[List[int]], int]]:
    """Per-rank counter rows captured by the last coalesced sync's metadata
    collective, plus this process's index — or ``None`` when no coalesced sync
    ran under the currently active telemetry session. Remote rows are as of
    each rank's last sync (a rank without an active session contributes
    zeros); the consumer replaces the local row with a fresh snapshot."""
    return _fleet_rows("rows", len(COUNTER_FIELDS))


def fleet_histogram_rows() -> Optional[Tuple[List[List[int]], int]]:
    """Per-rank fleet histogram vectors captured by the last coalesced sync's
    metadata collective (same mailbox discipline as :func:`fleet_counter_rows`:
    keyed to the active session's epoch, local row to be refreshed by the
    consumer) — or ``None`` when no coalesced sync ran under this session."""
    return _fleet_rows("hist_rows", _HIST_VEC_LEN)


def clear_fleet_mailbox() -> None:
    _FLEET_MAILBOX.update(
        {"session_epoch": None, "rows": None, "hist_rows": None, "local_index": None}
    )


def gather_host_rows(
    vector: Any, process_group: Any = None, dist_sync_fn: Optional[Callable] = None
) -> List[np.ndarray]:
    """One-collective gather of a fixed-length host metadata vector (equal
    length on every rank by contract — no shape negotiation, unlike
    ``gather_all_arrays``' two-collective shape-then-payload dance)."""
    gather = _make_gather(process_group, dist_sync_fn)
    return [np.asarray(r) for r in gather(np.asarray(vector))]


# ---------------------------------------------------------------------------
# in-graph bucketed reduction (plane 1)
# ---------------------------------------------------------------------------

_NUMERIC_CLASS = {"sum": "sum", "mean": "sum", "max": "max", "min": "min"}
_NUMERIC_OP = {"sum": jax.lax.psum, "max": jax.lax.pmax, "min": jax.lax.pmin}


def reduce_many(
    pairs: Sequence[Tuple[Dict[str, Any], Mapping[str, Reduction]]],
    axis_name: Union[str, Sequence[str]],
) -> List[Dict[str, Any]]:
    """Reduce every leaf of one or many state dicts across a named mesh axis
    with one collective per (reduction-class × dtype) bucket. Call inside
    ``shard_map``; shapes are static so no metadata exchange is needed.

    Produces exactly what the per-leaf ``reduce_over_axis`` would: psum/pmax/
    pmin are elementwise, so reducing the concatenated flat bucket and slicing
    back is bitwise identical; cat/custom leaves are recovered from their
    all-gathered slice as ``(world, *shape)`` before tiling/applying ``fx``.
    """
    outs = [dict(s) for s, _ in pairs]
    numeric: Dict[Tuple[str, Any], List[Tuple[int, str, Any, Reduction]]] = {}
    gathered: Dict[Any, List[Tuple[int, str, Any, Reduction, str]]] = {}
    for pi, (state, reds) in enumerate(pairs):
        for name, value in state.items():
            fx = reds.get(name)
            if fx is None:
                continue  # passthrough (per-leaf semantics)
            if callable(fx):
                gathered.setdefault(jnp.asarray(value).dtype, []).append(
                    (pi, name, jnp.asarray(value), fx, "custom")
                )
            elif fx in _NUMERIC_CLASS:
                arr = jnp.asarray(value)
                numeric.setdefault((_NUMERIC_CLASS[fx], arr.dtype), []).append((pi, name, arr, fx))
            elif fx == "cat":
                arr = jnp.atleast_1d(jnp.asarray(value))
                gathered.setdefault(arr.dtype, []).append((pi, name, arr, fx, "cat"))
            else:
                raise ValueError(f"Unknown dist_reduce_fx: {fx!r}")
    axis_size = None
    for (cls, dtype), leaves in numeric.items():
        flat = jnp.concatenate([jnp.ravel(arr) for _, _, arr, _ in leaves])
        red = _NUMERIC_OP[cls](flat, axis_name)
        offset = 0
        for pi, name, arr, fx in leaves:
            n = int(np.prod(arr.shape)) if arr.shape else 1
            seg = red[offset : offset + n].reshape(arr.shape)
            offset += n
            if fx == "mean":
                if axis_size is None:
                    axis_size = jax.lax.psum(1, axis_name)  # static: constant-folded
                seg = seg / axis_size
            outs[pi][name] = seg
    for dtype, leaves in gathered.items():
        flat = jnp.concatenate([jnp.ravel(arr) for _, _, arr, _, _ in leaves])
        g = jax.lax.all_gather(flat, axis_name, axis=0, tiled=False)  # (world, L)
        world = g.shape[0]
        offset = 0
        for pi, name, arr, fx, mode in leaves:
            n = int(np.prod(arr.shape)) if arr.shape else 1
            seg = g[:, offset : offset + n].reshape((world,) + arr.shape)
            offset += n
            if mode == "cat":
                outs[pi][name] = seg.reshape((world * arr.shape[0],) + arr.shape[1:])
            else:
                outs[pi][name] = fx(seg)
    return outs


def quantized_payload_model(
    states: Sequence[Dict[str, Any]],
    reductions_list: Sequence[Mapping[str, Reduction]],
    sync_config: Optional[Any] = None,
    world: int = 2,
) -> Dict[str, int]:
    """Deterministic byte model of one sync over ``world`` identical ranks:
    what the exact plane would ship vs what the quantized plane ships
    (payload + scale metadata), total and restricted to the codec-eligible
    leaves. Metadata math only — no communication, no device reads beyond
    the scale computation; the ``quantized_sync`` bench gates on it."""
    leaves = _prepare_leaves(states, reductions_list)
    qctx = _make_qctx(leaves, sync_config)
    meta = _encode_metadata(leaves, None, None, qctx)
    plan = _plan_from_rows([meta] * max(1, int(world)), leaves, qctx)
    out: Dict[str, int] = {
        "buckets": len(plan.buckets), "quantized_buckets": 0, "leaves_quantized": 0,
        "exact_bytes": 0, "shipped_bytes": 0, "quant_meta_bytes": 0,
        "eligible_exact_bytes": 0, "eligible_shipped_bytes": 0,
    }
    for dtype, leaf_idxs in plan.buckets.items():
        itemsize = jnp.dtype(dtype).itemsize
        exact = max(
            sum(plan.leaf_plans[li].counts[r] for li in leaf_idxs)
            for r in range(plan.world)
        ) * itemsize
        out["exact_bytes"] += exact
        if _bucket_quantized(plan, dtype):
            out["quantized_buckets"] += 1
            out["shipped_bytes"] += max(_bucket_byte_totals(plan, dtype))
        else:
            out["shipped_bytes"] += exact
    if qctx is not None and plan.world > 1:
        out["quant_meta_bytes"] = 4 * sum(_quant_record_lens(qctx))
        out["shipped_bytes"] += out["quant_meta_bytes"]
        for dt in qctx.bucket_order:
            quant_lis = [li for li in qctx.bucket_leaves[dt] if qctx.leaf_code(li) != 0]
            blocks = dict(zip(quant_lis, qctx.bucket_blocks[dt]))
            for li in quant_lis:
                code = qctx.leaf_code(li)
                out["leaves_quantized"] += 1
                arr = leaves[li].array
                count = int(jnp.asarray(arr).size)
                itemsize = jnp.dtype(arr.dtype).itemsize
                out["eligible_exact_bytes"] += count * itemsize
                out["eligible_shipped_bytes"] += count * _quantize.codec_width(code, itemsize)
                if code == _quantize.CODEC_INT8:
                    out["eligible_shipped_bytes"] += 8 * blocks[li]
    return out


def collective_counts(
    states: Sequence[Dict[str, Any]], reductions_list: Sequence[Mapping[str, Reduction]]
) -> Dict[str, int]:
    """Static collective-count model for a sync of these states: how many
    collectives each plane launches, coalesced vs per-leaf (for benches/docs —
    no communication happens here)."""
    in_graph_buckets: set = set()
    process_buckets: set = set()
    n_leaves = 0
    per_leaf_in_graph = 0
    for state, reds in zip(states, reductions_list):
        for name, value in state.items():
            fx = reds.get(name)
            n_leaves += 1
            if isinstance(value, list):
                arr = jnp.asarray(value[0]) if value else None
            else:
                arr = jnp.asarray(value)
            if arr is not None:
                process_buckets.add(str(arr.dtype))
            if fx is None:
                continue
            per_leaf_in_graph += 1
            if callable(fx) or fx == "cat":
                in_graph_buckets.add(("gather", str(arr.dtype) if arr is not None else "?"))
            else:
                in_graph_buckets.add((_NUMERIC_CLASS[fx], str(arr.dtype)))
    return {
        "leaves": n_leaves,
        "in_graph_coalesced": len(in_graph_buckets),
        "in_graph_per_leaf": per_leaf_in_graph,
        "process_coalesced": 1 + len(process_buckets),  # metadata + one per dtype
        # gather_all_arrays pays a shape exchange + a payload gather per leaf
        "process_per_leaf": 2 * n_leaves,
    }
