"""Distributed state synchronization — TPU-native replacement for the reference's
``utilities/distributed.py`` + ``Metric._sync_dist`` stack.

Reference model (SURVEY §2.12): one padded ``all_gather`` per state + barrier over
torch.distributed (gloo/NCCL), driven by per-state ``dist_reduce_fx``.

TPU-native model — three sync planes, all driven by the same per-state reduction tag:

1. **In-graph** (``reduce_states``): inside ``shard_map``/``pjit`` over a
   ``jax.sharding.Mesh`` axis — sum→``lax.psum``, mean→``lax.pmean``, max→``lax.pmax``,
   min→``lax.pmin``, cat→``lax.all_gather(tiled=True)``. Static shapes ⇒ no
   barrier+shape-gather+pad dance (reference utilities/distributed.py:100-153); XLA
   lowers these onto ICI collectives directly.
2. **Cross-process** (``process_sync``): multi-controller JAX (one process per host,
   torchmetrics' usage pattern) — ``multihost_utils.process_allgather`` + host-side
   fold with the registered merge. Used by ``Metric.sync()`` when
   ``jax.process_count() > 1``.
3. **Commless** (``merge_states``): pure pytree fold of two state dicts — the
   reference's ``merge_state`` (metric.py:404) — also the building block for tree
   reductions of gathered custom states.

Planes 1 and 2 are **coalesced** (``parallel/coalesce.py``): all leaves ride one
collective per (reduction-class × dtype) bucket — K·L per-leaf collectives
collapse to a handful per sync — with the per-leaf plane kept as the bitwise
parity oracle and automatic fallback (``reduce_states_per_leaf``,
``_process_sync_per_leaf``). See docs/distributed.md, "Coalesced synchronization".

Plane 2 additionally runs **double-buffered** (``parallel/async_sync.py``):
:class:`~torchmetrics_tpu.parallel.AsyncSyncHandle` ships a frozen previous
window's states through the same coalesced gather on a background worker while
the current window keeps updating, committing with the blocking plane's
commit-after-validate rollback discipline — ``MetricCollection.sync(async_=
True)`` and ``ServingEngine.sync_async`` are the entry points
(docs/streaming.md, "Async double-buffered sync").
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from .. import observability as _observability
from ..observability import tracing as _tracing
from . import coalesce as _coalesce

Array = jax.Array
Reduction = Union[str, Callable, None]

# ---------------------------------------------------------------------------
# pairwise merge semantics per reduction tag (across batches / processes)
# ---------------------------------------------------------------------------


def _merge_sum(a, b):
    return a + b


def _merge_mean(a, b):  # mean of TWO participants only — n-way folds must use
    return (a + b) / 2.0  # weighted_mean / the stacked reduction in _fold_gathered


def _merge_max(a, b):
    return jnp.maximum(a, b)


def _merge_min(a, b):
    return jnp.minimum(a, b)


def _merge_cat(a, b):
    if isinstance(a, list):
        return a + (b if isinstance(b, list) else [b])
    return jnp.concatenate([jnp.atleast_1d(a), jnp.atleast_1d(b)], axis=0)


_PAIRWISE: Dict[str, Callable] = {
    "sum": _merge_sum,
    "mean": _merge_mean,
    "max": _merge_max,
    "min": _merge_min,
    "cat": _merge_cat,
}


def pairwise_merge(fx: Reduction, a, b, weights: Optional[tuple] = None):
    """Merge two values of one state according to its reduction tag.

    ``weights=(w_a, w_b)`` gives the participant weights for ``"mean"`` states —
    without them a plain 2-way average is used, which is only correct when both
    sides represent the same number of updates (reference metric.py:481 weights by
    ``_update_count`` for exactly this reason).
    """
    if fx is None:
        return a  # keep local value (reference semantics for fx=None)
    if callable(fx):
        # custom reduction operating on a stacked/concatenated tensor (reference
        # contract) — emulate pairwise by stacking
        return fx(jnp.stack([jnp.asarray(a), jnp.asarray(b)], axis=0))
    if fx == "mean" and weights is not None:
        return weighted_mean(a, b, weights[0], weights[1])
    return _PAIRWISE[fx](a, b)


def weighted_mean(a, b, w_a, w_b):
    """Count-weighted mean merge: exact for any number of folded participants as long
    as each carries its cumulative weight (reference metric.py:481 running-mean fold)."""
    total = w_a + w_b
    safe = jnp.where(total == 0, 1.0, total)
    return jnp.where(total == 0, a, (w_a * a + w_b * b) / safe)


# ---------------------------------------------------------------------------
# plane 1: in-graph mesh-axis reduction (use inside shard_map / pjit)
# ---------------------------------------------------------------------------


def reduce_over_axis(value: Array, fx: Reduction, axis_name: Union[str, Sequence[str]]):
    """Reduce one state leaf across a named mesh axis. Call inside shard_map."""
    if fx is None:
        return value
    if fx == "sum":
        return jax.lax.psum(value, axis_name)
    if fx == "mean":
        return jax.lax.pmean(value, axis_name)
    if fx == "max":
        return jax.lax.pmax(value, axis_name)
    if fx == "min":
        return jax.lax.pmin(value, axis_name)
    if fx == "cat":
        return jax.lax.all_gather(jnp.atleast_1d(value), axis_name, axis=0, tiled=True)
    if callable(fx):
        gathered = jax.lax.all_gather(value, axis_name, axis=0)
        return fx(gathered)
    raise ValueError(f"Unknown dist_reduce_fx: {fx!r}")


def reduce_states(
    state: Dict[str, Any], reductions: Mapping[str, Reduction], axis_name: Union[str, Sequence[str]]
) -> Dict[str, Any]:
    """Reduce a whole state dict across a mesh axis (in-graph), **coalesced**:
    all leaves ride one collective per (reduction-class × dtype) bucket instead
    of one per leaf (``parallel/coalesce.py``). Bitwise-equal to the per-leaf
    plane — psum/pmax/pmin are elementwise and gather slices are restored to
    the exact per-leaf layout before cat/custom folding."""
    return _coalesce.reduce_many([(state, reductions)], axis_name)[0]


def reduce_states_per_leaf(
    state: Dict[str, Any], reductions: Mapping[str, Reduction], axis_name: Union[str, Sequence[str]]
) -> Dict[str, Any]:
    """Reference per-leaf plane (one collective per leaf) — kept as the parity
    oracle for the coalesced path and for debugging collective layouts."""
    return {k: reduce_over_axis(v, reductions.get(k), axis_name) for k, v in state.items()}


# ---------------------------------------------------------------------------
# plane 2: cross-process sync (multi-controller)
# ---------------------------------------------------------------------------


def distributed_available() -> bool:
    """Counterpart of the reference's ``jittable_distributed_available``
    (metric.py:47-49): True when more than one JAX process is attached."""
    try:
        return jax.process_count() > 1
    except Exception:
        return False


_GATHER_MAX_RANK = 8
_GATHER_DTYPES = _coalesce.GATHER_DTYPES  # single source for both planes


def gather_all_arrays(value: Optional[Array], process_group: Any = None) -> List[Array]:
    """All-gather one array across JAX processes → list of per-process values.

    Counterpart of reference ``gather_all_tensors`` (utilities/distributed.py:100),
    including its uneven-shape path: shapes are gathered first (always a
    fixed-size vector, so every process enters the collective), every process
    pads each dimension to the world maximum, and the gathered results are
    trimmed back per process (reference :130-147). ``value=None`` means "this
    process has nothing" (a concat state after zero updates) — the process still
    participates, contributing a zero-length array in the dtype/rank its peers
    announce, so collectives never desynchronize across states.
    """
    import numpy as np
    from jax.experimental import multihost_utils

    _rows = _coalesce.process_rows  # world-of-one-normalized process_allgather

    vec = np.full(_GATHER_MAX_RANK + 2, -1, np.int64)
    if value is not None:
        value = jnp.asarray(value)
        if value.ndim > _GATHER_MAX_RANK:
            raise ValueError(f"gather_all_arrays supports rank <= {_GATHER_MAX_RANK}, got {value.ndim}")
        vec[0] = value.ndim
        vec[1 : 1 + value.ndim] = value.shape
        codes = [i for i, dt in enumerate(_GATHER_DTYPES) if value.dtype == jnp.dtype(dt)]
        # an unsupported dtype is announced as sentinel -2 INSIDE the shape
        # collective: raising before it would leave peers with supported dtypes
        # blocked in process_allgather; this way every rank completes the shape
        # exchange, sees the sentinel, and raises the same error together
        vec[-1] = codes[0] if codes else -2
    shapes = np.asarray(multihost_utils.process_allgather(jnp.asarray(vec), tiled=False)).reshape(-1, vec.size)
    known_rows = np.flatnonzero(shapes[:, 0] >= 0)
    if known_rows.size == 0:
        return []  # no process has data for this state
    codes_seen = sorted(set(shapes[known_rows, -1].tolist()))
    if -2 in codes_seen:
        raise ValueError(
            f"gather_all_arrays got an unsupported dtype on at least one process; supported: "
            f"{[str(jnp.dtype(d)) for d in _GATHER_DTYPES]}"
        )
    if len(codes_seen) > 1:
        raise ValueError(
            "gather_all_arrays requires the same dtype on every process, got "
            f"{[str(jnp.dtype(_GATHER_DTYPES[int(c)])) for c in codes_seen]}"
        )
    ranks = shapes[known_rows, 0]
    if int(ranks.min()) != int(ranks.max()):
        raise ValueError(f"gather_all_arrays requires equal ranks across processes, got {sorted(set(ranks.tolist()))}")
    rank = int(ranks[0])
    dtype = jnp.dtype(_GATHER_DTYPES[int(shapes[known_rows[0], -1])])
    world = shapes.shape[0]
    if rank == 0:
        if value is None:
            value = jnp.zeros((), dtype)  # scalar states can't signal emptiness; contribute zero
        return _rows(value)
    template = shapes[known_rows[0], 1 : 1 + rank].astype(np.int64)
    dims = np.tile(template, (world, 1))
    for i in range(world):
        if shapes[i, 0] >= 0:
            dims[i] = shapes[i, 1 : 1 + rank]
        else:
            dims[i, 0] = 0  # empty contributor: zero length, peers' trailing dims
    if value is None:
        value = jnp.zeros(tuple(int(d) for d in dims[jax.process_index()]), dtype)
    if (dims == dims[0]).all():
        return _rows(value)
    max_dims = dims.max(axis=0)
    pad = [(0, int(m) - int(s)) for m, s in zip(max_dims, value.shape)]
    stacked = multihost_utils.process_allgather(jnp.pad(value, pad), tiled=False)
    return [stacked[(i, *(slice(0, int(d)) for d in dims[i]))] for i in range(world)]


def process_sync(
    state: Dict[str, Any],
    reductions: Mapping[str, Reduction],
    process_group: Any = None,
    dist_sync_fn: Optional[Callable] = None,
    sync_config: Optional[Any] = None,
) -> Dict[str, Any]:
    """Synchronize a state dict across JAX processes (host-driven plane).

    ``dist_sync_fn`` is the injection seam (reference metric.py:133): signature
    ``fn(value, group) -> list_of_values``.

    ``sync_config`` (:class:`~torchmetrics_tpu.parallel.SyncConfig`) opts the
    coalesced fast path into quantized (bf16/int8) buckets — see
    docs/distributed.md, "Quantized synchronization". The per-leaf fallback
    plane below is always exact.

    Transient-failure retry lives one level up: ``Metric.sync`` wraps the whole
    ``process_sync`` call under its ``ReliabilityConfig`` retry policy. That is
    only safe when every rank runs the same deterministic policy and the failure
    surfaces on all ranks before the collective is entered (host dropout /
    coordination-service faults do; a one-rank mid-collective abort needs the
    cluster-level restart path instead).
    """
    rec = _observability._ACTIVE
    if rec is not None:
        rec.counters.record_sync(_payload_bytes(state))
    with _tracing.trace_span("process_sync"):
        try:
            # coalesced fast path: one metadata collective + one padded gather
            # per dtype bucket serves every leaf at once; per-leaf merge
            # semantics preserved exactly (parallel/coalesce.py)
            return _coalesce.coalesced_process_sync(
                [state], [reductions], process_group=process_group,
                dist_sync_fn=dist_sync_fn, sync_config=sync_config,
            )[0]
        except _coalesce.CoalesceFallback:
            # undecodable/inconsistent metadata (e.g. an injected gather that
            # rewrites values): every rank sees the same gathered rows, so the
            # whole fleet falls back to the per-leaf plane in lockstep
            return _process_sync_per_leaf(state, reductions, process_group, dist_sync_fn)


def _process_sync_per_leaf(
    state: Dict[str, Any],
    reductions: Mapping[str, Reduction],
    process_group: Any = None,
    dist_sync_fn: Optional[Callable] = None,
) -> Dict[str, Any]:
    """The per-leaf plane: one ``gather_all_arrays`` per state leaf."""
    gather = dist_sync_fn or gather_all_arrays
    rec = _observability._ACTIVE
    out: Dict[str, Any] = {}
    for name, value in state.items():
        fx = reductions.get(name)
        if rec is not None:
            rec.counters.record_gather()
            # the real gather_all_arrays launches TWO collectives per leaf
            # (shape-vector exchange + payload); an injected fn is one call
            rec.counters.record_sync_collectives(1 if dist_sync_fn is not None else 2)
            # per-collective payload size (metadata math) — contrast with the
            # "coalesced" series: per-leaf syncs show many small collectives
            rec.record_gather_payload("per_leaf", _payload_bytes({name: value}))
        if isinstance(value, list):  # concat list state: pre-concat, then gather
            local = (
                jnp.concatenate([jnp.atleast_1d(jnp.asarray(v)) for v in value], axis=0)
                if value
                else None  # zero-update process still participates in the collective
            )
            if local is None and dist_sync_fn is not None:
                # injected gathers keep the plain fn(value, group) contract
                local = jnp.zeros((0,), jnp.float32)
            gathered = gather(local, process_group)
            out[name] = [g for g in gathered if g.shape[0] > 0] or value
            continue
        gathered = gather(value, process_group)
        out[name] = _fold_gathered(gathered, fx)
    return out


def gather_metadata_vector(
    values: Sequence[int],
    process_group: Any = None,
    dist_sync_fn: Optional[Callable] = None,
) -> List[List[int]]:
    """All-gather one small per-host int64 metadata vector → list of per-rank
    vectors, indexed by process.

    This is the fleet-telemetry rollup plane: counter snapshots ride the SAME
    coalesced gather plane as metric states (``dist_sync_fn`` stays the
    injection seam), but the payload is metadata-sized — a handful of integers
    per rank, never state data. The vector has the same length on every rank
    by contract, so it ships through ``coalesce.gather_host_rows`` as ONE
    collective (no per-leaf shape round-trip — ``gather_all_arrays`` would pay
    a shape collective first). Values ship as (hi, lo) 31-bit int32 halves:
    with jax's default x64-disabled config ``jnp.asarray`` silently downcasts
    int64 to int32, which would wrap byte/time counters past 2**31 (a >2 GiB
    cumulative sync payload is a normal afternoon on a pod). The split keeps
    every value below 2**62 exact on any config. Single-process (and no
    injected gather): the local vector comes straight back without touching a
    device. Note that a coalesced sync already ships the active session's
    counter vector inside its metadata collective — ``observability.
    gather_counters`` reuses those rows, so a fleet rollup right after a sync
    calls this function not at all.
    """
    import numpy as np

    vals = [int(v) for v in values]
    if any(not 0 <= v < 1 << 62 for v in vals):
        raise ValueError(f"gather_metadata_vector values must be in [0, 2**62), got {vals}")
    if dist_sync_fn is None and not distributed_available():
        return [vals]
    halves = np.empty(2 * len(vals), np.int32)
    _coalesce._pack_halves(halves, vals)
    return [
        _coalesce.unpack_halves(row)
        for row in _coalesce.gather_host_rows(halves, process_group, dist_sync_fn)
    ]


def _payload_bytes(state: Dict[str, Any]) -> int:
    """Bytes this process contributes to a sync — from ``size``/``itemsize``
    metadata only, never a device read."""
    total = 0
    for value in state.values():
        leaves = value if isinstance(value, list) else [value]
        for leaf in leaves:
            if hasattr(leaf, "size") and hasattr(leaf, "dtype"):
                total += int(leaf.size) * jnp.dtype(leaf.dtype).itemsize
    return total


def _fold_gathered(gathered: List[Array], fx: Reduction):
    """Reduce a world-sized list of one state's values.

    Mirrors the reference's stack-then-reduce (metric.py:525-540): "mean" reduces the
    whole stacked gather in one shot — a sequential pairwise ``(a+b)/2`` fold would be
    wrong for 3+ ranks (``((a+b)/2+c)/2 != mean(a,b,c)``).
    """
    if fx is None:
        return gathered[0] if len(gathered) == 1 else jnp.stack(gathered)
    if callable(fx):
        return fx(jnp.stack(gathered))
    if fx == "cat":
        return jnp.concatenate([jnp.atleast_1d(g) for g in gathered], axis=0)
    stacked = jnp.stack(gathered)
    if fx == "sum":
        return stacked.sum(axis=0)
    if fx == "mean":
        return stacked.mean(axis=0)
    if fx == "max":
        return stacked.max(axis=0)
    if fx == "min":
        return stacked.min(axis=0)
    raise ValueError(f"Unknown dist_reduce_fx: {fx!r}")


# ---------------------------------------------------------------------------
# plane 3: commless merge (pytree fold)
# ---------------------------------------------------------------------------


def merge_states(
    a: Dict[str, Any],
    b: Dict[str, Any],
    reductions: Mapping[str, Reduction],
    weights: Optional[tuple] = None,
) -> Dict[str, Any]:
    """Fold state dict ``b`` into ``a`` using per-state reductions (pure).

    ``weights=(w_a, w_b)`` carries each side's update count so ``"mean"`` states fold
    exactly for any chain length (``Metric.merge_state`` passes its ``_update_count``).
    """
    out: Dict[str, Any] = {}
    for name, va in a.items():
        vb = b[name]
        fx = reductions.get(name)
        if isinstance(va, list) or isinstance(vb, list):
            la = va if isinstance(va, list) else [va]
            lb = vb if isinstance(vb, list) else [vb]
            out[name] = la + lb
        else:
            out[name] = pairwise_merge(fx, va, vb, weights=weights)
    return out


# ---------------------------------------------------------------------------
# classic reductions on stacked tensors (reference utilities/distributed.py:22-88)
# ---------------------------------------------------------------------------


# canonical implementations live in utilities.compute (single source; the public
# torchmetrics.utilities surface exports them)
from ..utilities.compute import class_reduce, reduce  # noqa: E402,F401
