"""Distributed state synchronization — TPU-native replacement for the reference's
``utilities/distributed.py`` + ``Metric._sync_dist`` stack.

Reference model (SURVEY §2.12): one padded ``all_gather`` per state + barrier over
torch.distributed (gloo/NCCL), driven by per-state ``dist_reduce_fx``.

TPU-native model — three sync planes, all driven by the same per-state reduction tag:

1. **In-graph** (``reduce_states``): inside ``shard_map``/``pjit`` over a
   ``jax.sharding.Mesh`` axis — sum→``lax.psum``, mean→``lax.pmean``, max→``lax.pmax``,
   min→``lax.pmin``, cat→``lax.all_gather(tiled=True)``. Static shapes ⇒ no
   barrier+shape-gather+pad dance (reference utilities/distributed.py:100-153); XLA
   lowers these onto ICI collectives directly.
2. **Cross-process** (``process_sync``): multi-controller JAX (one process per host,
   torchmetrics' usage pattern) — ``multihost_utils.process_allgather`` per state then a
   host-side fold with the registered merge. Used by ``Metric.sync()`` when
   ``jax.process_count() > 1``.
3. **Commless** (``merge_states``): pure pytree fold of two state dicts — the
   reference's ``merge_state`` (metric.py:404) — also the building block for tree
   reductions of gathered custom states.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Union

import jax
import jax.numpy as jnp

Array = jax.Array
Reduction = Union[str, Callable, None]

# ---------------------------------------------------------------------------
# pairwise merge semantics per reduction tag (across batches / processes)
# ---------------------------------------------------------------------------


def _merge_sum(a, b):
    return a + b


def _merge_mean(a, b):  # mean of TWO participants only — n-way folds must use
    return (a + b) / 2.0  # weighted_mean / the stacked reduction in _fold_gathered


def _merge_max(a, b):
    return jnp.maximum(a, b)


def _merge_min(a, b):
    return jnp.minimum(a, b)


def _merge_cat(a, b):
    if isinstance(a, list):
        return a + (b if isinstance(b, list) else [b])
    return jnp.concatenate([jnp.atleast_1d(a), jnp.atleast_1d(b)], axis=0)


_PAIRWISE: Dict[str, Callable] = {
    "sum": _merge_sum,
    "mean": _merge_mean,
    "max": _merge_max,
    "min": _merge_min,
    "cat": _merge_cat,
}


def pairwise_merge(fx: Reduction, a, b, weights: Optional[tuple] = None):
    """Merge two values of one state according to its reduction tag.

    ``weights=(w_a, w_b)`` gives the participant weights for ``"mean"`` states —
    without them a plain 2-way average is used, which is only correct when both
    sides represent the same number of updates (reference metric.py:481 weights by
    ``_update_count`` for exactly this reason).
    """
    if fx is None:
        return a  # keep local value (reference semantics for fx=None)
    if callable(fx):
        # custom reduction operating on a stacked/concatenated tensor (reference
        # contract) — emulate pairwise by stacking
        return fx(jnp.stack([jnp.asarray(a), jnp.asarray(b)], axis=0))
    if fx == "mean" and weights is not None:
        return weighted_mean(a, b, weights[0], weights[1])
    return _PAIRWISE[fx](a, b)


def weighted_mean(a, b, w_a, w_b):
    """Count-weighted mean merge: exact for any number of folded participants as long
    as each carries its cumulative weight (reference metric.py:481 running-mean fold)."""
    total = w_a + w_b
    safe = jnp.where(total == 0, 1.0, total)
    return jnp.where(total == 0, a, (w_a * a + w_b * b) / safe)


# ---------------------------------------------------------------------------
# plane 1: in-graph mesh-axis reduction (use inside shard_map / pjit)
# ---------------------------------------------------------------------------


def reduce_over_axis(value: Array, fx: Reduction, axis_name: Union[str, Sequence[str]]):
    """Reduce one state leaf across a named mesh axis. Call inside shard_map."""
    if fx is None:
        return value
    if fx == "sum":
        return jax.lax.psum(value, axis_name)
    if fx == "mean":
        return jax.lax.pmean(value, axis_name)
    if fx == "max":
        return jax.lax.pmax(value, axis_name)
    if fx == "min":
        return jax.lax.pmin(value, axis_name)
    if fx == "cat":
        return jax.lax.all_gather(jnp.atleast_1d(value), axis_name, axis=0, tiled=True)
    if callable(fx):
        gathered = jax.lax.all_gather(value, axis_name, axis=0)
        return fx(gathered)
    raise ValueError(f"Unknown dist_reduce_fx: {fx!r}")


def reduce_states(
    state: Dict[str, Any], reductions: Mapping[str, Reduction], axis_name: Union[str, Sequence[str]]
) -> Dict[str, Any]:
    """Reduce a whole state dict across a mesh axis (in-graph)."""
    return {k: reduce_over_axis(v, reductions.get(k), axis_name) for k, v in state.items()}


# ---------------------------------------------------------------------------
# plane 2: cross-process sync (multi-controller)
# ---------------------------------------------------------------------------


def distributed_available() -> bool:
    """Counterpart of the reference's ``jittable_distributed_available``
    (metric.py:47-49): True when more than one JAX process is attached."""
    try:
        return jax.process_count() > 1
    except Exception:
        return False


def gather_all_arrays(value: Array, process_group: Any = None) -> List[Array]:
    """All-gather one array across JAX processes → list of per-process values.

    Counterpart of reference ``gather_all_tensors`` (utilities/distributed.py:100),
    including its uneven-shape path: when leading dimensions differ across
    processes (concat states after different numbers of updates), lengths are
    gathered first (always equal-shape), every process pads to the maximum, and
    the gathered results are trimmed back (reference :130-147). Equal shapes take
    the direct fast path.
    """
    import numpy as np
    from jax.experimental import multihost_utils

    value = jnp.asarray(value)
    local_len = jnp.asarray([value.shape[0] if value.ndim else 1], jnp.int32)
    lengths = np.asarray(multihost_utils.process_allgather(local_len, tiled=False)).reshape(-1)
    if value.ndim == 0 or int(lengths.min()) == int(lengths.max()):
        stacked = multihost_utils.process_allgather(value, tiled=False)
        return [stacked[i] for i in range(stacked.shape[0])]
    max_len = int(lengths.max())
    pad = [(0, max_len - value.shape[0])] + [(0, 0)] * (value.ndim - 1)
    stacked = multihost_utils.process_allgather(jnp.pad(value, pad), tiled=False)
    return [stacked[i, : int(lengths[i])] for i in range(stacked.shape[0])]


def process_sync(
    state: Dict[str, Any],
    reductions: Mapping[str, Reduction],
    process_group: Any = None,
    dist_sync_fn: Optional[Callable] = None,
) -> Dict[str, Any]:
    """Synchronize a state dict across JAX processes (host-driven plane).

    ``dist_sync_fn`` is the injection seam (reference metric.py:133): signature
    ``fn(value, group) -> list_of_values``.
    """
    gather = dist_sync_fn or gather_all_arrays
    out: Dict[str, Any] = {}
    for name, value in state.items():
        fx = reductions.get(name)
        if isinstance(value, list):  # concat list state: gather each element? pre-concat first
            if not value:
                out[name] = value
                continue
            local = jnp.concatenate([jnp.atleast_1d(jnp.asarray(v)) for v in value], axis=0)
            gathered = gather(local, process_group)
            out[name] = [g for g in gathered]
            continue
        gathered = gather(value, process_group)
        out[name] = _fold_gathered(gathered, fx)
    return out


def _fold_gathered(gathered: List[Array], fx: Reduction):
    """Reduce a world-sized list of one state's values.

    Mirrors the reference's stack-then-reduce (metric.py:525-540): "mean" reduces the
    whole stacked gather in one shot — a sequential pairwise ``(a+b)/2`` fold would be
    wrong for 3+ ranks (``((a+b)/2+c)/2 != mean(a,b,c)``).
    """
    if fx is None:
        return gathered[0] if len(gathered) == 1 else jnp.stack(gathered)
    if callable(fx):
        return fx(jnp.stack(gathered))
    if fx == "cat":
        return jnp.concatenate([jnp.atleast_1d(g) for g in gathered], axis=0)
    stacked = jnp.stack(gathered)
    if fx == "sum":
        return stacked.sum(axis=0)
    if fx == "mean":
        return stacked.mean(axis=0)
    if fx == "max":
        return stacked.max(axis=0)
    if fx == "min":
        return stacked.min(axis=0)
    raise ValueError(f"Unknown dist_reduce_fx: {fx!r}")


# ---------------------------------------------------------------------------
# plane 3: commless merge (pytree fold)
# ---------------------------------------------------------------------------


def merge_states(
    a: Dict[str, Any],
    b: Dict[str, Any],
    reductions: Mapping[str, Reduction],
    weights: Optional[tuple] = None,
) -> Dict[str, Any]:
    """Fold state dict ``b`` into ``a`` using per-state reductions (pure).

    ``weights=(w_a, w_b)`` carries each side's update count so ``"mean"`` states fold
    exactly for any chain length (``Metric.merge_state`` passes its ``_update_count``).
    """
    out: Dict[str, Any] = {}
    for name, va in a.items():
        vb = b[name]
        fx = reductions.get(name)
        if isinstance(va, list) or isinstance(vb, list):
            la = va if isinstance(va, list) else [va]
            lb = vb if isinstance(vb, list) else [vb]
            out[name] = la + lb
        else:
            out[name] = pairwise_merge(fx, va, vb, weights=weights)
    return out


# ---------------------------------------------------------------------------
# classic reductions on stacked tensors (reference utilities/distributed.py:22-88)
# ---------------------------------------------------------------------------


def reduce(x: Array, reduction: str) -> Array:
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction == "none" or reduction is None:
        return x
    raise ValueError("Reduction parameter unknown.")


def class_reduce(num: Array, denom: Array, weights: Array, class_reduction: str = "none") -> Array:
    from ..utilities.compute import _safe_divide

    valid_reduction = ("micro", "macro", "weighted", "none", None)
    fraction = _safe_divide(jnp.sum(num), jnp.sum(denom)) if class_reduction == "micro" else _safe_divide(num, denom)
    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * (weights.astype(jnp.float32) / jnp.sum(weights)))
    if class_reduction == "none" or class_reduction is None:
        return fraction
    raise ValueError(f"Reduction parameter {class_reduction!r} unknown. Choose between one of these: {valid_reduction}")
