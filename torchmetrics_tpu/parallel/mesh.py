"""Mesh helpers — the ``process_group`` analogue for TPU.

The reference scopes collectives by ``torch.distributed`` process groups; here the scope
is a named axis (or axes) of a ``jax.sharding.Mesh``. These helpers build standard
meshes and hold a default axis name used by metric sync when running in-graph.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DEFAULT_AXIS = "metrics_dp"


def shard_map(f, mesh: Mesh, in_specs, out_specs, check_vma: Optional[bool] = None):
    """``jax.shard_map`` across jax versions.

    Newer jax exposes ``jax.shard_map(..., check_vma=)``; older releases only
    have ``jax.experimental.shard_map.shard_map(..., check_rep=)`` (same knob,
    earlier name). Every in-repo shard_map site goes through this helper so the
    sharded planes run on either runtime. ``check_vma=None`` keeps the
    runtime's own default.
    """
    if hasattr(jax, "shard_map"):
        kwargs = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map

    kwargs = {} if check_vma is None else {"check_rep": check_vma}
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)


def make_data_mesh(n_devices: Optional[int] = None, axis_name: str = DEFAULT_AXIS) -> Mesh:
    """1-D data-parallel mesh over the first ``n_devices`` devices."""
    devs = jax.devices()[: (n_devices or len(jax.devices()))]
    return jax.make_mesh((len(devs),), (axis_name,), devices=devs)


def make_2d_mesh(dp: int, mp: int, axis_names: Tuple[str, str] = ("data", "model")) -> Mesh:
    """2-D (data, model) mesh — dp×mp must equal the device count used."""
    devs = jax.devices()[: dp * mp]
    return jax.make_mesh((dp, mp), axis_names, devices=devs)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh, axis_name: str = DEFAULT_AXIS) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(axis_name))
