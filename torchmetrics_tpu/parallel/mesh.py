"""Mesh helpers — the ``process_group`` analogue for TPU.

The reference scopes collectives by ``torch.distributed`` process groups; here the scope
is a named axis (or axes) of a ``jax.sharding.Mesh``. These helpers build standard
meshes and hold a default axis name used by metric sync when running in-graph.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DEFAULT_AXIS = "metrics_dp"

#: mesh-axis name the serving engine shards its stacked tenant states over
DEFAULT_TENANT_AXIS = "tenants"


def shard_map(f, mesh: Mesh, in_specs, out_specs, check_vma: Optional[bool] = None):
    """``jax.shard_map`` across jax versions.

    Newer jax exposes ``jax.shard_map(..., check_vma=)``; older releases only
    have ``jax.experimental.shard_map.shard_map(..., check_rep=)`` (same knob,
    earlier name). Every in-repo shard_map site goes through this helper so the
    sharded planes run on either runtime. ``check_vma=None`` keeps the
    runtime's own default.
    """
    if hasattr(jax, "shard_map"):
        kwargs = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map

    kwargs = {} if check_vma is None else {"check_rep": check_vma}
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)


def runtime_fingerprint(mesh: Optional[Mesh] = None) -> str:
    """Backend/topology identity for AOT compile-cache keys (``aot/``).

    A serialized executable is native code for one runtime generation: any
    drift in jax/jaxlib version, backend platform (+ its reported platform
    version, which tracks the XLA build), device kind, or device/process
    topology must make the cache key MISS — a stale entry loading would run
    a wrong (or un-loadable) program. Collective-bearing programs also bake
    in the mesh layout, so an explicit ``mesh`` folds its axis shape in.
    Metadata only; never touches a device.
    """
    import jax as _jax

    dev = _jax.devices()[0]
    platform_version = str(getattr(getattr(dev, "client", None), "platform_version", "") or "")
    parts = [
        f"jax={_jax.__version__}",
        f"jaxlib={_jaxlib_version()}",
        f"backend={_jax.default_backend()}",
        f"platver={platform_version[:60]}",
        f"device={getattr(dev, 'device_kind', type(dev).__name__)}",
        f"ndev={_jax.device_count()}",
        f"nproc={_jax.process_count()}",
        # x64 mode changes what every Python scalar and f64 input canonicalizes
        # to — a different program for the same signature string, so it must key
        f"x64={int(bool(_jax.config.jax_enable_x64))}",
    ]
    if mesh is not None:
        parts.append(f"mesh={tuple(sorted(dict(mesh.shape).items()))!r}")
    return "|".join(parts)


def _jaxlib_version() -> str:
    try:
        import jaxlib

        return getattr(jaxlib, "__version__", "?")
    except Exception:  # noqa: BLE001 — fingerprint stays usable without jaxlib metadata
        return "?"


def make_data_mesh(n_devices: Optional[int] = None, axis_name: str = DEFAULT_AXIS) -> Mesh:
    """1-D data-parallel mesh over the first ``n_devices`` devices."""
    devs = jax.devices()[: (n_devices or len(jax.devices()))]
    return jax.make_mesh((len(devs),), (axis_name,), devices=devs)


def make_2d_mesh(dp: int, mp: int, axis_names: Tuple[str, str] = ("data", "model")) -> Mesh:
    """2-D (data, model) mesh — dp×mp must equal the device count used."""
    devs = jax.devices()[: dp * mp]
    return jax.make_mesh((dp, mp), axis_names, devices=devs)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh, axis_name: str = DEFAULT_AXIS) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(axis_name))


def tenant_sharding(mesh: Mesh, axis_name: str = DEFAULT_TENANT_AXIS) -> NamedSharding:
    """Shard-by-tenant placement for the serving engine's stacked states.

    Every stack leaf carries a leading tenant-row axis; partitioning THAT axis
    over a mesh axis spreads the fleet's state (and the vmapped megabatch
    work addressing it) across devices while each tenant's row stays whole on
    one device — tenants never need cross-device reduction with each other.
    Pass the result as ``ServingConfig(sharding=...)``; pick a stack row count
    (``capacity + 1`` — one scratch row rides along) divisible by the mesh
    axis size so XLA keeps the gather/scatter local-major.
    """
    if axis_name not in mesh.shape:
        raise ValueError(f"mesh has no axis {axis_name!r}; axes: {tuple(mesh.shape)}")
    return NamedSharding(mesh, PartitionSpec(axis_name))
