"""Mesh helpers — the ``process_group`` analogue for TPU.

The reference scopes collectives by ``torch.distributed`` process groups; here the scope
is a named axis (or axes) of a ``jax.sharding.Mesh``. These helpers build standard
meshes and hold a default axis name used by metric sync when running in-graph.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DEFAULT_AXIS = "metrics_dp"


def make_data_mesh(n_devices: Optional[int] = None, axis_name: str = DEFAULT_AXIS) -> Mesh:
    """1-D data-parallel mesh over the first ``n_devices`` devices."""
    devs = jax.devices()[: (n_devices or len(jax.devices()))]
    return jax.make_mesh((len(devs),), (axis_name,), devices=devs)


def make_2d_mesh(dp: int, mp: int, axis_names: Tuple[str, str] = ("data", "model")) -> Mesh:
    """2-D (data, model) mesh — dp×mp must equal the device count used."""
    devs = jax.devices()[: dp * mp]
    return jax.make_mesh((dp, mp), axis_names, devices=devs)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh, axis_name: str = DEFAULT_AXIS) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(axis_name))
