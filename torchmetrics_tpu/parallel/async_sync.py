"""Double-buffered asynchronous state synchronization.

Every sync plane so far is serial with updates: the caller blocks on the
collective set before touching its metrics again, so the full gather latency
lands on the hot path. Real monitoring traffic has the opposite shape — the
*previous* window's state is frozen (the window rolled, or the caller rotated
state via ``reset()``) while the *current* window keeps accumulating — which
is exactly the compute/communication overlap pjit-era training stacks practice
(arXiv:2204.06514): ship the frozen buffers in the background, keep the update
loop running, and pay only the residual wait at the commit barrier.

:class:`AsyncSyncHandle` is that overlap as an object:

- **launch** (construction): a daemon worker thread runs the SAME coalesced
  bucketed gather the blocking planes use (``coalesce.coalesced_process_sync``
  — one metadata collective plus one padded gather per dtype bucket), with the
  per-leaf plane preserved as the in-worker fallback when the gathered
  metadata cannot be decoded (``CoalesceFallback``) and the caller's
  ``RetryPolicy`` honored for transient gather failures;
- **overlap**: the caller keeps updating. The frozen snapshot is a *shallow*
  dict copy — jax arrays are immutable, so freezing is zero-copy — and the
  caller guarantees the frozen buffers stay exclusively owned (either by
  rotating/resetting its live state, or by re-buffering the live side the way
  ``MetricCollection.sync(async_=True)`` does), because a donated update on a
  still-aliased buffer would delete it under the in-flight gather;
- **commit** (the barrier): waits for the worker, re-raises any failure with
  NOTHING installed (the caller keeps its last good state — the same
  commit-after-validate rollback discipline as the blocking collection sync),
  runs the caller's ``committer`` (which validates BEFORE installing), and
  records the overlap accounting: the gather's full wall-clock vs how long
  commit actually blocked — the difference is the sync latency the overlap
  hid (``async_sync`` event, ``async_syncs``/``async_sync_wait_us`` counters).

Single-threaded jax note: the worker only drives HOST-side collectives
(``process_allgather`` / an injected ``dist_sync_fn``); it never touches the
caller's donated dispatch path, so the update loop and the gather share the
runtime safely.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Union

import jax.numpy as jnp

from .. import observability as _observability
from ..observability import spans as _obs_spans
from ..utilities.exceptions import TorchMetricsUserError
from . import coalesce as _coalesce

StateDict = Dict[str, Any]
Reduction = Union[str, Callable, None]


class AsyncSyncHandle:
    """One in-flight background sync of frozen state dicts.

    Args:
        states: the frozen state dicts to synchronize (the handle shallow-
            copies each dict, so the caller may keep mutating its own dict
            CONTAINERS; the captured arrays must stay exclusively owned —
            see the module docstring's donation note).
        reductions: one reduction mapping per state dict.
        process_group / dist_sync_fn: the usual gather seams.
        retry: an optional :class:`~torchmetrics_tpu.reliability.RetryPolicy`
            applied to the whole gather attempt (transient failures retry in
            the worker; the per-leaf ``CoalesceFallback`` path is taken
            inside each attempt exactly like the blocking plane).
        sync_config: an optional
            :class:`~torchmetrics_tpu.parallel.SyncConfig` — the worker
            quantizes the frozen buckets IN the background thread, so the
            codec's encode cost overlaps with ongoing updates exactly like
            the gather latency does (the bandwidth win compounds with the
            overlap win). Error-feedback residuals commit from the worker
            only after every bucket gathered; a failed or per-leaf-fallback
            attempt leaves them untouched.
        committer: called under :meth:`commit` with the synced state list —
            the seam where ``MetricCollection`` validates and atomically
            installs. Its exceptions propagate from ``commit()`` with nothing
            recorded as committed.
        label: telemetry identity for the ``async_sync`` event.
        noop: build an already-completed empty handle (the distributed-
            unavailable case — ``commit()`` is a cheap no-op barrier).
    """

    def __init__(
        self,
        states: Sequence[StateDict],
        reductions: Sequence[Mapping[str, Reduction]],
        process_group: Any = None,
        dist_sync_fn: Optional[Callable] = None,
        retry: Any = None,
        committer: Optional[Callable[[List[StateDict]], Any]] = None,
        label: str = "AsyncSyncHandle",
        noop: bool = False,
        sync_config: Optional[Any] = None,
    ) -> None:
        self.label = label
        self._committer = committer
        self._sync_config = sync_config
        self._states = [
            {k: (list(v) if isinstance(v, list) else v) for k, v in s.items()} for s in states
        ]
        self._reductions = [dict(r) for r in reductions]
        self._process_group = process_group
        self._dist_sync_fn = dist_sync_fn
        self._retry = retry
        self._result: Optional[List[StateDict]] = None
        self._error: Optional[BaseException] = None
        self._gather_s = 0.0
        self._wait_s = 0.0
        self._collectives = 0
        self._fallback = False
        self._dead_ranks: Dict[int, int] = {}
        self._committed = False
        # the request span active when the sync was LAUNCHED: commit() may run
        # much later (or on another thread) — the async_sync event must still
        # attribute the overlap window to the trace that started it
        self._trace = _obs_spans.current() if _observability._ACTIVE is not None else None
        self._done = threading.Event()
        self._payload_bytes = sum(_payload_bytes(s) for s in self._states)
        if noop:
            self._result = []
            self._states = []
            self._done.set()
            self._thread = None
            return
        self._thread = threading.Thread(
            target=self._work, name=f"tm-async-sync:{label}", daemon=True
        )
        self._thread.start()

    # ----------------------------------------------------------------- worker

    def _attempt(self) -> List[StateDict]:
        from . import sync as _sync  # late: sync.py imports coalesce at top level

        try:
            return _coalesce.coalesced_process_sync(
                self._states, self._reductions,
                process_group=self._process_group, dist_sync_fn=self._dist_sync_fn,
                sync_config=self._sync_config,
            )
        except _coalesce.CoalesceFallback:
            # per-leaf fallback preserved, in lockstep: every rank decodes the
            # same gathered metadata, so a real fleet falls back together
            self._fallback = True
            return [
                _sync._process_sync_per_leaf(
                    s, r, self._process_group, self._dist_sync_fn
                )
                for s, r in zip(self._states, self._reductions)
            ]

    def _work(self) -> None:
        rec = _observability._ACTIVE
        coll0 = rec.counters.value("sync_collectives") if rec is not None else 0
        t0 = time.perf_counter()
        try:
            if self._retry is None:
                self._result = self._attempt()
            else:
                self._result = self._retry.call(self._attempt, describe=self.label)
            # the coalesced plane's liveness ledger at commit time: non-empty
            # means this gather completed over a survivor quorum (degraded)
            self._dead_ranks = dict(_coalesce.dead_ranks())
            if rec is not None:
                # one successful sync entry, mirroring the blocking planes
                rec.counters.record_sync(self._payload_bytes)
                self._collectives = rec.counters.value("sync_collectives") - coll0
        except BaseException as err:  # noqa: BLE001 — re-raised at commit()
            self._error = err
        finally:
            self._gather_s = time.perf_counter() - t0
            self._done.set()

    # ------------------------------------------------------------------- API

    @classmethod
    def noop(cls, label: str = "AsyncSyncHandle") -> "AsyncSyncHandle":
        """An already-completed empty handle (nothing to sync — the
        distributed-unavailable no-op, kept so call sites stay uniform)."""
        return cls([], [], label=label, noop=True)

    @property
    def done(self) -> bool:
        """Whether the background gather finished (success or failure)."""
        return self._done.is_set()

    @property
    def committed(self) -> bool:
        return self._committed

    @property
    def overlap_pct(self) -> float:
        """How much of the gather's wall-clock the overlap hid (valid after
        :meth:`commit`): 100% means commit never blocked."""
        if self._gather_s <= 0:
            return 0.0
        return max(0.0, 1.0 - self._wait_s / self._gather_s) * 100.0

    @property
    def gather_s(self) -> float:
        return self._gather_s

    @property
    def wait_s(self) -> float:
        return self._wait_s

    @property
    def used_fallback(self) -> bool:
        return self._fallback

    @property
    def degraded(self) -> bool:
        """Whether this handle's gather completed over a survivor quorum —
        one or more ranks were dead (all-zero tombstone rows) when the
        coalesced collective ran. The synced states are still valid: they
        fold the survivors only, and the missing contribution reconciles
        when the rank rejoins."""
        return bool(self._dead_ranks)

    @property
    def dead_ranks(self) -> Dict[int, int]:
        """Rank → consecutive-degraded-sync count observed at gather time
        (a snapshot of :func:`~torchmetrics_tpu.parallel.coalesce.dead_ranks`)."""
        return dict(self._dead_ranks)

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the background gather finishes (no install)."""
        return self._done.wait(timeout)

    def result(self) -> List[StateDict]:
        """The synced state dicts (blocks; raises the worker's failure)."""
        self._done.wait()
        if self._error is not None:
            raise self._error
        assert self._result is not None
        return self._result

    def commit(self) -> Any:
        """Barrier + validate + atomic install.

        Waits for the gather, re-raises any worker failure with NOTHING
        installed (the caller stays at its last good state), then runs the
        committer (which validates before installing). Returns the
        committer's result (the synced state list when no committer is set).
        Telemetry records the overlap accounting on success. One-shot on
        SUCCESS only: a failed commit leaves the handle uncommitted —
        ``committed`` stays ``False``, and calling again re-raises the real
        error (or re-runs a committer that rejected validation) instead of a
        misleading "already ran".
        """
        if self._committed:
            raise TorchMetricsUserError(f"{self.label}: commit() already ran for this handle.")
        t0 = time.perf_counter()
        self._done.wait()
        self._wait_s = time.perf_counter() - t0
        if self._error is not None:
            raise self._error
        assert self._result is not None
        out = self._committer(self._result) if self._committer is not None else self._result
        self._committed = True
        rec = _observability._ACTIVE
        if rec is not None and self._states:
            ctx = None
            if self._trace is not None:
                ctx = _obs_spans.enter("commit", self.label, parent=self._trace)
            try:
                rec.record_async_sync(
                    self.label, self._gather_s, self._wait_s, self._payload_bytes,
                    collectives=self._collectives, fallback=self._fallback,
                )
            finally:
                if ctx is not None:
                    _obs_spans.exit(ctx)
        return out


def _payload_bytes(state: StateDict) -> int:
    total = 0
    for value in state.values():
        leaves = value if isinstance(value, list) else [value]
        for leaf in leaves:
            if hasattr(leaf, "size") and hasattr(leaf, "dtype"):
                total += int(leaf.size) * jnp.dtype(leaf.dtype).itemsize
    return total
