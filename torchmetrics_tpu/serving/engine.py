"""Multi-tenant serving engine: vmapped megabatch dispatch over stacked states.

The runtime layers below (reliability, telemetry, coalesced sync, AOT warm
start) all assume ONE training loop owning a handful of metric objects. A
metric *service* — the ROADMAP's "millions of users" north star — inverts the
shape: thousands of logical sessions, each a tiny per-tenant state, each fed a
trickle of traffic. One python dispatch per tenant per batch is the killer:
dispatch overhead (tens of microseconds on CPU, ~ms through a TPU tunnel)
dwarfs the per-tenant math, and one ``Metric`` object per tenant multiplies
trace/compile cost by the fleet size.

The DrJAX-style fix (PAPERS.md): hold all tenants of a *shape-class* as one
**stacked pytree** — every tensor-state leaf grows a leading tenant-row axis —
and update many tenants per XLA call:

- ``update(tenant_id, *batch)`` buffers traffic per shape-class (the
  shape/dtype signature of the batch — the same notion the compile counters
  and the AOT cache key on);
- a **megabatch** is up to ``megabatch_size`` distinct tenants' batches
  stacked along a leading axis, padded to a FIXED size with scratch rows so
  the dispatch signature never varies → **one XLA compile per (shape-class ×
  tag) regardless of tenant count**, provable from the compile counters
  (``tenants_per_dispatch`` and ``aot_cache_hits`` reconcile exactly);
- the program (``Metric._get_vupdate_fn``) gathers the addressed rows,
  ``jax.vmap``s the SAME single-metric update fold over them (running-mean
  weights ride a per-row count vector inside the stack), and scatters back —
  dispatched through ``Metric._donation_safe_dispatch`` so donation, the
  telemetry counters, and the AOT compile cache all apply unchanged.

Around the hot path: tenant admission with **LRU spill** of cold tenant state
to host memory (slots are finite; spilled tenants readmit transparently on
their next traffic, and spill/readmit wall-clock lands in the
``tenant_spill_us`` counter), per-tenant ``compute``/``reset``/checkpoint by
slicing the stack (checkpoints round-trip with ``Metric.load_state_dict``),
optional shard-by-tenant placement over a mesh axis
(``parallel.tenant_sharding``), and engine-level fault isolation
(``on_error="quarantine"``: a poisoned megabatch is rolled back and re-driven
one tenant at a time, quarantining only the offending tenant, never the
stack). With ``ServingConfig(aot_cache_dir=...)`` a freshly booted server
self-warms: the first megabatch per shape-class either loads a serialized
executable or compiles once and writes through (``write_on_miss``), so the
SECOND boot serves its first traffic from a cache load.

See ``docs/serving.md`` for the architecture walk-through and
``tools/serve_demo.py`` for a runnable end-to-end demo.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Any, Callable, Dict, Hashable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import aot as _aot
from .. import observability as _observability
from ..observability import spans as _spans
from ..aot import keys as _aot_keys
from ..parallel import quantize as _quantize
from . import durability as _durability
from ..metric import (
    TENANT_COUNT_KEY,
    Metric,
    _dual_fold,
    _stack_fold,
    window_defaults,
    window_stack_geometry,
    window_tier,
)
from ..utilities.exceptions import StateCorruptionError, TorchMetricsUserError

StateDict = Dict[str, Any]

_ON_ERROR_MODES = ("raise", "quarantine")


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Knobs for one :class:`ServingEngine`.

    Args:
        capacity: resident tenant slots per shape-class stack. Each stack
            allocates ``capacity + 1`` rows — the extra row is the scratch
            slot megabatch padding scatters into (pick ``capacity + 1``
            divisible by the mesh axis size when sharding).
        megabatch_size: tenant rows per dispatch. Every megabatch is padded
            to exactly this many rows so each shape-class compiles ONE
            program; undersized flushes burn scratch rows (cheap), oversized
            queues split into several dispatches.
        auto_flush: dispatch a shape-class as soon as a full megabatch of
            distinct tenants is pending (otherwise only :meth:`ServingEngine.
            flush` dispatches).
        spill: evict the least-recently-used tenant's state rows to host
            memory when a stack is full (off: admission past capacity raises).
        spill_codec: compress spilled tenant state with the quantized sync
            plane's codecs (``"none"`` — exact, the default — ``"bf16"`` or
            ``"int8"``, ``parallel/quantize.py``): float32/float64 rows
            shrink ~2-4x in host memory and the spill/readmit copies move
            fewer bytes (``tenant_spill_us`` drops with them). Integer/bool
            count rows always stay bitwise exact. Each spill→readmit cycle
            is one bounded quantization round-trip (error <= block_range/510
            for int8, relative 2^-8 for bf16) — repeated eviction of the
            same cold tenant compounds it, so keep the exact default when
            per-tenant values must be reproducible to the last bit.
        on_error: ``"raise"`` propagates any dispatch failure (no rollback
            copies on the hot path — the default); ``"quarantine"`` backs the
            stack up before every megabatch, rolls back on failure, re-drives
            the entries one tenant at a time, and quarantines only the
            offending tenant(s).
        max_tenants_per_sec: admission rate limit — a token bucket refilled
            at this rate (burst capacity = one second's tokens) gates
            :meth:`ServingEngine.update`; a batch arriving with the bucket
            empty is SHED (``update`` returns ``False``, the
            ``serve_rejected`` counter/event fires) instead of queueing into
            LRU-spill thrash. ``None`` (default) admits everything.
        clock: monotonic-seconds source for the admission token bucket
            (default ``time.monotonic``). Injecting a virtual clock makes
            admission/shed decisions exactly reproducible — the chaos soak
            harness (``torchmetrics_tpu.chaos``) advances one per simulated
            step, and a scripted *backwards* jump models real clock skew
            (a negative delta drains tokens, so the bucket sheds until the
            clock catches up). Ignored when ``max_tenants_per_sec`` is None.
        aot_cache_dir: activate the AOT compile-cache plane process-wide at
            engine construction, pointed at this directory, with
            ``write_on_miss`` below — the self-warming boot path (a second
            boot loads executables instead of compiling). ``None`` leaves
            whatever plane is active untouched.
        write_on_miss: with ``aot_cache_dir``: write freshly compiled
            megabatch programs through to the cache so the NEXT boot is warm.
        sharding: a ``jax.sharding.Sharding`` applied to every stack leaf
            (leading axis = tenant rows) — see ``parallel.tenant_sharding``.
        window: give every tenant a SLIDING WINDOW of this many updates
            instead of a forever accumulator ("each tenant's last-hour
            accuracy"). The per-tenant state uses the constant-memory
            dual/two-stack window tiers (``docs/streaming.md``), so the
            stacked leaves grow by a small constant factor — NOT ×window —
            and updates stay one vmapped megabatch dispatch (tag
            ``vwupdate``). Metrics whose reduce-tags only admit the ring
            tier are rejected (a per-tenant ring would multiply the stack by
            the window length). Per-tenant values are exact over the
            trailing ``covered_updates(tenant)`` updates (window-hop
            semantics, same contract as ``SlidingWindow``).
        window_tier: ``"auto"`` derives dual/two_stack from the template's
            reduce-tags; force ``"two_stack"`` for a tighter hop (one pane
            instead of one window) on sum/mean metrics.
        window_pane: two-stack pane length override (default: window-
            independent depth of ``metric.WINDOW_STACK_DEPTH`` panes).
        journal: directory for a write-ahead traffic journal
            (``serving/durability.py``): every admitted batch appends a
            ``(seq, tenant_id, batch-digest, clock)`` record BEFORE it is
            queued for dispatch, so :meth:`ServingEngine.restore` + journal
            replay reaches the exact pre-crash state. ``None`` (default)
            journals nothing. Only str/int tenant ids can be journaled.
        journal_fsync_every: fsync the journal every this-many appends (plus
            on rotation/close). ``1`` is RPO=0 — no admitted batch can be
            lost; larger values batch fsyncs and bound the loss window at
            ``journal_fsync_every - 1`` records.
        journal_segment_records: rotate to a fresh journal segment file after
            this many records (bounds per-file recovery scan cost).
        retain_snapshots: keep only the newest N snapshot generations after
            each :meth:`ServingEngine.snapshot` (and drop journal segments
            every retained snapshot already covers). ``None`` (default)
            retains everything — unbounded disk growth under periodic
            snapshotting. The newest generation is never pruned, and the
            journal tail past the OLDEST retained snapshot's seq cursor is
            always kept, so restore + replay from any retained generation
            still reaches the exact pre-crash state.
    """

    capacity: int = 1024
    megabatch_size: int = 256
    auto_flush: bool = True
    spill: bool = True
    spill_codec: str = "none"
    on_error: str = "raise"
    max_tenants_per_sec: Optional[float] = None
    clock: Optional[Callable[[], float]] = None
    aot_cache_dir: Optional[str] = None
    write_on_miss: bool = True
    sharding: Any = None
    window: Optional[int] = None
    window_tier: str = "auto"
    window_pane: Optional[int] = None
    journal: Optional[str] = None
    journal_fsync_every: int = 1
    journal_segment_records: int = 512
    retain_snapshots: Optional[int] = None

    def __post_init__(self) -> None:
        if self.capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {self.capacity}")
        if self.window is not None and not (isinstance(self.window, int) and self.window > 0):
            raise ValueError(f"window must be a positive integer (or None), got {self.window}")
        if self.window_tier not in ("auto", "dual", "two_stack"):
            raise ValueError(
                f"window_tier must be 'auto', 'dual' or 'two_stack', got {self.window_tier!r} "
                "(the ring tier cannot be stacked per tenant — its rows scale with the window)"
            )
        if self.max_tenants_per_sec is not None and not self.max_tenants_per_sec > 0:
            raise ValueError(
                f"max_tenants_per_sec must be > 0 (or None), got {self.max_tenants_per_sec}"
            )
        if self.clock is not None and not callable(self.clock):
            raise ValueError(f"clock must be a zero-arg callable returning seconds, got {self.clock!r}")
        if self.spill_codec not in _quantize.CODEC_NAMES:
            raise ValueError(
                f"spill_codec must be one of {sorted(_quantize.CODEC_NAMES)}, "
                f"got {self.spill_codec!r}"
            )
        if self.megabatch_size < 1:
            raise ValueError(f"megabatch_size must be >= 1, got {self.megabatch_size}")
        if self.megabatch_size > self.capacity:
            # every megabatch member needs a resident slot for the duration of
            # its dispatch — a chunk wider than the stack cannot be seated
            raise ValueError(
                f"megabatch_size ({self.megabatch_size}) must be <= capacity ({self.capacity})"
            )
        if self.on_error not in _ON_ERROR_MODES:
            raise ValueError(f"Expected `on_error` to be one of {_ON_ERROR_MODES}, got {self.on_error!r}")
        if self.journal is not None and not isinstance(self.journal, str):
            raise ValueError(f"journal must be a directory path (or None), got {self.journal!r}")
        if self.journal_fsync_every < 1:
            raise ValueError(f"journal_fsync_every must be >= 1, got {self.journal_fsync_every}")
        if self.journal_segment_records < 1:
            raise ValueError(
                f"journal_segment_records must be >= 1, got {self.journal_segment_records}"
            )
        if self.retain_snapshots is not None and self.retain_snapshots < 1:
            raise ValueError(
                f"retain_snapshots must be >= 1 (or None for unbounded), "
                f"got {self.retain_snapshots}"
            )


class _Tenant:
    """Host-side bookkeeping for one logical session."""

    __slots__ = ("tenant_id", "shape_key", "slot", "update_count", "last_touch",
                 "pending", "quarantined", "error", "spilled", "unfolded", "trace")

    def __init__(self, tenant_id: Hashable) -> None:
        self.tenant_id = tenant_id
        self.shape_key: Optional[str] = None
        self.slot: Optional[int] = None  # row in the shape-class stack; None = not resident
        self.update_count = 0
        self.last_touch = 0
        self.pending = 0  # queued batches not yet dispatched
        self.quarantined = False
        self.error: Optional[str] = None
        # host copy of the state rows while evicted: {"state": {name: np}, "count": float}
        self.spilled: Optional[Dict[str, Any]] = None
        # journal seqs admitted but not yet folded (journaling engines only);
        # a quarantine rolls these back and records them so replay skips them
        self.unfolded: List[int] = []
        # span active at the LAST admission (telemetry-only): the megabatch
        # dispatch links its fan-in back to the request traces it folds
        self.trace: Optional[Any] = None

    @property
    def resident(self) -> bool:
        return self.slot is not None


class _ShapeClass:
    """One stacked pytree + its traffic queue: all tenants whose batches share
    a shape/dtype signature."""

    __slots__ = ("key", "stacked", "free", "slot_tenant", "queue", "pad_example", "dispatches")

    def __init__(self, key: str, stacked: StateDict, capacity: int, pad_example: Tuple[tuple, dict]) -> None:
        self.key = key
        self.stacked = stacked  # tensor states + TENANT_COUNT_KEY, leaves (capacity+1, ...)
        self.free: List[int] = list(range(capacity))  # row `capacity` is the scratch slot
        self.slot_tenant: Dict[int, Hashable] = {}
        self.queue: deque = deque()  # (tenant_id, args, kwargs) in arrival order
        self.pad_example = pad_example  # zero batch used for megabatch padding
        self.dispatches = 0


class ServingEngine:
    """Sessionized multi-tenant metric serving over one metric template.

    Example (conceptual)::

        engine = ServingEngine(MulticlassAccuracy(num_classes=10, validate_args=False),
                               ServingConfig(capacity=8192, megabatch_size=256))
        engine.update("user-1", preds, target)     # buffered, auto-dispatched
        engine.flush()                             # drain partial megabatches
        engine.compute("user-1")                   # slice one tenant's value
        engine.state_dict("user-1")                # per-tenant checkpoint

    The template metric must hold only static-shape tensor states (no concat
    lists) with its jitted dispatch path enabled; the engine works on a
    private clone, so the caller's object is never touched.
    """

    def __init__(self, template: Metric, config: Optional[ServingConfig] = None) -> None:
        if not isinstance(template, Metric):
            raise TorchMetricsUserError(f"ServingEngine needs a Metric template, got {type(template).__name__}")
        self.config = config or ServingConfig()
        if template._list_state_names:
            raise TorchMetricsUserError(
                f"{type(template).__name__} holds dynamic-length concat states and cannot be "
                "served from a stacked pytree; use a binned/static-shape variant."
            )
        if not template._enable_jit:
            raise TorchMetricsUserError("ServingEngine requires a jit-enabled metric template (jit=True).")
        # private clone: the engine's dispatches must not disturb the caller's
        # object, and per-metric reliability retry is incompatible with the
        # stacked dispatch (its exhausted-budget restore writes into
        # `_state`) — fault tolerance is engine-level (on_error="quarantine")
        self._metric = template.clone()
        self._metric._reliability = None
        self._metric._fault_hook = None
        self._defaults_t, _ = self._metric._split_tensor_list(self._metric.init_state())
        # windowed tenants: constant-memory dual/two-stack window state per
        # row (docs/streaming.md "Dual-form windows") — the ring tier is
        # refused because its per-row cost is ×window, exactly the HBM
        # explosion ServingConfig(window=) exists to avoid
        self._window = self.config.window
        self._wtier: Optional[str] = None
        self._wpane: Optional[int] = None
        self._wdepth: int = 0
        self._wparam_arr = None  # lazy device scalar (window / pane length)
        if self._window is not None:
            tier = self.config.window_tier
            if tier == "auto":
                tier = window_tier(self._metric)
            if tier == "ring":
                raise TorchMetricsUserError(
                    f"{type(template).__name__}'s reduce-tags only admit the 'ring' window "
                    "tier (custom _merge / cat states), whose per-tenant cost is ×window — "
                    "windowed serving needs a dual/two-stack-admissible metric "
                    "(see the window-tier column in docs/serving.md)."
                )
            self._metric._check_windowable(tier)
            self._wtier = tier
            if tier == "two_stack":
                self._wpane, self._wdepth = window_stack_geometry(self._window, self.config.window_pane)
            self._row_defaults = window_defaults(
                self._metric, self._window, tier, self._wpane
            )
        else:
            self._row_defaults = self._defaults_t
        self._classes: Dict[str, _ShapeClass] = {}
        self._tenants: Dict[Hashable, _Tenant] = {}
        self._touch = itertools.count(1)
        # (treedef, leaf-metadata) → shape-class key. The full signature string
        # costs ~30µs to build; at fleet ingest rates that is the hot path, so
        # repeat shapes resolve through this exact-metadata memo instead.
        self._sig_cache: Dict[Any, str] = {}
        #: engine-fault injection seam (tests): called with the megabatch's
        #: tenant ids right before each dispatch; raising fails the dispatch
        self._fault_hook: Optional[Callable[[List[Hashable]], None]] = None
        self.stats: Dict[str, int] = {
            "dispatches": 0, "tenant_rows": 0, "padded_rows": 0, "flushes": 0,
            "spills": 0, "readmissions": 0, "spill_ns": 0, "spill_bytes_saved": 0,
            "quarantined": 0,
            "dropped_batches": 0, "rejected_batches": 0, "window_rotations": 0,
        }
        # admission token bucket (ServingConfig.max_tenants_per_sec): starts
        # full (one second's burst, floored at one whole token so sub-1/s
        # rates can admit at all); ServingConfig(clock=) injects a virtual
        # time source (chaos soak, deterministic operators' drills)
        self._clock: Callable[[], float] = self.config.clock or time.monotonic
        self._rl_tokens = (
            max(float(self.config.max_tenants_per_sec), 1.0)
            if self.config.max_tenants_per_sec is not None else 0.0
        )
        self._rl_last: Optional[float] = None
        # vmapped batch-compute support memo: None = untried, False = this
        # metric's _compute cannot vmap (host path / untraceable) — eager wins
        self._vcompute_ok: Optional[bool] = None
        # durability plane (serving/durability.py): write-ahead journal handle
        # plus the sequence cursor pair that makes restore+replay exactly-once
        # (_next_seq = next admission's record, _applied_seq = highest folded)
        self._journal: Optional[_durability.TrafficJournal] = None
        self._next_seq = 1
        self._applied_seq = 0
        self._replaying = False
        self._replay_clock: Optional[float] = None
        if self.config.journal is not None:
            self._journal = _durability.TrafficJournal(
                self.config.journal,
                fsync_every=self.config.journal_fsync_every,
                segment_records=self.config.journal_segment_records,
            )
        if self.config.aot_cache_dir is not None:
            # the self-warming boot path: every fresh megabatch compile writes
            # through, so the next boot of this server loads instead
            _aot.enable(config=_aot.AotConfig(
                cache_dir=self.config.aot_cache_dir,
                write_on_miss=self.config.write_on_miss,
            ))

    # ------------------------------------------------------------- shape-classes

    @staticmethod
    def _shape_key(args: tuple, kwargs: dict) -> str:
        sig, tree = _aot_keys.dispatch_signature_parts((args, kwargs))
        return f"{sig}#{tree}"

    def _shape_key_cached(self, args: tuple, kwargs: dict) -> str:
        """Shape-class key with an exact-metadata fast path: the memo key is
        the pytree structure plus every leaf's (shape, dtype, weak) — the
        same facts the signature string encodes, compared without string
        building. A never-seen combination falls through to the full key."""
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        meta = tuple(
            (np.shape(leaf), getattr(leaf, "dtype", None) or type(leaf),
             bool(getattr(leaf, "weak_type", False)))
            for leaf in leaves
        )
        ck = (treedef, meta)
        key = self._sig_cache.get(ck)
        if key is None:
            key = self._shape_key(args, kwargs)
            self._sig_cache[ck] = key
        return key

    def _fresh_stack(self) -> StateDict:
        """A default-valued stack with the engine's exact layout (rows =
        capacity + scratch, every tensor leaf + :data:`TENANT_COUNT_KEY`,
        sharding applied) — the ONE definition of the stacked calling
        convention, shared by shape-class creation and window rotation."""
        rows = self.config.capacity + 1  # + the scratch row padding scatters into
        stacked: StateDict = {
            name: jnp.repeat(jnp.asarray(leaf)[None], rows, axis=0)
            for name, leaf in self._row_defaults.items()
        }
        stacked[TENANT_COUNT_KEY] = jnp.zeros((rows,), jnp.float32)
        if self.config.sharding is not None:
            stacked = jax.device_put(stacked, self.config.sharding)
        return stacked

    def _ensure_class(self, key: str, args: tuple, kwargs: dict) -> _ShapeClass:
        cls = self._classes.get(key)
        if cls is not None:
            return cls
        stacked = self._fresh_stack()
        # zero pytree with the class's exact leaf shapes/dtypes — the values
        # never reach a real tenant (pad rows scatter into the scratch slot)
        pad = jax.tree.map(lambda leaf: np.zeros(np.shape(leaf), _np_dtype(leaf)), (args, kwargs))
        cls = _ShapeClass(key, stacked, self.config.capacity, pad)
        self._classes[key] = cls
        return cls

    # ------------------------------------------------------------------ tenants

    def _tenant(self, tenant_id: Hashable) -> _Tenant:
        t = self._tenants.get(tenant_id)
        if t is None:
            t = _Tenant(tenant_id)
            self._tenants[tenant_id] = t
        return t

    def _admit(self, t: _Tenant, cls: _ShapeClass, pinned: frozenset = frozenset()) -> None:
        """Give ``t`` a stack slot, evicting the LRU resident if needed, and
        upload its spilled state (readmission) or a fresh default row.
        ``pinned`` tenants (the megabatch currently being seated) are never
        eviction candidates — seating a late member must not unseat an early
        one mid-dispatch."""
        if t.resident:
            return
        if not cls.free:
            self._evict_lru(cls, pinned)
        slot = cls.free.pop()
        cls.slot_tenant[slot] = t.tenant_id
        t.slot = slot
        if t.spilled is not None:
            t0 = time.perf_counter()
            host = t.spilled
            # codec-encoded spills dequantize here (exact spills pass through)
            for name, value in _quantize.decode_spill_state(host["state"]).items():
                cls.stacked[name] = cls.stacked[name].at[slot].set(jnp.asarray(value))
            cls.stacked[TENANT_COUNT_KEY] = cls.stacked[TENANT_COUNT_KEY].at[slot].set(
                jnp.float32(host["count"])
            )
            dur = time.perf_counter() - t0
            t.spilled = None
            self.stats["readmissions"] += 1
            self.stats["spill_ns"] += int(dur * 1e9)
            rec = _observability._ACTIVE
            if rec is not None:
                rec.record_tenant_spill(
                    self._metric, dur, _quantize.spill_state_bytes(host["state"]), readmit=True
                )
        else:
            # the slot may hold a previously evicted tenant's stale rows
            for name, leaf in self._row_defaults.items():
                cls.stacked[name] = cls.stacked[name].at[slot].set(jnp.asarray(leaf))
            cls.stacked[TENANT_COUNT_KEY] = cls.stacked[TENANT_COUNT_KEY].at[slot].set(0.0)

    def _evict_lru(self, cls: _ShapeClass, pinned: frozenset = frozenset()) -> None:
        if not self.config.spill:
            raise TorchMetricsUserError(
                f"shape-class stack is full ({self.config.capacity} resident tenants) and "
                "spill is disabled — raise ServingConfig.capacity or enable spill."
            )
        # least-recently-touched unpinned resident; tenants with queued
        # traffic are evicted only when every candidate has traffic pending
        # (they would readmit within the same flush — correct, just slower)
        candidates = [
            self._tenants[tid] for tid in cls.slot_tenant.values() if tid not in pinned
        ]
        if not candidates:  # unreachable: megabatch_size <= capacity by config
            raise TorchMetricsUserError(
                "every resident tenant is part of the megabatch being seated — "
                "megabatch_size must not exceed capacity"
            )
        victim = min(candidates, key=lambda t: (t.pending > 0, t.last_touch))
        self._spill(victim, cls)

    def _spill(self, t: _Tenant, cls: _ShapeClass) -> None:
        """Move one resident tenant's state rows to host memory (LRU spill).

        The row reads are a deliberate device→host transfer — counted like
        ``state_dict``'s — but all byte accounting is metadata-only
        (shape × itemsize), never an extra device read."""
        assert t.slot is not None
        t0 = time.perf_counter()
        state = {name: np.asarray(cls.stacked[name][t.slot]) for name in self._row_defaults}
        count = float(np.asarray(cls.stacked[TENANT_COUNT_KEY][t.slot]))
        # opt-in codec: float rows block-quantize before parking on host —
        # 2-4x fewer host bytes per cold tenant, count rows stay bitwise
        enc = _quantize.encode_spill_state(state, self.config.spill_codec)
        dur = time.perf_counter() - t0
        t.spilled = {"state": enc, "count": count}
        cls.slot_tenant.pop(t.slot, None)
        cls.free.append(t.slot)
        t.slot = None
        self.stats["spills"] += 1
        self.stats["spill_ns"] += int(dur * 1e9)
        nbytes = _quantize.spill_state_bytes(enc)
        raw_bytes = _state_bytes(state)
        self.stats["spill_bytes_saved"] += max(0, raw_bytes - nbytes)
        rec = _observability._ACTIVE
        if rec is not None:
            rec.record_tenant_spill(self._metric, dur, nbytes)
            # the device->host readback moved the FULL-width rows; the codec
            # shrinks what stays resident on host, not what crossed the wire
            rec.record_d2h("tenant_spill", raw_bytes, metric=self._metric)

    # ------------------------------------------------------------------ ingest

    def _admit_rate(self) -> bool:
        """Token-bucket admission: refill at ``max_tenants_per_sec``, burst
        capacity one second's tokens — floored at ONE token, because admission
        spends a whole token and a sub-1/s rate capped below 1.0 could never
        admit anything (a permanent outage, not a limit). ``True`` = admitted
        (one token spent)."""
        rate = self.config.max_tenants_per_sec
        if rate is None:
            return True
        # replay drives the bucket with the JOURNALED admission clock, so the
        # standby's token state converges on the primary's exactly (refills
        # compose: two refills over [t0,t1],[t1,t2] equal one over [t0,t2])
        now = self._replay_clock if self._replaying and self._replay_clock is not None else self._clock()
        if self._rl_last is None:
            self._rl_last = now
        cap = max(float(rate), 1.0)
        self._rl_tokens = min(cap, self._rl_tokens + (now - self._rl_last) * float(rate))
        self._rl_last = now
        if self._rl_tokens >= 1.0:
            self._rl_tokens -= 1.0
            return True
        return False

    def update(self, tenant_id: Hashable, *args: Any, **kwargs: Any) -> bool:
        """Route one ``(tenant_id, batch)`` into its shape-class megabatch
        queue (dispatched when a full megabatch accumulates, at
        :meth:`flush`, or before any per-tenant read).

        Returns ``True`` when the batch was admitted. With
        ``ServingConfig(max_tenants_per_sec=...)`` set, an over-rate batch is
        SHED — ``False`` comes back, the ``serve_rejected`` counter/event
        fires, and no tenant state/queue/LRU bookkeeping is touched — so
        overload degrades to dropped samples instead of spill thrash."""
        if not self._admit_rate():
            self.stats["rejected_batches"] += 1
            rec = _observability._ACTIVE
            if rec is not None:
                rec.record_serve_rejected(self._metric, tenant_id)
            return False
        t = self._tenant(tenant_id)
        if t.quarantined:
            raise TorchMetricsUserError(
                f"tenant {tenant_id!r} is quarantined (last error: {t.error}); reset() lifts it."
            )
        args, kwargs = self._metric._prepare_inputs(*args, **kwargs)
        key = self._shape_key_cached(args, kwargs)
        if t.shape_key is None:
            t.shape_key = key
        elif t.shape_key != key:
            raise TorchMetricsUserError(
                f"tenant {tenant_id!r} sent a batch of shape-class {key} but its state lives "
                f"in shape-class {t.shape_key}; per-tenant traffic must keep a stable "
                "batch shape/dtype (pad or bucket inputs)."
            )
        cls = self._ensure_class(key, args, kwargs)
        self._admit(t, cls)
        if self._journal is not None and not self._replaying:
            # write-ahead: the record must be durable-ordered BEFORE the batch
            # can dispatch; digest covers the prepared inputs, t the admission
            # clock (bucket replay), seq the exactly-once dedup cursor
            seq = self._next_seq
            synced = self._journal.append(
                tenant_id,
                _durability.batch_digest(args, kwargs),
                seq,
                t=self._rl_last if self.config.max_tenants_per_sec is not None else 0.0,
            )
            self._next_seq = seq + 1
            self._applied_seq = seq
            t.unfolded.append(seq)
            rec = _observability._ACTIVE
            if rec is not None:
                rec.counters.record_journal_append(synced)
        if _observability._ACTIVE is not None:
            ctx = _spans.current()
            if ctx is not None:
                t.trace = ctx
        cls.queue.append((tenant_id, args, kwargs))
        t.pending += 1
        t.last_touch = next(self._touch)
        if self.config.auto_flush and len(cls.queue) >= self.config.megabatch_size:
            self._dispatch_chunk(cls)
        return True

    def flush(self) -> int:
        """Dispatch every pending megabatch (partial ones padded with scratch
        rows). Returns the number of tenant batches served."""
        served = 0
        self.stats["flushes"] += 1
        for cls in self._classes.values():
            while cls.queue:
                served += self._dispatch_chunk(cls)
        return served

    # ---------------------------------------------------------------- dispatch

    def _dispatch_chunk(self, cls: _ShapeClass) -> int:
        """Pull up to ``megabatch_size`` DISTINCT tenants' batches off the
        queue and serve them with one vmapped dispatch. A tenant with several
        queued batches contributes one per chunk (the per-row fold is one
        batch deep); the rest go back to the queue front in order."""
        entries: List[Tuple[Hashable, tuple, dict]] = []
        seen: set = set()
        holdback: List[Tuple[Hashable, tuple, dict]] = []
        while cls.queue and len(entries) < self.config.megabatch_size:
            tid, args, kwargs = cls.queue.popleft()
            t = self._tenants[tid]
            if t.quarantined:
                t.pending -= 1
                self.stats["dropped_batches"] += 1
                continue
            if tid in seen:
                holdback.append((tid, args, kwargs))
                continue
            seen.add(tid)
            entries.append((tid, args, kwargs))
        cls.queue.extendleft(reversed(holdback))
        if not entries:
            return 0
        if self.config.on_error == "raise":
            self._dispatch_rows(cls, entries)
            return len(entries)
        # quarantine mode: back up, roll back on failure, isolate per tenant.
        # Seating happens INSIDE _dispatch_rows (readmissions decode spilled
        # rows, evictions spill LRU residents), so the rollback must restore
        # the seating bookkeeping alongside the stack values — restoring only
        # the arrays would leave a readmitted tenant marked resident over a
        # slot whose rolled-back rows belong to the evicted victim, and the
        # per-tenant re-drive would then fold healthy batches into the wrong
        # tenant's counts (the spill-codec × quarantine regression test pins
        # this).
        backup = {k: jnp.copy(v) for k, v in cls.stacked.items()}
        seating = self._seating_snapshot(cls, entries)
        try:
            self._dispatch_rows(cls, entries)
            return len(entries)
        except Exception:
            cls.stacked = backup
            self._restore_seating(cls, seating)
        served = 0
        for entry in entries:
            single_backup = {k: jnp.copy(v) for k, v in cls.stacked.items()}
            single_seating = self._seating_snapshot(cls, [entry])
            try:
                self._dispatch_rows(cls, [entry])
                served += 1
            except Exception as err:  # noqa: BLE001 — quarantine, never poison the stack
                cls.stacked = single_backup
                self._restore_seating(cls, single_seating)
                self._quarantine(entry[0], err)
        return served

    def _seating_snapshot(
        self, cls: _ShapeClass, entries: List[Tuple[Hashable, tuple, dict]]
    ) -> Tuple[Dict[int, Hashable], List[int], Dict[Hashable, Tuple[Optional[int], Any]]]:
        """Rollback unit for the seating a dispatch may perform: the class's
        slot maps plus (slot, spilled) for every tenant seating can touch —
        current residents (eviction victims) and the megabatch members
        (readmissions). Spilled dicts are never mutated in place, so holding
        the reference is enough."""
        tids = set(cls.slot_tenant.values()) | {tid for tid, _, _ in entries}
        return (
            dict(cls.slot_tenant),
            list(cls.free),
            {tid: (self._tenants[tid].slot, self._tenants[tid].spilled) for tid in tids},
        )

    def _restore_seating(
        self,
        cls: _ShapeClass,
        snap: Tuple[Dict[int, Hashable], List[int], Dict[Hashable, Tuple[Optional[int], Any]]],
    ) -> None:
        slot_tenant, free, per_tenant = snap
        cls.slot_tenant = dict(slot_tenant)
        cls.free = list(free)
        for tid, (slot, spilled) in per_tenant.items():
            t = self._tenants[tid]
            t.slot = slot
            t.spilled = spilled

    def _dispatch_rows(self, cls: _ShapeClass, entries: List[Tuple[Hashable, tuple, dict]]) -> None:
        """One megabatch dispatch: stack entries + pad to the fixed size,
        donate the stack through ``_donation_safe_dispatch`` (telemetry + AOT
        planes apply), commit the new stack and the host bookkeeping."""
        m = self.config.megabatch_size
        real = len(entries)
        scratch = self.config.capacity  # the reserved pad row
        # seat every member first, pinned against each other: admitting a late
        # member must never evict an earlier one out of this very megabatch
        # (possible when capacity-many chunk members have the oldest touches)
        pinned = frozenset(tid for tid, _, _ in entries)
        for tid, _, _ in entries:
            t = self._tenants[tid]
            if not t.resident:
                self._admit(t, cls, pinned)
        idx = np.full((m,), scratch, np.int32)
        batches = []
        for i, (tid, args, kwargs) in enumerate(entries):
            idx[i] = self._tenants[tid].slot
            batches.append((args, kwargs))
        batches.extend([cls.pad_example] * (m - real))
        mb_args, mb_kwargs = jax.tree.map(_stack_leaves, *batches)
        idx_dev = jnp.asarray(idx)
        if self._fault_hook is not None:
            self._fault_hook([tid for tid, _, _ in entries])
        if self._wtier is not None:
            fn = self._metric._get_vwupdate_fn(self._wtier, self._wdepth)
            warr = self._wparam()
            new_stacked = self._metric._donation_safe_dispatch(
                "vwupdate",
                lambda t, n: fn(t, n, warr, idx_dev, mb_args, mb_kwargs),
                cls.stacked,
                inputs=((warr, idx_dev, mb_args, mb_kwargs), {}),
                jitted=fn,
                owner=cls.stacked,  # defensive: rollback lands in the stack, not _state
            )
        else:
            fn = self._metric._get_vupdate_fn()
            new_stacked = self._metric._donation_safe_dispatch(
                "vupdate",
                lambda t, n: fn(t, n, idx_dev, mb_args, mb_kwargs),
                cls.stacked,
                inputs=((idx_dev, mb_args, mb_kwargs), {}),
                jitted=fn,
                owner=cls.stacked,  # defensive: rollback lands in the stack, not _state
            )
        cls.stacked = new_stacked
        cls.dispatches += 1
        self.stats["dispatches"] += 1
        self.stats["tenant_rows"] += real
        self.stats["padded_rows"] += m - real
        hop = self._window if self._wtier == "dual" else self._wpane
        rotations = 0
        for tid, _, _ in entries:
            t = self._tenants[tid]
            t.update_count += 1
            t.pending -= 1
            if t.unfolded:
                del t.unfolded[0]  # this fold retires its write-ahead admission
            if self._wtier is not None and t.update_count % hop == 0:
                rotations += 1
        self.stats["window_rotations"] += rotations
        rec = _observability._ACTIVE
        if rec is not None:
            links: List[str] = []
            for tid, _, _ in entries:
                t = self._tenants[tid]
                if t.trace is not None:
                    if len(links) < 8:  # bounded: a megabatch folds many requests
                        links.append(t.trace.trace_id)
                    t.trace = None
            rec.record_serve_dispatch(self._metric, real, m - real, links=links)
            if self._wtier is not None:
                rec.counters.record_window_rolls(real, rotations)

    def _quarantine(self, tenant_id: Hashable, exc: BaseException) -> None:
        t = self._tenants[tenant_id]
        err_text = f"{type(exc).__name__}: {exc}"[:240]
        synced: Optional[bool] = None
        if self._journal is not None and not self._replaying:
            # the quarantine is a state transition the WAL must carry: a
            # standby replaying this journal has no fault environment, so
            # without this record it would fold the very batches the primary
            # rolled back and come up with the tenant live — state divergence.
            # The record names the rolled-back admission seqs (everything this
            # tenant admitted but never folded); replay skips those and
            # re-applies the flag instead.
            # the record takes a seq from the admission counter (the journal
            # enforces strict seq ordering) but does NOT advance _applied_seq:
            # that cursor names the highest applied ADMISSION, and callers key
            # their retention buffers on it right after update() returns
            seq = self._next_seq
            synced = self._journal.append(
                tenant_id, err_text, seq, kind="quarantine", rolled_back=list(t.unfolded),
            )
            self._next_seq = seq + 1
            t.unfolded = []
        t.quarantined = True
        t.error = err_text
        # drop the tenant's remaining queued batches everywhere
        if t.shape_key is not None and t.shape_key in self._classes:
            cls = self._classes[t.shape_key]
            kept = [e for e in cls.queue if e[0] != tenant_id]
            self.stats["dropped_batches"] += len(cls.queue) - len(kept)
            cls.queue = deque(kept)
        t.pending = 0
        self.stats["quarantined"] += 1
        rec = _observability._ACTIVE
        if rec is not None:
            if synced is not None:
                rec.counters.record_journal_append(synced)
            rec.record_quarantine(repr(tenant_id), "vupdate", "quarantined", exc, t.update_count)

    # ---------------------------------------------------------------- reads

    def _wparam(self):
        """The traced window parameter (window length for dual, pane length
        for two-stack) as a cached device scalar."""
        if self._wparam_arr is None:
            wparam = self._window if self._wtier == "dual" else self._wpane
            self._wparam_arr = jax.device_put(np.float32(wparam))
        return self._wparam_arr

    def _fold_row(self, row_state: StateDict) -> StateDict:
        """Collapse one tenant's windowed row into a compute-ready state
        (identity for unwindowed engines)."""
        if self._wtier is None:
            return row_state
        if self._wtier == "dual":
            return _dual_fold(dict(self._metric._reductions), self._defaults_t, row_state)
        return _stack_fold(
            dict(self._metric._reductions), self._defaults_t, self._wdepth,
            row_state, self._wparam(),
        )

    def covered_updates(self, tenant_id: Hashable) -> int:
        """How many trailing updates one tenant's value folds (the windowed
        serving analogue of ``SlidingWindow.covered_updates``; the tenant's
        whole history when the engine is unwindowed)."""
        n = self._require(tenant_id).update_count
        if self._wtier == "dual":
            return (self._window if n >= self._window else 0) + n % self._window
        if self._wtier == "two_stack":
            full_panes, cc = divmod(n, self._wpane)
            return min(full_panes, self._wdepth) * self._wpane + cc
        return n

    def _tenant_state(self, t: _Tenant) -> StateDict:
        """One tenant's (window-layout) state dict — a stack slice when
        resident, the host copy when spilled (no readmission: reads never
        churn the LRU)."""
        if t.spilled is not None:
            return {
                k: jnp.asarray(v)
                for k, v in _quantize.decode_spill_state(t.spilled["state"]).items()
            }
        if t.slot is None:
            return {k: jnp.asarray(v) for k, v in self._row_defaults.items()}
        cls = self._classes[t.shape_key]
        return {name: cls.stacked[name][t.slot] for name in self._row_defaults}

    def compute(self, tenant_id: Hashable) -> Any:
        """One tenant's metric value, by slicing its rows out of the stack
        (pending traffic is flushed first so the value is current; windowed
        engines fold the row's dual/two-stack window first)."""
        t = self._require(tenant_id)
        if t.quarantined:
            raise TorchMetricsUserError(
                f"tenant {tenant_id!r} is quarantined (last error: {t.error}); reset() lifts it."
            )
        if t.pending:
            self.flush()
        return self._metric._compute(self._fold_row(self._tenant_state(t)))

    def compute_all(self) -> Dict[Hashable, Any]:
        """Every non-quarantined tenant's value (flushes pending traffic once).

        Resident tenants compute through ONE vmapped XLA call per shape-class
        (``Metric._get_vcompute_fn`` over the whole stack — the compile
        counters prove one ``vcompute`` compile per shape-class regardless of
        fleet size), replacing the eager per-tenant stack-slicing loop whose
        python dispatch overhead scaled with the roster. Spilled tenants and
        metrics whose ``_compute`` cannot trace (host computes) fall back to
        the eager slice path — values are identical either way."""
        self.flush()
        out: Dict[Hashable, Any] = {}
        done: set = set()
        if self._vcompute_ok is not False:
            for cls in self._classes.values():
                residents = [
                    (slot, tid) for slot, tid in cls.slot_tenant.items()
                    if not self._tenants[tid].quarantined
                ]
                if not residents:
                    continue
                try:
                    vals = self._vcompute(cls)
                except Exception:  # noqa: BLE001 — eager slicing below serves everyone
                    self._vcompute_ok = False
                    break
                self._vcompute_ok = True
                for slot, tid in residents:
                    out[tid] = jax.tree.map(lambda a, s=slot: a[s], vals)
                    done.add(tid)
        for tid, t in self._tenants.items():
            if tid in done or t.quarantined:
                continue
            out[tid] = self._metric._compute(self._fold_row(self._tenant_state(t)))
        return {tid: out[tid] for tid in self._tenants if tid in out}

    def _vcompute(self, cls: _ShapeClass) -> Any:
        """One whole-stack vmapped compute, dispatched through the usual
        donation-safe seam (telemetry + AOT planes apply; the program itself
        never donates — the stack keeps serving traffic). Every row computes
        (free/scratch rows are discarded) so the dispatch signature is fixed
        per shape-class; the class's zero pad example rides along purely as
        the signature carrier that keys each class's own compile. Windowed
        engines route through ``vwcompute``, which folds every row's
        dual/two-stack window INSIDE the same vmapped call."""
        pa, pk = cls.pad_example
        # owner= is defensive: the engine strips its clone's reliability, but
        # should retry ever engage, an exhausted-budget rollback must restore
        # into the STACK, never pollute the template metric's _state
        if self._wtier is not None:
            fn = self._metric._get_vwcompute_fn(self._wtier, self._wdepth)
            warr = self._wparam()
            return self._metric._donation_safe_dispatch(
                "vwcompute", lambda t, n: fn(t, n, warr, *pa, **pk), cls.stacked,
                inputs=((warr,) + tuple(pa), pk), jitted=fn, owner=cls.stacked,
            )
        fn = self._metric._get_vcompute_fn()
        return self._metric._donation_safe_dispatch(
            "vcompute", lambda t, n: fn(t, n, *pa, **pk), cls.stacked,
            inputs=(pa, pk), jitted=fn, owner=cls.stacked,
        )

    def update_count(self, tenant_id: Hashable) -> int:
        return self._require(tenant_id).update_count

    def tenants(self) -> Dict[Hashable, Dict[str, Any]]:
        """Fleet roster: per-tenant residency/quarantine/update status."""
        return {
            tid: {
                "resident": t.resident, "spilled": t.spilled is not None,
                "quarantined": t.quarantined, "update_count": t.update_count,
                "pending": t.pending, "shape_class": t.shape_key,
            }
            for tid, t in self._tenants.items()
        }

    def _require(self, tenant_id: Hashable) -> _Tenant:
        t = self._tenants.get(tenant_id)
        if t is None:
            raise KeyError(f"unknown tenant {tenant_id!r}")
        return t

    # ------------------------------------------------------------- lifecycle

    def reset(self, tenant_id: Hashable) -> None:
        """Restore one tenant to default state (lifts quarantine, drops its
        queued traffic, keeps its slot)."""
        t = self._require(tenant_id)
        if t.shape_key is not None and t.shape_key in self._classes:
            cls = self._classes[t.shape_key]
            kept = [e for e in cls.queue if e[0] != tenant_id]
            self.stats["dropped_batches"] += len(cls.queue) - len(kept)
            cls.queue = deque(kept)
            if t.slot is not None:
                for name, leaf in self._row_defaults.items():
                    cls.stacked[name] = cls.stacked[name].at[t.slot].set(jnp.asarray(leaf))
                cls.stacked[TENANT_COUNT_KEY] = cls.stacked[TENANT_COUNT_KEY].at[t.slot].set(0.0)
        t.spilled = None
        t.pending = 0
        t.update_count = 0
        t.quarantined = False
        t.error = None

    def evict(self, tenant_id: Hashable) -> None:
        """Force-spill one resident tenant's state to host (admin path)."""
        t = self._require(tenant_id)
        if t.resident and t.shape_key is not None:
            self._spill(t, self._classes[t.shape_key])

    def forget(self, tenant_id: Hashable) -> None:
        """Drop one tenant entirely — slot freed (row zeroed back to the
        default state), spilled copy and bookkeeping discarded. The fleet
        migration cutover uses this on the source host once the destination
        owns the tenant; any queued traffic is flushed first so no admitted
        batch is silently dropped."""
        t = self._require(tenant_id)
        if t.pending:
            self.flush()
        if t.resident and t.shape_key in self._classes:
            cls = self._classes[t.shape_key]
            for name, leaf in self._row_defaults.items():
                cls.stacked[name] = cls.stacked[name].at[t.slot].set(jnp.asarray(leaf))
            cls.stacked[TENANT_COUNT_KEY] = cls.stacked[TENANT_COUNT_KEY].at[t.slot].set(0.0)
            cls.slot_tenant.pop(t.slot, None)
            cls.free.append(t.slot)
        del self._tenants[tenant_id]

    def state_dict(self, tenant_id: Hashable) -> Dict[str, Any]:
        """One tenant's checkpoint, shaped exactly like ``Metric.state_dict``
        output so it loads into a standalone metric (and back via
        :meth:`load_state_dict`). Pending traffic is flushed first. Windowed
        engines checkpoint the WINDOW-LAYOUT leaves (restorable only into an
        engine with the same window geometry — the window cannot be rebuilt
        from its fold)."""
        t = self._require(tenant_id)
        if t.pending:
            self.flush()
        state = self._tenant_state(t)
        out: Dict[str, Any] = {name: np.asarray(v) for name, v in state.items()}
        out["_update_count"] = int(t.update_count)
        out["_saved_states"] = len(out) - 1
        return out

    def load_state_dict(self, tenant_id: Hashable, state_dict: Dict[str, Any]) -> None:
        """Restore one tenant from a checkpoint (its own or, for unwindowed
        engines, a standalone ``Metric.state_dict``). The state parks as a
        host-side (spilled) tenant and uploads into a stack slot on its next
        traffic."""
        t = self._tenant(tenant_id)
        if t.pending:
            raise TorchMetricsUserError(
                f"tenant {tenant_id!r} has {t.pending} undispatched batches; flush() before restoring."
            )
        unknown = [k for k in state_dict if k not in self._row_defaults and not k.startswith("_")]
        if unknown:
            raise TorchMetricsUserError(f"checkpoint carries unknown state keys {sorted(unknown)}")
        missing = [k for k in self._row_defaults if k not in state_dict]
        if missing:
            raise TorchMetricsUserError(
                f"checkpoint is missing state keys {sorted(missing)}"
                + (" (windowed engines need window-layout checkpoints of the same geometry)"
                   if self._wtier is not None else "")
            )
        if t.resident and t.shape_key is not None:
            cls = self._classes[t.shape_key]
            cls.slot_tenant.pop(t.slot, None)
            cls.free.append(t.slot)
            t.slot = None
        t.update_count = int(state_dict.get("_update_count", 1))
        t.spilled = {
            "state": _quantize.encode_spill_state(
                {k: np.asarray(state_dict[k]) for k in self._row_defaults},
                self.config.spill_codec,
            ),
            "count": float(t.update_count),
        }
        t.quarantined = False
        t.error = None

    # ------------------------------------------------------------- durability

    def _geometry(self) -> Dict[str, Any]:
        """The config facts a snapshot must match to be restorable: stack
        layout, window geometry, spill codec and admission rate (the journal
        replays the token bucket, so the rate must agree too)."""
        return {
            "capacity": self.config.capacity,
            "megabatch_size": self.config.megabatch_size,
            "spill_codec": self.config.spill_codec,
            "max_tenants_per_sec": self.config.max_tenants_per_sec,
            "window": self._window,
            "window_tier": self._wtier,
            "window_pane": self._wpane,
            "window_depth": self._wdepth,
            "state_keys": sorted(self._row_defaults),
        }

    def snapshot(self, directory: str) -> Dict[str, Any]:
        """Write one crash-consistent whole-engine snapshot generation.

        Pending megabatches are flushed first, then EVERY tenant's state rows
        (window layout included), seating/LRU/quarantine bookkeeping, the
        admission bucket, engine stats and the journal cursors land in one
        content-addressed container (``serving/durability.SnapshotStore`` —
        the ``aot/cache.py`` tmp+fsync+``os.replace`` discipline). Returns
        ``{"generation", "path", "bytes", "tenants"}``."""
        t0 = time.perf_counter()
        self.flush()
        store = _durability.SnapshotStore(directory)
        sections: Dict[str, np.ndarray] = {}
        tenants_meta: List[Dict[str, Any]] = []
        for i, (tid, t) in enumerate(self._tenants.items()):
            entry: Dict[str, Any] = {
                "id": _durability.encode_tenant_id(tid),
                "shape_key": t.shape_key,
                "update_count": int(t.update_count),
                "last_touch": int(t.last_touch),
                "quarantined": bool(t.quarantined),
                "error": t.error,
                "state": False,
            }
            if t.slot is not None or t.spilled is not None:
                state = self._tenant_state(t)
                for name in self._row_defaults:
                    sections[f"t{i}/{name}"] = np.asarray(state[name])
                if t.spilled is not None:
                    entry["count"] = float(t.spilled["count"])
                else:
                    cls = self._classes[t.shape_key]
                    entry["count"] = float(np.asarray(cls.stacked[TENANT_COUNT_KEY][t.slot]))
                entry["state"] = True
            tenants_meta.append(entry)
        meta = {
            "engine": self._geometry(),
            "tenants": tenants_meta,
            "stats": dict(self.stats),
            "rl": {"tokens": float(self._rl_tokens), "last": self._rl_last},
            # consuming one tick here shifts every later touch by one — order,
            # which is all LRU eviction compares, is preserved
            "touch": next(self._touch),
            "applied_seq": int(self._applied_seq),
            "next_seq": int(self._next_seq),
        }
        out = store.write(meta, sections)
        out["tenants"] = len(tenants_meta)
        if self.config.retain_snapshots is not None:
            pruned = store.prune(keep_last=self.config.retain_snapshots)
            out["pruned_generations"] = len(pruned)
            if pruned and self._journal is not None:
                # the OLDEST retained snapshot's cursor bounds what replay can
                # ever need — segments at or below it are dead weight
                oldest_meta, _ = store.read(store.generations()[0])
                self._journal.prune_covered(int(oldest_meta.get("applied_seq", 0)))
        rec = _observability._ACTIVE
        if rec is not None:
            rec.record_snapshot(
                self._metric, "write", time.perf_counter() - t0,
                out["bytes"], out["generation"],
            )
        return out

    def restore(self, directory: str, generation: Optional[int] = None) -> Dict[str, Any]:
        """Load one snapshot generation (latest by default) into this engine.

        The engine must have the same geometry the snapshot was taken with
        (capacity, megabatch size, window shape, spill codec, admission rate
        — mismatch raises ``TorchMetricsUserError``); a torn or corrupt
        snapshot raises ``StateCorruptionError`` and loads NOTHING. Every
        tenant parks host-side (the ``load_state_dict`` spill convention) and
        reseats lazily on its next traffic. Follow with
        :meth:`replay_journal` to roll forward past the snapshot point."""
        t0 = time.perf_counter()
        store = _durability.SnapshotStore(directory)
        meta, sections = store.read(generation)
        theirs = meta.get("engine")
        mine = self._geometry()
        if theirs != mine:
            raise TorchMetricsUserError(
                f"snapshot engine geometry {theirs!r} does not match this engine's {mine!r}; "
                "restore into an identically configured engine."
            )
        self._classes = {}
        self._tenants = {}
        try:
            for i, entry in enumerate(meta["tenants"]):
                tid = _durability.decode_tenant_id(entry["id"])
                t = _Tenant(tid)
                self._tenants[tid] = t
                t.shape_key = entry["shape_key"]
                t.update_count = int(entry["update_count"])
                t.last_touch = int(entry["last_touch"])
                t.quarantined = bool(entry["quarantined"])
                t.error = entry["error"]
                if entry["state"]:
                    state = {
                        name: np.asarray(sections[f"t{i}/{name}"])
                        for name in self._row_defaults
                    }
                    t.spilled = {
                        "state": _quantize.encode_spill_state(state, self.config.spill_codec),
                        "count": float(entry["count"]),
                    }
            self.stats = {k: meta["stats"].get(k, 0) for k in self.stats}
            rl = meta["rl"]
            self._rl_tokens = float(rl["tokens"])
            self._rl_last = None if rl["last"] is None else float(rl["last"])
            self._touch = itertools.count(int(meta["touch"]))
            self._applied_seq = int(meta["applied_seq"])
            self._next_seq = int(meta["next_seq"])
        except (KeyError, TypeError, ValueError) as err:
            raise StateCorruptionError(
                f"snapshot in {directory!r} decodes but its bookkeeping is malformed: {err}"
            ) from err
        gens = store.generations()
        used = int(generation) if generation is not None else gens[-1]
        rec = _observability._ACTIVE
        if rec is not None:
            rec.record_snapshot(
                self._metric, "restore", time.perf_counter() - t0, 0, used,
            )
        return {"generation": used, "tenants": len(self._tenants)}

    def replay_journal(
        self,
        records: List[_durability.JournalRecord],
        fetch: Callable[[_durability.JournalRecord], Tuple[tuple, dict]],
    ) -> int:
        """Roll a restored engine forward through the journal tail.

        ``fetch(record) -> (args, kwargs)`` resolves each record's batch from
        the traffic source's retention buffer; the journaled digest is
        verified against the refetched (prepared) batch before it is applied.
        Records at or below the snapshot's applied-seq cursor are skipped —
        replay is exactly-once no matter how often it is retried.

        ``kind="quarantine"`` records re-apply the primary's quarantine
        transition: the tenant comes back flagged (with the journaled error
        text) and the admissions the record names as rolled back are skipped
        outright — the primary never folded them, so replay must not either.
        Returns the number of records applied."""
        t0 = time.perf_counter()
        replayed = 0
        # admissions a later quarantine rolled back on the primary — collected
        # up front because they appear in the journal BEFORE the quarantine
        # record that dooms them
        rolled: set = set()
        for jrec in records:
            if jrec.kind == "quarantine":
                rolled.update(jrec.rolled_back)
        for jrec in records:
            if jrec.seq <= self._applied_seq:
                continue  # already folded before the snapshot — exactly-once
            if jrec.kind == "quarantine":
                t = self._tenant(jrec.tenant_id)
                if not t.quarantined:
                    t.quarantined = True
                    t.error = jrec.digest
                    t.pending = 0
                    t.unfolded = []
                    self.stats["quarantined"] += 1
                self._applied_seq = jrec.seq
                self._next_seq = max(self._next_seq, jrec.seq + 1)
                replayed += 1
                continue
            if jrec.seq in rolled:
                # admitted on the primary but rolled back by the quarantine
                # that journaled this seq — advance the cursor without folding
                self._applied_seq = jrec.seq
                self._next_seq = max(self._next_seq, jrec.seq + 1)
                continue
            args, kwargs = fetch(jrec)
            pargs, pkwargs = self._metric._prepare_inputs(*args, **kwargs)
            if _durability.batch_digest(pargs, pkwargs) != jrec.digest:
                raise StateCorruptionError(
                    f"journal seq {jrec.seq}: refetched batch does not match the journaled "
                    "digest — the retention buffer diverged from what the primary admitted."
                )
            ctx = None
            if _observability._ACTIVE is not None:
                ctx = _spans.enter("replay", jrec.seq, repr(jrec.tenant_id))
            self._replaying = True
            self._replay_clock = jrec.t
            try:
                ok = self.update(jrec.tenant_id, *args, **kwargs)
            finally:
                self._replaying = False
                self._replay_clock = None
                if ctx is not None:
                    _spans.exit(ctx)
            if not ok:
                raise StateCorruptionError(
                    f"journal seq {jrec.seq}: replayed admission was shed — the admission "
                    "bucket diverged from the journaled run (config mismatch?)."
                )
            self._applied_seq = jrec.seq
            self._next_seq = max(self._next_seq, jrec.seq + 1)
            replayed += 1
        rec = _observability._ACTIVE
        if rec is not None and replayed:
            rec.record_journal_replay(self._metric, replayed, time.perf_counter() - t0)
        return replayed

    def close(self) -> None:
        """Release the write-ahead journal handle (flushes its pending tail).
        A no-op for engines without a journal."""
        if self._journal is not None:
            self._journal.close()

    # ------------------------------------------------------------ warm start

    def _megabatch_sds(
        self, example_inputs: tuple, example_kwargs: dict
    ) -> Tuple[str, _ShapeClass, tuple]:
        """Shape-class key, its (created) stack, and the megabatch-shaped
        ``(idx, args, kwargs)`` avals for one example batch — EXACTLY the
        calling convention ``_dispatch_rows`` dispatches, so warm-start keys
        match what real traffic will look up."""
        args, kwargs = self._metric._prepare_inputs(*example_inputs, **example_kwargs)
        key = self._shape_key(args, kwargs)
        cls = self._ensure_class(key, args, kwargs)
        m = self.config.megabatch_size
        idx = jax.ShapeDtypeStruct((m,), jnp.int32)
        stack_sds = lambda leaf: jax.ShapeDtypeStruct((m,) + tuple(np.shape(leaf)), _np_dtype(leaf))
        mb_args, mb_kwargs = jax.tree.map(stack_sds, (args, kwargs))
        if self._wtier is not None:
            # windowed calling convention threads the traced window parameter
            wparam = jax.ShapeDtypeStruct((), jnp.float32)
            return key, cls, (wparam, idx, mb_args, mb_kwargs)
        return key, cls, (idx, mb_args, mb_kwargs)

    def _serve_tag(self) -> str:
        """The engine's megabatch dispatch tag: ``vwupdate`` when windowed."""
        return "vupdate" if self._wtier is None else "vwupdate"

    def _build_serve_fn(self) -> None:
        """Materialize the megabatch program for this engine's mode (the
        windowed builders are geometry-parameterized, so warm-start paths
        must build before ``_aot_program`` can key the cache)."""
        if self._wtier is None:
            self._metric._get_vupdate_fn()
        else:
            self._metric._get_vwupdate_fn(self._wtier, self._wdepth)

    def precompile(self, *example_inputs: Any, force: bool = False, **example_kwargs: Any) -> Dict[str, Any]:
        """Compile (or confirm cached) the megabatch program for the example
        batch's shape-class ahead of traffic and publish it into the active
        AOT cache — the deploy-time half of the self-warming boot story."""
        plane = _aot._ACTIVE
        if plane is None:
            raise TorchMetricsUserError(
                "precompile needs an active AOT plane — pass ServingConfig(aot_cache_dir=...) "
                "or call torchmetrics_tpu.aot.enable(cache_dir) first."
            )
        key, cls, mb = self._megabatch_sds(example_inputs, example_kwargs)
        tag = self._serve_tag()
        self._build_serve_fn()
        fn, donate = self._metric._aot_program(tag)
        row = plane.precompile_program(
            self._metric, tag, fn, donate, cls.stacked, mb, {}, force=force,
        )
        return {key: row}

    def prefetch(self, *example_inputs: Any, **example_kwargs: Any) -> Dict[str, Any]:
        """Load the example shape-class's cached megabatch executable into the
        dispatch memo without compiling on a miss (boot-time warm read)."""
        plane = _aot._ACTIVE
        if plane is None:
            raise TorchMetricsUserError("prefetch needs an active AOT plane.")
        key, cls, mb = self._megabatch_sds(example_inputs, example_kwargs)
        self._build_serve_fn()
        slot = plane.lookup_dispatch(self._metric, self._serve_tag(), cls.stacked, (mb, {}))
        if slot is not None and slot.compiled is not None:
            return {key: {"status": "loaded", "codec": slot.codec, "load_s": round(slot.load_s, 6)}}
        return {key: {"status": "miss"}}

    # ------------------------------------------------------------ async sync

    def sync_async(
        self,
        process_group: Any = None,
        dist_sync_fn: Optional[Callable] = None,
        reset_window: bool = False,
        sync_config: Optional[Any] = None,
    ) -> Any:
        """Launch a background coalesced sync of every shape-class's stacked
        tenant states — the hook that takes windowed per-tenant metrics' sync
        off the hot path (see ``docs/streaming.md``).

        Pending megabatch queues are ``flush()``-ed first (same read-path
        convention as ``compute``/``compute_all``), so every batch admitted
        before the call lands in the window it arrived in. ``handle.commit()``
        returns ``{shape_class_key: synced_stack}`` — a GLOBAL (cross-rank
        folded) read-side snapshot of the RESIDENT rows; the live stacks keep
        serving traffic untouched, so committing never discards updates that
        arrived during the overlap. Spilled (cold, host-side) tenants are not
        part of the stacks and therefore not part of the snapshot — readmit
        (or size capacity for) the tenants a window report must cover.
        Cross-rank row folding requires every rank to seat the same tenant in
        the same slot (a shard-by-tenant placement contract); "mean"-tagged
        leaves are rejected because a rowwise mean cannot weight per-row
        counts — keep sum+weight states (see ``MeanMetric``).

        ``reset_window=True`` rotates the window: the frozen stacks keep the
        current buffers (zero-copy), the live stacks restart from defaults,
        and spilled tenants' host copies are dropped to defaults too (a
        half-rotated fleet would readmit OLD-window state into the new
        window) — the serving analogue of ``SlidingWindow``'s roll. With
        ``reset_window=False`` the live stacks are re-buffered (one value
        copy per stack) so the engine's donated dispatches cannot delete the
        frozen buffers mid-gather.

        ``sync_config`` (:class:`~torchmetrics_tpu.parallel.SyncConfig`)
        opts the background gather into the quantized collective buckets —
        pass ONE config instance across repeated syncs so its error-feedback
        residuals fold correctly (``docs/distributed.md``).
        """
        from ..parallel.async_sync import AsyncSyncHandle

        if self._wtier is not None:
            raise TorchMetricsUserError(
                "sync_async cannot fold windowed tenant stacks across ranks: dual/two-stack "
                "accumulators carry block/pane phase that has no defined rowwise cross-rank "
                "merge. Compute per-rank windowed values instead (compute_all), or sync an "
                "unwindowed engine."
            )
        if any(fx == "mean" for fx in self._metric._reductions.values()):
            raise TorchMetricsUserError(
                "sync_async cannot fold bare 'mean'-reduced stacked states across ranks "
                "without per-row counts; keep sum+weight states instead (see MeanMetric)."
            )
        self.flush()  # admitted-but-queued batches belong to THIS window
        keys_list = list(self._classes)
        if not keys_list:
            return AsyncSyncHandle.noop(label="ServingEngine.sync_async")
        states: List[StateDict] = []
        reductions: List[Dict[str, Any]] = []
        for key in keys_list:
            cls = self._classes[key]
            frozen = dict(cls.stacked)  # shallow: zero-copy freeze
            if reset_window:
                cls.stacked = self._fresh_stack()
            else:
                # live side re-buffered: the engine's donated megabatch
                # dispatches must not delete the frozen buffers mid-gather
                cls.stacked = {name: jnp.copy(v) for name, v in cls.stacked.items()}
            states.append(frozen)
            red = {name: self._metric._reductions.get(name) for name in self._defaults_t}
            red[TENANT_COUNT_KEY] = "sum"  # per-row update counts sum across ranks
            reductions.append(red)
        if reset_window:
            # the whole fleet rotates, spilled tenants included: their host
            # copies are OLD-window state and must not readmit into the fresh one
            for t in self._tenants.values():
                if t.spilled is not None:
                    t.spilled = None

        def committer(synced: List[StateDict]) -> Dict[str, StateDict]:
            return dict(zip(keys_list, synced))

        return AsyncSyncHandle(
            states, reductions, process_group=process_group, dist_sync_fn=dist_sync_fn,
            committer=committer, label="ServingEngine.sync_async",
            sync_config=sync_config,
        )

    # ----------------------------------------------------------- observability

    def memory(self) -> Dict[str, Any]:
        """Resident (stacked, device) vs spilled (host) state footprint —
        metadata only (shape × itemsize), zero device reads."""
        from ..observability import memory as _memory

        classes = {}
        resident = 0
        for key, cls in self._classes.items():
            report = _memory.state_memory(cls.stacked)
            classes[key] = {
                "rows": self.config.capacity + 1,
                "resident_tenants": len(cls.slot_tenant),
                "total_bytes": report["total_bytes"],
            }
            resident += report["total_bytes"]
        spilled = sum(
            _quantize.spill_state_bytes(t.spilled["state"])
            for t in self._tenants.values()
            if t.spilled is not None
        )
        return {
            "classes": classes,
            "resident_bytes": resident,
            "spilled_tenants": sum(1 for t in self._tenants.values() if t.spilled is not None),
            "spilled_host_bytes": spilled,
        }

    def summary(self) -> Dict[str, Any]:
        """Engine-side stats (independent of any telemetry session)."""
        s = dict(self.stats)
        s["tenants"] = len(self._tenants)
        s["shape_classes"] = len(self._classes)
        s["tenants_per_dispatch"] = (
            round(s["tenant_rows"] / s["dispatches"], 3) if s["dispatches"] else 0.0
        )
        s["tenant_spill_us"] = s.pop("spill_ns") // 1000
        # the chosen per-tenant window tier, reported per-engine (ISSUE 12):
        # None when unwindowed; dual/two_stack carry their geometry
        s["window"] = self._window
        s["window_tier"] = self._wtier
        if self._wtier == "two_stack":
            s["window_pane"] = self._wpane
            s["window_depth"] = self._wdepth
        return s

    def block_until_ready(self) -> None:
        """Wait for every stack's pending device work (bench/test timing aid)."""
        for cls in self._classes.values():
            jax.block_until_ready(cls.stacked)


def _stack_leaves(*leaves: Any) -> jax.Array:
    """Stack one megabatch leaf across its M entries, cheaply.

    ``jnp.stack`` pays one eager ``expand_dims`` per entry and ``jnp.asarray``
    pays a dtype-lattice walk per entry — hundreds of tiny host dispatches per
    megabatch, which at fleet ingest rates dominates the dispatch itself. Host
    inputs stack in numpy and upload once; device inputs (guaranteed
    shape/dtype-identical by the shape-class) ride a single raw
    ``lax.concatenate`` + reshape pair."""
    if not isinstance(leaves[0], jax.Array):
        if all(isinstance(leaf, np.ndarray) or np.isscalar(leaf) for leaf in leaves):
            return jnp.asarray(np.stack(leaves, axis=0))
    arrs = [leaf if isinstance(leaf, jax.Array) else jnp.asarray(leaf) for leaf in leaves]
    if arrs[0].ndim == 0:
        return jnp.stack(arrs)
    shape = arrs[0].shape
    return jax.lax.concatenate(arrs, 0).reshape((len(arrs),) + tuple(shape))


def _np_dtype(leaf: Any) -> Any:
    dt = getattr(leaf, "dtype", None)
    if dt is not None:
        return dt
    return np.asarray(leaf).dtype


def _state_bytes(state: Dict[str, Any]) -> int:
    return int(sum(np.asarray(v).size * np.asarray(v).dtype.itemsize for v in state.values()))
