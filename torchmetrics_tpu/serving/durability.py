"""Durability plane: crash-consistent snapshots + a write-ahead traffic journal.

The serving engine's checkpoints so far are per-tenant and pull-based
(``state_dict``/``load_state_dict``): a process crash loses every resident
tenant's state and there is no record of which batches were already folded.
This module supplies the two on-disk primitives the failover story needs:

- :class:`SnapshotStore` — a generation-numbered container for whole-engine
  snapshots, written with the exact ``aot/cache.py`` discipline (magic +
  length-prefixed sorted-JSON header + sha256-verified payload, staged to a
  same-dir ``.tmp-*`` file, flushed + fsynced, then ``os.replace``'d). The one
  deliberate difference from the AOT cache: a torn or corrupt snapshot is not
  a cache miss, it is a *recovery failure* — every decode problem raises
  :class:`~torchmetrics_tpu.utilities.exceptions.StateCorruptionError`, never
  a silent ``None`` (extending PR 1's truncated-restore contract to the
  engine). Older generations stay on disk, so an operator can fall back to
  the previous intact snapshot explicitly.

- :class:`TrafficJournal` — an append-only write-ahead log of
  ``(seq, tenant_id, batch-digest, clock)`` records, segment-rotated and
  fsync-batched. The journal stores *digests*, not payloads: replay fetches
  each batch from the traffic source's retention buffer and the digest proves
  it is byte-identical to what the primary admitted. Records are CRC-framed;
  a truncated tail on the LAST segment is the bounded-loss crash window
  (records past the final fsync) and is tolerated, while any corruption of a
  *complete* record — or of any earlier segment — raises
  ``StateCorruptionError``. With ``fsync_every=1`` the loss window is zero
  (RPO=0); larger batches trade at most ``fsync_every - 1`` records for
  fewer fsyncs.

Replay idempotency rides the sequence numbers: the engine snapshot records
the highest applied ``seq``, replay skips anything at or below it, and every
applied record advances it — so restore + replay is exactly-once no matter
how many times it is retried (``docs/serving.md``, "Durability & failover").
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import os
import struct
import uuid
import zlib
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from ..utilities.exceptions import StateCorruptionError, TorchMetricsUserError

SNAPSHOT_MAGIC = b"TMSNAP1\x00"
SNAPSHOT_VERSION = 1
JOURNAL_MAGIC = b"TMJRNL1\x00"
JOURNAL_VERSION = 1
_HEADER_LEN_FMT = ">I"
# snapshots carry the whole tenant roster in the header; journals a few keys
_MAX_HEADER_BYTES = 1 << 22
_REC_FRAME_FMT = "<II"  # [body_len, crc32(body)]
_REC_FRAME_LEN = struct.calcsize(_REC_FRAME_FMT)


def _fsync_write(path_dir: str, final: str, payload: bytes) -> None:
    """The aot/cache.py publish discipline: same-dir tmp, flush + fsync,
    ``os.replace`` — a reader never sees a half-written file."""
    tmp = os.path.join(path_dir, f".tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}")
    try:
        with open(tmp, "wb") as fh:
            fh.write(payload)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, final)
    finally:
        if os.path.exists(tmp):  # publish failed after write — sweep
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _array_blob(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.lib.format.write_array(buf, np.ascontiguousarray(arr), allow_pickle=False)
    return buf.getvalue()


def _blob_array(blob: bytes, context: str) -> np.ndarray:
    try:
        return np.lib.format.read_array(io.BytesIO(blob), allow_pickle=False)
    except Exception as err:  # noqa: BLE001 — any decode problem is corruption
        raise StateCorruptionError(f"{context}: section payload is not a valid array: {err}") from err


def encode_tenant_id(tid: Any) -> List[Any]:
    """JSON-safe tenant id encoding. Snapshots/journals support the id types
    real services key sessions on (str/int); anything fancier must be mapped
    by the caller before it reaches the durability plane."""
    if isinstance(tid, bool) or not isinstance(tid, (int, str)):
        raise TorchMetricsUserError(
            f"durable serving requires str or int tenant ids, got {type(tid).__name__}"
        )
    return ["i", int(tid)] if isinstance(tid, int) else ["s", tid]


def decode_tenant_id(enc: Any) -> Any:
    if not (isinstance(enc, (list, tuple)) and len(enc) == 2 and enc[0] in ("i", "s")):
        raise StateCorruptionError(f"malformed tenant id encoding {enc!r}")
    return int(enc[1]) if enc[0] == "i" else str(enc[1])


def batch_digest(args: tuple, kwargs: dict) -> str:
    """Content digest of one (prepared) batch: pytree structure plus every
    leaf's dtype/shape/bytes. The journal stores this instead of the payload;
    replay verifies the refetched batch against it bit-for-bit."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    h = hashlib.sha256()
    h.update(repr(treedef).encode("utf-8"))
    for leaf in leaves:
        arr = np.asarray(leaf)
        h.update(str(arr.dtype).encode("utf-8"))
        h.update(str(arr.shape).encode("utf-8"))
        h.update(arr.tobytes())
    return h.hexdigest()[:32]


# ---------------------------------------------------------------------------
# snapshots
# ---------------------------------------------------------------------------


class SnapshotStore:
    """Generation-numbered, content-addressed snapshot container.

    Each generation is ONE file (``snap-<n>.tmsnap``): magic, a u32
    length-prefixed sorted-JSON header carrying the engine bookkeeping plus a
    ``[name, len]`` section table and the payload's sha256, then the raw
    section blobs. Writes are atomic (tmp + fsync + ``os.replace``); reads
    validate magic → header bounds → version → section totals → sha256 and
    raise :class:`StateCorruptionError` on ANY mismatch."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    def path_for(self, generation: int) -> str:
        return os.path.join(self.root, f"snap-{int(generation):08d}.tmsnap")

    def generations(self) -> List[int]:
        out = []
        for name in sorted(os.listdir(self.root)):
            if name.startswith("snap-") and name.endswith(".tmsnap"):
                try:
                    out.append(int(name[5:-7]))
                except ValueError:
                    continue
        return sorted(out)

    def write(self, meta: Dict[str, Any], sections: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """Publish the next generation atomically; returns
        ``{"generation", "path", "bytes"}``."""
        order: List[Tuple[str, bytes]] = [
            (name, _array_blob(np.asarray(arr))) for name, arr in sections.items()
        ]
        payload = b"".join(blob for _, blob in order)
        gens = self.generations()
        generation = (gens[-1] if gens else 0) + 1
        header = {
            "version": SNAPSHOT_VERSION,
            "generation": generation,
            "meta": dict(meta),
            "sections": [[name, len(blob)] for name, blob in order],
            "sha256": hashlib.sha256(payload).hexdigest(),
        }
        header_bytes = json.dumps(header, sort_keys=True).encode("utf-8")
        blob = SNAPSHOT_MAGIC + struct.pack(_HEADER_LEN_FMT, len(header_bytes)) + header_bytes + payload
        final = self.path_for(generation)
        _fsync_write(self.root, final, blob)
        return {"generation": generation, "path": final, "bytes": len(blob)}

    def prune(self, keep_last: int = 1) -> List[int]:
        """Delete all but the newest ``keep_last`` generations; returns the
        generations removed (oldest first).

        The newest generation is never removable (``keep_last`` must be
        >= 1): pruning bounds disk growth, it must not take away the only
        snapshot a restore could start from. Deleting an old generation is
        safe at any time — generations are immutable once published, and
        nothing references one except an explicit ``read(generation=)``."""
        if keep_last < 1:
            raise TorchMetricsUserError(f"keep_last must be >= 1, got {keep_last}")
        gens = self.generations()
        doomed = gens[:-int(keep_last)] if len(gens) > keep_last else []
        for gen in doomed:
            try:
                os.unlink(self.path_for(gen))
            except OSError:
                pass  # already gone — pruning is idempotent
        return doomed

    def read(self, generation: Optional[int] = None) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
        """Decode one generation (latest by default) → ``(meta, sections)``.

        Unlike the AOT cache's miss-on-damage ``get``, every validation
        failure here raises ``StateCorruptionError``: a restore must never
        silently load a torn snapshot."""
        gens = self.generations()
        if not gens:
            raise TorchMetricsUserError(f"no snapshot generations in {self.root!r}")
        gen = int(generation) if generation is not None else gens[-1]
        path = self.path_for(gen)
        try:
            with open(path, "rb") as fh:
                raw = fh.read()
        except OSError as err:
            raise StateCorruptionError(f"snapshot generation {gen} unreadable: {err}") from err
        ctx = f"snapshot {path!r}"
        if not raw.startswith(SNAPSHOT_MAGIC):
            raise StateCorruptionError(f"{ctx}: bad magic")
        off = len(SNAPSHOT_MAGIC)
        if len(raw) < off + struct.calcsize(_HEADER_LEN_FMT):
            raise StateCorruptionError(f"{ctx}: truncated before the header length")
        (hlen,) = struct.unpack_from(_HEADER_LEN_FMT, raw, off)
        off += struct.calcsize(_HEADER_LEN_FMT)
        if hlen <= 0 or hlen > _MAX_HEADER_BYTES or off + hlen > len(raw):
            raise StateCorruptionError(f"{ctx}: header length {hlen} out of bounds")
        try:
            header = json.loads(raw[off : off + hlen].decode("utf-8"))
        except Exception as err:  # noqa: BLE001
            raise StateCorruptionError(f"{ctx}: undecodable header: {err}") from err
        if not isinstance(header, dict) or header.get("version") != SNAPSHOT_VERSION:
            raise StateCorruptionError(
                f"{ctx}: unsupported snapshot version {header.get('version') if isinstance(header, dict) else '?'}"
            )
        payload = raw[off + hlen :]
        table = header.get("sections")
        if not isinstance(table, list) or any(
            not (isinstance(e, list) and len(e) == 2 and isinstance(e[1], int) and e[1] >= 0)
            for e in table
        ):
            raise StateCorruptionError(f"{ctx}: malformed section table")
        if sum(e[1] for e in table) != len(payload):
            raise StateCorruptionError(f"{ctx}: section table does not cover the payload")
        if hashlib.sha256(payload).hexdigest() != header.get("sha256"):
            raise StateCorruptionError(f"{ctx}: payload sha256 mismatch")
        sections: Dict[str, np.ndarray] = {}
        at = 0
        for name, length in table:
            sections[str(name)] = _blob_array(payload[at : at + length], ctx)
            at += length
        return dict(header.get("meta") or {}), sections


# ---------------------------------------------------------------------------
# write-ahead traffic journal
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class JournalRecord:
    """One journal entry.

    ``kind="admit"`` (the default) records an admitted batch: its sequence
    number, tenant, content digest and the admission-clock timestamp (so
    replay can rebuild the token bucket). ``kind="quarantine"`` records the
    engine quarantining a tenant mid-run — ``digest`` carries the error text
    (there is no batch) and ``rolled_back`` the seqs of the tenant's
    admitted-but-never-folded batches, which the primary rolled back and a
    replaying standby must therefore skip, not fold."""

    seq: int
    tenant_id: Any
    digest: str
    t: float = 0.0
    kind: str = "admit"
    rolled_back: Tuple[int, ...] = ()


class TrafficJournal:
    """Append-only, segment-rotated, fsync-batched write-ahead journal.

    ``append`` frames each record as ``u32 len + u32 crc32 + JSON body`` and
    fsyncs every ``fsync_every`` records (plus on rotation/close). A fresh
    instance always opens a NEW segment numbered after any existing ones, so
    a standby taking over after :meth:`read` keeps appending to the same
    journal directory without rewriting history."""

    def __init__(self, root: str, fsync_every: int = 1, segment_records: int = 512) -> None:
        if fsync_every < 1:
            raise TorchMetricsUserError(f"fsync_every must be >= 1, got {fsync_every}")
        if segment_records < 1:
            raise TorchMetricsUserError(f"segment_records must be >= 1, got {segment_records}")
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.fsync_every = int(fsync_every)
        self.segment_records = int(segment_records)
        self.records = 0
        self.fsyncs = 0
        self._pending = 0  # appended since the last fsync
        self._segment = max(self._segments() or [0]) + 1
        self._seg_records = 0
        self._fh = None
        self._open_segment()

    def _segments(self) -> List[int]:
        out = []
        for name in sorted(os.listdir(self.root)):
            if name.startswith("seg-") and name.endswith(".tmj"):
                try:
                    out.append(int(name[4:-4]))
                except ValueError:
                    continue
        return sorted(out)

    def _seg_path(self, segment: int) -> str:
        return os.path.join(self.root, f"seg-{int(segment):08d}.tmj")

    def _open_segment(self) -> None:
        header = json.dumps(
            {"version": JOURNAL_VERSION, "segment": self._segment}, sort_keys=True
        ).encode("utf-8")
        self._fh = open(self._seg_path(self._segment), "wb")
        self._fh.write(JOURNAL_MAGIC)
        self._fh.write(struct.pack(_HEADER_LEN_FMT, len(header)))
        self._fh.write(header)
        self._seg_records = 0
        self._synced_bytes = 0  # durable high-water mark of the active segment

    def append(
        self,
        tenant_id: Any,
        digest: str,
        seq: int,
        t: float = 0.0,
        kind: str = "admit",
        rolled_back: Optional[Iterable[int]] = None,
    ) -> bool:
        """Append one record; returns whether this append fsynced (the
        caller's RPO accounting). ``kind``/``rolled_back`` frame non-admission
        state transitions (see :class:`JournalRecord`); admission records keep
        the original byte layout."""
        doc: Dict[str, Any] = {
            "seq": int(seq), "tenant": encode_tenant_id(tenant_id), "digest": str(digest),
            "t": float(t),
        }
        if kind != "admit":
            doc["kind"] = str(kind)
        if rolled_back:
            doc["rolled_back"] = [int(s) for s in rolled_back]
        body = json.dumps(doc, sort_keys=True).encode("utf-8")
        self._fh.write(struct.pack(_REC_FRAME_FMT, len(body), zlib.crc32(body)))
        self._fh.write(body)
        self.records += 1
        self._seg_records += 1
        self._pending += 1
        synced = False
        if self._pending >= self.fsync_every:
            self.flush()
            synced = True
        if self._seg_records >= self.segment_records:
            self._rotate()
        return synced

    def flush(self) -> None:
        """Push the pending tail to stable storage (one fsync)."""
        if self._fh is None or self._fh.closed:
            return
        self._fh.flush()
        os.fsync(self._fh.fileno())
        if self._pending:
            self.fsyncs += 1
        self._pending = 0
        self._synced_bytes = self._fh.tell()

    def _rotate(self) -> None:
        self.flush()
        self._fh.close()
        self._segment += 1
        self._open_segment()

    def close(self) -> None:
        if self._fh is not None and not self._fh.closed:
            self.flush()
            self._fh.close()

    def crash(self) -> None:
        """Simulate process death at this instant: cut the active segment
        back to its last fsync, discarding every record past the durable
        high-water mark — exactly the torn tail :meth:`read` tolerates on
        the final segment. With ``fsync_every=1`` nothing is lost (RPO=0);
        larger batches lose at most the pending ``fsync_every - 1``
        records. The fleet soak's ``host_loss`` fault uses this so a killed
        host's journal looks like a real crash, not a clean shutdown."""
        if self._fh is None or self._fh.closed:
            return
        path = self._seg_path(self._segment)
        try:
            self._fh.close()  # flushes python buffers; durability is decided below
        finally:
            with open(path, "r+b") as fh:
                fh.truncate(self._synced_bytes)

    def __enter__(self) -> "TrafficJournal":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # ------------------------------------------------------------------ read

    @classmethod
    def read(cls, root: str) -> List[JournalRecord]:
        """Decode every record in seq order.

        Truncation at the tail of the LAST segment — an incomplete frame, or
        a segment header cut short by a crash during rotation — is the
        bounded-loss window and is tolerated. A *complete* record whose CRC
        or JSON does not check out, anywhere, is corruption and raises
        :class:`StateCorruptionError`; so is any damage to a non-final
        segment (nothing was ever appended past a rotated segment's fsync)."""
        if not os.path.isdir(root):
            return []
        segments = []
        for name in sorted(os.listdir(root)):
            if name.startswith("seg-") and name.endswith(".tmj"):
                segments.append(os.path.join(root, name))
        out: List[JournalRecord] = []
        last_seq = 0
        for si, path in enumerate(segments):
            for rec in _decode_segment(path, is_last=si == len(segments) - 1):
                if rec.seq <= last_seq:
                    raise StateCorruptionError(
                        f"journal segment {path!r}: sequence regressed ({rec.seq} after {last_seq})"
                    )
                last_seq = rec.seq
                out.append(rec)
        return out

    # ----------------------------------------------------------------- prune

    def prune_covered(self, applied_seq: int) -> List[int]:
        """Delete rotated segments whose every record is already covered by a
        retained snapshot's seq cursor; returns the segments removed.

        Replay skips records at or below the snapshot's ``applied_seq``, so a
        segment whose last record's seq is ``<= applied_seq`` contributes
        nothing to any restore that starts from that snapshot (or a newer
        one) — it is dead weight. Seqs are monotone across segments, so
        pruning stops at the first segment with an uncovered record. The
        segment currently open for appends is never touched."""
        removed: List[int] = []
        for seg in self._segments():
            if seg >= self._segment:
                break
            recs = _decode_segment(self._seg_path(seg), is_last=False)
            if recs and recs[-1].seq > int(applied_seq):
                break
            try:
                os.unlink(self._seg_path(seg))
            except OSError:
                pass  # already gone — pruning is idempotent
            removed.append(seg)
        return removed


def _decode_segment(path: str, is_last: bool) -> List[JournalRecord]:
    """Decode one segment file. Torn tails are tolerated only when
    ``is_last`` (nothing was ever appended past a rotated segment's fsync);
    any complete-but-wrong frame raises :class:`StateCorruptionError`."""
    with open(path, "rb") as fh:
        raw = fh.read()
    ctx = f"journal segment {path!r}"
    out: List[JournalRecord] = []
    off = len(JOURNAL_MAGIC)
    if not raw.startswith(JOURNAL_MAGIC) or len(raw) < off + _REC_FRAME_LEN - 4:
        if is_last and len(raw) < off + struct.calcsize(_HEADER_LEN_FMT):
            return out  # rotation crashed before the header landed
        raise StateCorruptionError(f"{ctx}: bad magic")
    (hlen,) = struct.unpack_from(_HEADER_LEN_FMT, raw, off)
    off += struct.calcsize(_HEADER_LEN_FMT)
    if hlen <= 0 or hlen > _MAX_HEADER_BYTES:
        raise StateCorruptionError(f"{ctx}: header length {hlen} out of bounds")
    if off + hlen > len(raw):
        if is_last:
            return out  # torn header tail on the final segment
        raise StateCorruptionError(f"{ctx}: truncated header")
    try:
        header = json.loads(raw[off : off + hlen].decode("utf-8"))
    except Exception as err:  # noqa: BLE001
        raise StateCorruptionError(f"{ctx}: undecodable header: {err}") from err
    if header.get("version") != JOURNAL_VERSION:
        raise StateCorruptionError(f"{ctx}: unsupported version {header.get('version')}")
    off += hlen
    while off < len(raw):
        if off + _REC_FRAME_LEN > len(raw):
            if is_last:
                break  # torn frame tail — bounded loss
            raise StateCorruptionError(f"{ctx}: truncated record frame")
        blen, crc = struct.unpack_from(_REC_FRAME_FMT, raw, off)
        body_at = off + _REC_FRAME_LEN
        if body_at + blen > len(raw):
            if is_last:
                break  # torn body tail — bounded loss
            raise StateCorruptionError(f"{ctx}: truncated record body")
        body = raw[body_at : body_at + blen]
        if zlib.crc32(body) != crc:
            # a COMPLETE record that fails its CRC is a bitflip, not a
            # crash tail — never silently skipped
            raise StateCorruptionError(f"{ctx}: record CRC mismatch at offset {off}")
        try:
            doc = json.loads(body.decode("utf-8"))
            rec = JournalRecord(
                seq=int(doc["seq"]),
                tenant_id=decode_tenant_id(doc["tenant"]),
                digest=str(doc["digest"]),
                t=float(doc.get("t", 0.0)),
                kind=str(doc.get("kind", "admit")),
                rolled_back=tuple(int(s) for s in doc.get("rolled_back", ())),
            )
        except StateCorruptionError:
            raise
        except Exception as err:  # noqa: BLE001
            raise StateCorruptionError(f"{ctx}: undecodable record: {err}") from err
        out.append(rec)
        off = body_at + blen
    return out
