"""Multi-tenant serving engine — stacked tenant states, vmapped megabatch
dispatch, LRU spill, per-tenant lifecycle. See ``docs/serving.md``."""

from .engine import ServingConfig, ServingEngine

__all__ = ["ServingConfig", "ServingEngine"]
