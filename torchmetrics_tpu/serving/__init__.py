"""Multi-tenant serving engine — stacked tenant states, vmapped megabatch
dispatch, LRU spill, per-tenant lifecycle, crash-consistent snapshots and a
write-ahead traffic journal. See ``docs/serving.md``."""

from .durability import JournalRecord, SnapshotStore, TrafficJournal, batch_digest
from .engine import ServingConfig, ServingEngine

__all__ = [
    "JournalRecord",
    "ServingConfig",
    "ServingEngine",
    "SnapshotStore",
    "TrafficJournal",
    "batch_digest",
]
