"""Text tower — stateful metric classes (reference ``src/torchmetrics/text/``)."""

from .metrics import (
    BLEUScore,
    ExtendedEditDistance,
    TranslationEditRate,
    CharErrorRate,
    CHRFScore,
    EditDistance,
    MatchErrorRate,
    Perplexity,
    ROUGEScore,
    SacreBLEUScore,
    SQuAD,
    WordErrorRate,
    WordInfoLost,
    WordInfoPreserved,
)

__all__ = [
    "BLEUScore",
    "CHRFScore",
    "CharErrorRate",
    "EditDistance",
    "ExtendedEditDistance",
    "MatchErrorRate",
    "Perplexity",
    "ROUGEScore",
    "SQuAD",
    "SacreBLEUScore",
    "TranslationEditRate",
    "WordErrorRate",
    "WordInfoLost",
    "WordInfoPreserved",
]
