"""Text tower — stateful metric classes (reference ``src/torchmetrics/text/``)."""

from .metrics import (
    BERTScore,
    BLEUScore,
    InfoLM,
    ExtendedEditDistance,
    TranslationEditRate,
    CharErrorRate,
    CHRFScore,
    EditDistance,
    MatchErrorRate,
    Perplexity,
    ROUGEScore,
    SacreBLEUScore,
    SQuAD,
    WordErrorRate,
    WordInfoLost,
    WordInfoPreserved,
)

__all__ = [
    "BERTScore",
    "BLEUScore",
    "CHRFScore",
    "CharErrorRate",
    "EditDistance",
    "ExtendedEditDistance",
    "InfoLM",
    "MatchErrorRate",
    "Perplexity",
    "ROUGEScore",
    "SQuAD",
    "SacreBLEUScore",
    "TranslationEditRate",
    "WordErrorRate",
    "WordInfoLost",
    "WordInfoPreserved",
]
