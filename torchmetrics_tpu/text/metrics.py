"""Text tower metric classes (reference ``src/torchmetrics/text/*.py``).

All string processing runs host-side in ``_host_batch_state``; states are fixed-shape
count tensors (sum-reduced — sync is one psum each) except ROUGE/EditDistance('none')
which keep per-sentence cat rows like the reference.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..functional.text.asr import (
    _asr_counts,
    _cer_compute,
    _mer_compute,
    _wer_compute,
    _wil_compute,
    _wip_compute,
)
from ..functional.text.bleu import _bleu_score_compute, _bleu_score_update, _resolve_weights, _tokenize_fn
from ..functional.text.chrf import _chrf_score_compute, _chrf_score_update, _validate_chrf_args
from ..functional.text.edit import _edit_distance_compute, _edit_distance_update
from ..functional.text.perplexity import _perplexity_compute, _perplexity_update
from ..functional.text.rouge import (
    ALLOWED_ACCUMULATE_VALUES,
    _make_stemmer,
    _resolve_rouge_keys,
    _rouge_score_update,
)
from ..functional.text.sacre_bleu import AVAILABLE_TOKENIZERS, _SacreBLEUTokenizer
from ..functional.text.squad import _squad_compute, _squad_input_check, _squad_update
from ..metric import HostMetric, Metric
from ..utilities.exceptions import TorchMetricsUserError


class BLEUScore(HostMetric):
    """Corpus BLEU (reference ``text/bleu.py:34``; states ``text/bleu.py:92-95``).

    Example:
        >>> from torchmetrics_tpu.text import BLEUScore
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> metric = BLEUScore()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.75983566, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self, n_gram: int = 4, smooth: bool = False, weights: Optional[Sequence[float]] = None, **kwargs: Any
    ) -> None:
        super().__init__(**kwargs)
        self.n_gram = n_gram
        self.smooth = smooth
        self.weights = _resolve_weights(n_gram, weights)
        self.tokenizer: Callable = _tokenize_fn
        self.add_state("preds_len", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("target_len", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("numerator", jnp.zeros(self.n_gram), dist_reduce_fx="sum")
        self.add_state("denominator", jnp.zeros(self.n_gram), dist_reduce_fx="sum")

    def _host_batch_state(self, preds: Sequence[str], target: Sequence[Union[str, Sequence[str]]]):
        preds_ = [preds] if isinstance(preds, str) else preds
        target_ = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]
        if len(preds_) != len(target_):
            raise ValueError(f"Corpus has different size {len(preds_)} != {len(target_)}")
        numerator, denominator, preds_len, target_len = _bleu_score_update(
            preds_, target_, self.n_gram, self.tokenizer
        )
        return {
            "numerator": jnp.asarray(numerator, jnp.float32),
            "denominator": jnp.asarray(denominator, jnp.float32),
            "preds_len": jnp.asarray(preds_len, jnp.float32),
            "target_len": jnp.asarray(target_len, jnp.float32),
        }

    def _compute(self, state):
        return _bleu_score_compute(
            state["preds_len"], state["target_len"], state["numerator"], state["denominator"],
            self.n_gram, self.weights, self.smooth,
        )


class SacreBLEUScore(BLEUScore):
    """BLEU with sacrebleu tokenization (reference ``text/sacre_bleu.py:35``).

    Example:
        >>> from torchmetrics_tpu.text import SacreBLEUScore
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> metric = SacreBLEUScore()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.75983566, dtype=float32)
    """

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        tokenize: str = "13a",
        lowercase: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(n_gram=n_gram, smooth=smooth, weights=weights, **kwargs)
        if tokenize not in AVAILABLE_TOKENIZERS:
            raise ValueError(f"Argument `tokenize` expected to be one of {AVAILABLE_TOKENIZERS} but got {tokenize}.")
        self.tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)


class _ASRMetric(HostMetric):
    """Shared shell for CER/WER/MER: (errors, total) sum states."""

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    _char_level = False
    _total_is_max = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def _host_batch_state(self, preds, target):
        errors, total_max, target_total, _ = _asr_counts(preds, target, char_level=self._char_level)
        return {
            "errors": jnp.asarray(errors, jnp.float32),
            "total": jnp.asarray(total_max if self._total_is_max else target_total, jnp.float32),
        }


class CharErrorRate(_ASRMetric):
    """Character error rate (reference ``text/cer.py:29``).

    Example:
        >>> from torchmetrics_tpu.text import CharErrorRate
        >>> metric = CharErrorRate()
        >>> metric.update(['this is the prediction'], ['this is the reference'])
        >>> metric.compute()
        Array(0.3809524, dtype=float32)
    """

    _char_level = True

    def _compute(self, state):
        return _cer_compute(state["errors"], state["total"])


class WordErrorRate(_ASRMetric):
    """Word error rate (reference ``text/wer.py:29``).

    Example:
        >>> from torchmetrics_tpu.text import WordErrorRate
        >>> metric = WordErrorRate()
        >>> metric.update(['this is the prediction'], ['this is the reference'])
        >>> metric.compute()
        Array(0.25, dtype=float32)
    """

    def _compute(self, state):
        return _wer_compute(state["errors"], state["total"])


class MatchErrorRate(_ASRMetric):
    """Match error rate (reference ``text/mer.py:29``).

    Example:
        >>> from torchmetrics_tpu.text import MatchErrorRate
        >>> metric = MatchErrorRate()
        >>> metric.update(['this is the prediction'], ['this is the reference'])
        >>> metric.compute()
        Array(0.25, dtype=float32)
    """

    _total_is_max = True

    def _compute(self, state):
        return _mer_compute(state["errors"], state["total"])


class _WordInfoMetric(HostMetric):
    is_differentiable = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("target_total", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("preds_total", jnp.zeros(()), dist_reduce_fx="sum")

    def _host_batch_state(self, preds, target):
        errors, total, target_total, preds_total = _asr_counts(preds, target, char_level=False)
        return {
            "errors": jnp.asarray(errors - total, jnp.float32),
            "target_total": jnp.asarray(target_total, jnp.float32),
            "preds_total": jnp.asarray(preds_total, jnp.float32),
        }


class WordInfoLost(_WordInfoMetric):
    """Word information lost (reference ``text/wil.py:28``).

    Example:
        >>> from torchmetrics_tpu.text import WordInfoLost
        >>> metric = WordInfoLost()
        >>> metric.update(['this is the prediction'], ['this is the reference'])
        >>> metric.compute()
        Array(0.4375, dtype=float32)
    """

    higher_is_better = False

    def _compute(self, state):
        return _wil_compute(state["errors"], state["target_total"], state["preds_total"])


class WordInfoPreserved(_WordInfoMetric):
    """Word information preserved (reference ``text/wip.py:28``).

    Example:
        >>> from torchmetrics_tpu.text import WordInfoPreserved
        >>> metric = WordInfoPreserved()
        >>> metric.update(['this is the prediction'], ['this is the reference'])
        >>> metric.compute()
        Array(0.5625, dtype=float32)
    """

    higher_is_better = True

    def _compute(self, state):
        return _wip_compute(state["errors"], state["target_total"], state["preds_total"])


class EditDistance(HostMetric):
    """Levenshtein edit distance (reference ``text/edit.py:30``).

    Example:
        >>> from torchmetrics_tpu.text import EditDistance
        >>> metric = EditDistance()
        >>> metric.update(['rain'], ['shine'])
        >>> metric.compute()
        Array(3., dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, substitution_cost: int = 1, reduction: Optional[str] = "mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(substitution_cost, int) and substitution_cost >= 0):
            raise ValueError(
                f"Expected argument `substitution_cost` to be a positive integer, but got {substitution_cost}"
            )
        allowed_reduction = (None, "mean", "sum", "none")
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction}, but got {reduction}")
        self.substitution_cost = substitution_cost
        self.reduction = reduction
        if self.reduction in ("none", None):
            self.add_state("edit_scores_list", default=[], dist_reduce_fx="cat")
        else:
            self.add_state("edit_scores", default=np.zeros((), jnp.int32), dist_reduce_fx="sum")
            self.add_state("num_elements", default=np.zeros((), jnp.int32), dist_reduce_fx="sum")

    def _host_batch_state(self, preds, target):
        distance = _edit_distance_update(preds, target, self.substitution_cost)
        if self.reduction in ("none", None):
            return {"edit_scores_list": distance}
        return {
            "edit_scores": distance.sum(),
            "num_elements": jnp.asarray(distance.size, jnp.int32),
        }

    def _compute(self, state):
        if self.reduction in ("none", None):
            return _edit_distance_compute(jnp.asarray(state["edit_scores_list"], jnp.int32), 1, self.reduction)
        return _edit_distance_compute(state["edit_scores"], state["num_elements"], self.reduction)


class CHRFScore(HostMetric):
    """chrF/chrF++ (reference ``text/chrf.py:53``): six per-order count vectors.

    Example:
        >>> from torchmetrics_tpu.text import CHRFScore
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat']]
        >>> metric = CHRFScore()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.4941851, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        n_char_order: int = 6,
        n_word_order: int = 2,
        beta: float = 2.0,
        lowercase: bool = False,
        whitespace: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        _validate_chrf_args(n_char_order, n_word_order, beta)
        self.n_char_order = n_char_order
        self.n_word_order = n_word_order
        self.beta = beta
        self.lowercase = lowercase
        self.whitespace = whitespace
        self.return_sentence_level_score = return_sentence_level_score
        self.n_order = float(n_char_order + n_word_order)
        for name in ("preds_char", "preds_word", "target_char", "target_word", "matching_char", "matching_word"):
            order = n_char_order if "char" in name else n_word_order
            self.add_state(f"total_{name}_n_grams", jnp.zeros(order), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_chrf_score", default=[], dist_reduce_fx="cat")

    def _host_batch_state(self, preds, target):
        p_char, p_word, t_char, t_word, m_char, m_word, sentence_scores = _chrf_score_update(
            preds, target, self.n_char_order, self.n_word_order, self.beta, self.lowercase, self.whitespace
        )
        out = {
            "total_preds_char_n_grams": jnp.asarray(p_char, jnp.float32),
            "total_preds_word_n_grams": jnp.asarray(p_word, jnp.float32),
            "total_target_char_n_grams": jnp.asarray(t_char, jnp.float32),
            "total_target_word_n_grams": jnp.asarray(t_word, jnp.float32),
            "total_matching_char_n_grams": jnp.asarray(m_char, jnp.float32),
            "total_matching_word_n_grams": jnp.asarray(m_word, jnp.float32),
        }
        if self.return_sentence_level_score:
            out["sentence_chrf_score"] = jnp.asarray(sentence_scores, jnp.float32)
        return out

    def _compute(self, state):
        score = _chrf_score_compute(
            state["total_preds_char_n_grams"], state["total_preds_word_n_grams"],
            state["total_target_char_n_grams"], state["total_target_word_n_grams"],
            state["total_matching_char_n_grams"], state["total_matching_word_n_grams"],
            self.n_order, self.beta,
        )
        if self.return_sentence_level_score:
            return score, jnp.asarray(state["sentence_chrf_score"])
        return score


class SQuAD(HostMetric):
    """SQuAD EM/F1 (reference ``text/squad.py:35``).

    Example:
        >>> from torchmetrics_tpu.text import SQuAD
        >>> preds = [{'prediction_text': '1976', 'id': '56e10a3be3433e1400422b22'}]
        >>> target = [{'answers': {'answer_start': [97], 'text': ['1976']}, 'id': '56e10a3be3433e1400422b22'}]
        >>> metric = SQuAD()
        >>> metric.update(preds, target)
        >>> {k: round(float(v), 4) for k, v in metric.compute().items()}
        {'exact_match': 100.0, 'f1': 100.0}
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 100.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("f1_score", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("exact_match", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), jnp.int32), dist_reduce_fx="sum")

    def _host_batch_state(self, preds, target):
        preds_dict, target_dict = _squad_input_check(preds, target)
        f1, exact_match, total = _squad_update(preds_dict, target_dict)
        return {
            "f1_score": jnp.asarray(f1, jnp.float32),
            "exact_match": jnp.asarray(exact_match, jnp.float32),
            "total": jnp.asarray(total, jnp.int32),
        }

    def _compute(self, state):
        return _squad_compute(state["f1_score"], state["exact_match"], state["total"])


class Perplexity(Metric):
    """Perplexity (reference ``text/perplexity.py:29``) — jitted device update.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.text import Perplexity
        >>> preds = jnp.asarray([[[0.2, 0.4, 0.4], [0.5, 0.2, 0.3]]])
        >>> target = jnp.asarray([[1, 0]])
        >>> metric = Perplexity()
        >>> metric.update(jnp.log(preds), target)
        >>> metric.compute()
        Array(2.236068, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, ignore_index: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError(f"Argument `ignore_index` expected to either be `None` or an `int` but got {ignore_index}")
        self.ignore_index = ignore_index
        self.add_state("total_log_probs", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("count", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, preds, target):
        total_log_probs, count = _perplexity_update(preds, target, self.ignore_index)
        return {"total_log_probs": total_log_probs, "count": count.astype(jnp.float32)}

    def _compute(self, state):
        return _perplexity_compute(state["total_log_probs"], state["count"])


class ROUGEScore(HostMetric):
    """ROUGE-N/L/Lsum (reference ``text/rouge.py:37``): per-sentence cat rows per
    rouge key and statistic.

    Example:
        >>> from torchmetrics_tpu.text import ROUGEScore
        >>> metric = ROUGEScore(rouge_keys='rouge1')
        >>> metric.update(['the cat is on the mat'], [['a cat is on the mat']])
        >>> {k: round(float(v), 4) for k, v in metric.compute().items()}
        {'rouge1_fmeasure': 0.8333, 'rouge1_precision': 0.8333, 'rouge1_recall': 0.8333}
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        use_stemmer: bool = False,
        normalizer: Optional[Callable[[str], str]] = None,
        tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
        accumulate: str = "best",
        rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if accumulate not in ALLOWED_ACCUMULATE_VALUES:
            raise ValueError(
                f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE_VALUES}"
            )
        self.rouge_keys, self.rouge_keys_values = _resolve_rouge_keys(rouge_keys)
        self.stemmer = _make_stemmer(use_stemmer)
        self.normalizer = normalizer
        self.tokenizer = tokenizer
        self.accumulate = accumulate
        for rouge_key in self.rouge_keys:
            for score in ("fmeasure", "precision", "recall"):
                self.add_state(f"{rouge_key}_{score}", default=[], dist_reduce_fx="cat")

    def _host_batch_state(self, preds, target):
        if isinstance(target, list) and all(isinstance(tgt, str) for tgt in target):
            target = [target] if isinstance(preds, str) else [[tgt] for tgt in target]
        if isinstance(preds, str):
            preds = [preds]
        if isinstance(target, str):
            target = [[target]]
        results = _rouge_score_update(
            preds, target, self.rouge_keys_values, self.accumulate, self.stemmer, self.normalizer, self.tokenizer
        )
        out = {}
        for rouge_key, key_value in zip(self.rouge_keys, self.rouge_keys_values):
            for score in ("fmeasure", "precision", "recall"):
                out[f"{rouge_key}_{score}"] = jnp.asarray(
                    np.asarray([s[score] for s in results[key_value]], np.float32)
                )
        return out

    def _compute(self, state):
        return {
            key: jnp.mean(jnp.asarray(state[key]))
            for key in (f"{rk}_{sc}" for rk in self.rouge_keys for sc in ("fmeasure", "precision", "recall"))
        }

    def __hash__(self) -> int:
        # normalizer/tokenizer callables are unhashable with the default state-based hash
        hash_vals = [self.__class__.__name__, *(str(k) for k in self.rouge_keys)]
        return hash(tuple(hash_vals))


class TranslationEditRate(HostMetric):
    """TER (reference ``text/ter.py:30``): two scalar sum states + optional
    sentence-level cat rows.

    Example:
        >>> from torchmetrics_tpu.text import TranslationEditRate
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat']]
        >>> metric = TranslationEditRate()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.42857143, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        from ..functional.text.ter import _TercomTokenizer

        for name, val in (
            ("normalize", normalize), ("no_punctuation", no_punctuation),
            ("lowercase", lowercase), ("asian_support", asian_support),
        ):
            if not isinstance(val, bool):
                raise ValueError(f"Expected argument `{name}` to be of type boolean but got {val}.")
        self.tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
        self.return_sentence_level_score = return_sentence_level_score
        self.add_state("total_num_edits", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total_tgt_len", jnp.zeros(()), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_ter", default=[], dist_reduce_fx="cat")

    def _host_batch_state(self, preds, target):
        from ..functional.text.ter import _ter_update

        total_num_edits, total_tgt_length, sentence_ter = _ter_update(preds, target, self.tokenizer)
        out = {
            "total_num_edits": jnp.asarray(total_num_edits, jnp.float32),
            "total_tgt_len": jnp.asarray(total_tgt_length, jnp.float32),
        }
        if self.return_sentence_level_score:
            out["sentence_ter"] = jnp.asarray(sentence_ter, jnp.float32)
        return out

    def _compute(self, state):
        from ..functional.text.ter import _ter_compute

        score = _ter_compute(state["total_num_edits"], state["total_tgt_len"])
        if self.return_sentence_level_score:
            return score, jnp.asarray(state["sentence_ter"])
        return score

    def __hash__(self) -> int:
        return hash((self.__class__.__name__, id(self)))


class ExtendedEditDistance(HostMetric):
    """EED (reference ``text/eed.py:29``): per-sentence cat rows.

    Example:
        >>> from torchmetrics_tpu.text import ExtendedEditDistance
        >>> metric = ExtendedEditDistance()
        >>> metric.update(['this is the prediction'], [['this is the reference']])
        >>> metric.compute()
        Array(0.38345864, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        language: str = "en",
        return_sentence_level_score: bool = False,
        alpha: float = 2.0,
        rho: float = 0.3,
        deletion: float = 0.2,
        insertion: float = 1.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if language not in ("en", "ja"):
            raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")
        self.language = language
        self.return_sentence_level_score = return_sentence_level_score
        for name, val in (("alpha", alpha), ("rho", rho), ("deletion", deletion), ("insertion", insertion)):
            if not isinstance(val, float) or val < 0:
                raise ValueError(f"Parameter `{name}` is expected to be a non-negative float.")
        self.alpha = alpha
        self.rho = rho
        self.deletion = deletion
        self.insertion = insertion
        self.add_state("sentence_eed", default=[], dist_reduce_fx="cat")

    def _host_batch_state(self, preds, target):
        from ..functional.text.eed import _eed_update

        scores = _eed_update(preds, target, self.language, self.alpha, self.rho, self.deletion, self.insertion)
        return {"sentence_eed": jnp.asarray(scores, jnp.float32)}

    def _compute(self, state):
        from ..functional.text.eed import _eed_compute

        average = _eed_compute(state["sentence_eed"])
        if self.return_sentence_level_score:
            return average, jnp.asarray(state["sentence_eed"])
        return average


class BERTScore(HostMetric):
    """BERTScore (reference ``text/bert.py:59``): cat states of tokenized
    input_ids/attention_mask (reference ``text/bert.py:220``); the embedding +
    matching pipeline runs at compute.

    The matching half — greedy cosine alignment over normalized embeddings — is
    re-homed onto the jitted "escore" dispatch program: embeddings are zero-padded
    to power-of-two (batch, token) buckets so repeat computes reuse one compiled
    program per bucket signature, and an active AOT plane serves it from disk on
    warm boot. Zero padding is exactly parity-safe: the special-token mask already
    zeroes at least one position per row, so all-zero candidate columns are already
    in every row's max, and padded scale weights contribute nothing to the weighted
    sums. The embedder itself (arbitrary host code) stays eager."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        num_layers: Optional[int] = None,
        all_layers: bool = False,
        model: Optional[Callable] = None,
        user_tokenizer: Optional[Any] = None,
        user_forward_fn: Optional[Callable] = None,
        verbose: bool = False,
        idf: bool = False,
        max_length: int = 512,
        batch_size: int = 64,
        return_hash: bool = False,
        lang: str = "en",
        rescale_with_baseline: bool = False,
        baseline_path: Optional[str] = None,
        truncation: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        from ..functional.text.bert import _load_hf, _tokenize

        if all_layers:
            raise ValueError(
                "`all_layers=True` is only meaningful with per-layer baselines; use num_layers instead."
            )
        self.num_layers = num_layers
        self.all_layers = all_layers
        self.idf = idf
        self.verbose = verbose
        self.max_length = max_length
        self.batch_size = batch_size
        self.return_hash = return_hash
        self.lang = lang
        self.rescale_with_baseline = rescale_with_baseline
        self.baseline_path = baseline_path
        self.truncation = truncation
        self.model_name_or_path = model_name_or_path
        if model is not None:
            if user_tokenizer is None:
                raise ValueError("The model must be accompanied by a `user_tokenizer`.")
            self._forward = (
                (lambda ids, mask: user_forward_fn(model, {"input_ids": ids, "attention_mask": mask}))
                if user_forward_fn
                else model
            )
            self.tokenizer = user_tokenizer
        else:
            self.tokenizer, self._forward = _load_hf(model_name_or_path or "roberta-large", num_layers)
        self._tokenize = _tokenize
        self.add_state("preds_input_ids", default=[], dist_reduce_fx="cat")
        self.add_state("preds_attention_mask", default=[], dist_reduce_fx="cat")
        self.add_state("target_input_ids", default=[], dist_reduce_fx="cat")
        self.add_state("target_attention_mask", default=[], dist_reduce_fx="cat")

    def _host_batch_state(self, preds, target):
        preds = [preds] if isinstance(preds, str) else list(preds)
        target = [target] if isinstance(target, str) else list(target)
        p = self._tokenize(self.tokenizer, preds, self.max_length, self.truncation)
        t = self._tokenize(self.tokenizer, target, self.max_length, self.truncation)
        for tok in (p, t):
            if tok["input_ids"].shape[1] > self.max_length:
                raise ValueError(
                    f"Tokenized input of length {tok['input_ids'].shape[1]} exceeds max_length="
                    f"{self.max_length}. Enable `truncation=True` or raise `max_length`."
                )
        pad = lambda arr: np.pad(arr, ((0, 0), (0, self.max_length - arr.shape[1])))
        return {
            "preds_input_ids": jnp.asarray(pad(p["input_ids"])),
            "preds_attention_mask": jnp.asarray(pad(p["attention_mask"])),
            "target_input_ids": jnp.asarray(pad(t["input_ids"])),
            "target_attention_mask": jnp.asarray(pad(t["attention_mask"])),
        }

    def _compute(self, state):
        from ..functional.text.bert import bert_score

        preds = {
            "input_ids": np.asarray(state["preds_input_ids"]),
            "attention_mask": np.asarray(state["preds_attention_mask"]),
        }
        target = {
            "input_ids": np.asarray(state["target_input_ids"]),
            "attention_mask": np.asarray(state["target_attention_mask"]),
        }
        return bert_score(
            preds, target, model=self._forward, user_tokenizer=self.tokenizer, idf=self.idf,
            max_length=self.max_length, batch_size=self.batch_size, return_hash=self.return_hash,
            lang=self.lang, rescale_with_baseline=self.rescale_with_baseline,
            baseline_path=self.baseline_path, truncation=self.truncation,
            score_fn=self._dispatch_escore,
        )

    # --------------------------------------------------- jitted matching ("escore")

    def _get_escore_fn(self):
        if "escore" not in self._jit_cache:
            from ..functional.text.bert import _score_pairs

            def raw(tensor_state, n, p_emb, p_scale, t_emb, t_scale):
                # tensor_state/n are the dispatch convention's donated slots —
                # this metric has no tensor states, so both are empty/unused
                return _score_pairs(p_emb, p_scale, t_emb, t_scale)

            self._jit_cache["escore.raw"] = raw  # undonated source for _aot_program
            self._jit_cache["escore"] = jax.jit(raw) if self._enable_jit else raw
        return self._jit_cache["escore"]

    @staticmethod
    def _pad_escore(p_emb, p_scale, t_emb, t_scale) -> Tuple[tuple, int]:
        """Zero-pad one scoring batch to power-of-two (batch, token) buckets."""
        from ..functional.detection._map_eval import _bucket

        p_emb = np.asarray(p_emb, np.float32)
        t_emb = np.asarray(t_emb, np.float32)
        p_scale = np.asarray(p_scale, np.float32)
        t_scale = np.asarray(t_scale, np.float32)
        batch, length = p_emb.shape[0], max(p_emb.shape[1], t_emb.shape[1])
        b_cap = _bucket(max(batch, 1), floor=4)
        l_cap = _bucket(max(length, 1), floor=8)
        pad3 = lambda a: np.pad(a, ((0, b_cap - a.shape[0]), (0, l_cap - a.shape[1]), (0, 0)))
        pad2 = lambda a: np.pad(a, ((0, b_cap - a.shape[0]), (0, l_cap - a.shape[1])))
        padded = (
            jnp.asarray(pad3(p_emb)), jnp.asarray(pad2(p_scale)),
            jnp.asarray(pad3(t_emb)), jnp.asarray(pad2(t_scale)),
        )
        return padded, batch

    def _dispatch_escore(self, p_emb, p_scale, t_emb, t_scale):
        """``score_fn`` seam of :func:`bert_score`: pad to buckets, run the jitted
        escore program through the standard dispatch stack, slice real rows back."""
        (pe, ps, te, ts), batch = self._pad_escore(p_emb, p_scale, t_emb, t_scale)
        fn = self._get_escore_fn()
        precision, recall, f1 = self._donation_safe_dispatch(
            "escore", lambda t, n: fn(t, n, pe, ps, te, ts), {},
            inputs=((pe, ps, te, ts), {}), jitted=fn,
        )
        return precision[:batch], recall[:batch], f1[:batch]

    # ------------------------------------------------------------------ warm start

    def precompile(
        self,
        *example_inputs: Any,
        tags: Sequence[str] = ("escore",),
        cache_dir: Optional[str] = None,
        force: bool = False,
        **example_kwargs: Any,
    ) -> Dict[str, Any]:
        """Ahead-of-traffic compile of the ``"escore"`` matching program.

        ``example_inputs`` is one ``(preds, target)`` sentence batch; it is
        tokenized and embedded exactly as ``compute`` would, and the resulting
        bucketed signature is compiled into the active (or ``cache_dir``) AOT
        cache. Other tags fall back to the host no-op report."""
        tags = tuple(tags)
        report: Dict[str, Any] = {}
        rest = tuple(t for t in tags if t != "escore")
        if rest:
            report.update(super().precompile(*example_inputs, tags=rest, **example_kwargs))
        if "escore" not in tags:
            return report
        if cache_dir is not None:
            from .. import aot as _aot

            plane = _aot.AotPlane(_aot.AotConfig(cache_dir=cache_dir))
        else:
            from ..aot import _ACTIVE as plane

            if plane is None:
                raise TorchMetricsUserError(
                    "precompile needs an active AOT plane — call "
                    "torchmetrics_tpu.aot.enable(cache_dir) first, or pass cache_dir=."
                )
        if not self._enable_jit:
            report["escore"] = {"status": "skipped", "reason": "jit disabled on this metric"}
            return report
        from ..functional.text.bert import _embed, _idf_weights

        preds, target = example_inputs
        preds = [preds] if isinstance(preds, str) else list(preds)
        target = [target] if isinstance(target, str) else list(target)
        p = self._tokenize(self.tokenizer, preds, self.max_length, self.truncation)
        t = self._tokenize(self.tokenizer, target, self.max_length, self.truncation)
        # state rows are padded to max_length, so compute always scores at that width
        pad = lambda arr: np.pad(arr, ((0, 0), (0, self.max_length - arr.shape[1])))
        p = {k: pad(v) for k, v in p.items()}
        t = {k: pad(v) for k, v in t.items()}
        idf_lookup = _idf_weights(t["input_ids"], t["attention_mask"]) if self.idf else None
        p_emb, p_scale = _embed(
            self._forward, p["input_ids"], p["attention_mask"], self.max_length,
            self.idf, idf_lookup, self.batch_size,
        )
        t_emb, t_scale = _embed(
            self._forward, t["input_ids"], t["attention_mask"], self.max_length,
            self.idf, idf_lookup, self.batch_size,
        )
        (pe, ps, te, ts), _ = self._pad_escore(p_emb, p_scale, t_emb, t_scale)
        self._get_escore_fn()
        fn, donate = self._aot_program("escore")
        report["escore"] = plane.precompile_program(self, "escore", fn, donate, {}, (pe, ps, te, ts), {}, force=force)
        return report

    def __hash__(self) -> int:
        return hash((self.__class__.__name__, id(self)))


class InfoLM(HostMetric):
    """InfoLM (reference ``text/infolm.py:42``): information measures over masked-LM
    token distributions (``functional/text/infolm.py``). States are the tokenized
    sentences (4 cat states, reference ``text/infolm.py:168-171``) — rows are padded
    to ``max_length`` so cross-rank sync is static-width concatenation.

    The masked LM is pluggable: ``model_name_or_path`` loads ``AutoModelForMaskedLM``
    from the local HF cache (downloads are gated in an air-gapped environment), or
    ``model`` + ``user_tokenizer`` supply a custom pipeline (the BERTScore seam).
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        model_name_or_path: str = "bert-base-uncased",
        temperature: float = 0.25,
        information_measure: str = "kl_divergence",
        idf: bool = True,
        alpha: Optional[float] = None,
        beta: Optional[float] = None,
        device: Optional[Any] = None,
        max_length: Optional[int] = None,
        batch_size: int = 64,
        num_threads: int = 0,
        verbose: bool = True,
        return_sentence_level_score: bool = False,
        model: Optional[Callable] = None,
        user_tokenizer: Any = None,
        **kwargs: Any,
    ) -> None:
        from ..functional.text.infolm import _InformationMeasure, _infolm_prepare

        super().__init__(**kwargs)
        self.temperature = temperature
        self.idf = idf
        self.batch_size = batch_size
        self.return_sentence_level_score = return_sentence_level_score
        self._measure = _InformationMeasure(information_measure, alpha, beta)
        self._tokenizer, self._forward, self.max_length, self._special = _infolm_prepare(
            model_name_or_path, model, user_tokenizer, max_length
        )
        self.add_state("preds_input_ids", default=[], dist_reduce_fx="cat")
        self.add_state("preds_attention_mask", default=[], dist_reduce_fx="cat")
        self.add_state("target_input_ids", default=[], dist_reduce_fx="cat")
        self.add_state("target_attention_mask", default=[], dist_reduce_fx="cat")

    def _host_batch_state(self, preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> dict:
        from ..functional.text.infolm import _infolm_tokenize

        preds = [preds] if isinstance(preds, str) else list(preds)
        target = [target] if isinstance(target, str) else list(target)
        p = _infolm_tokenize(self._tokenizer, preds, self.max_length)
        t = _infolm_tokenize(self._tokenizer, target, self.max_length)
        return {
            "preds_input_ids": p["input_ids"],
            "preds_attention_mask": p["attention_mask"],
            "target_input_ids": t["input_ids"],
            "target_attention_mask": t["attention_mask"],
        }

    def _compute(self, state: dict):
        # state arrives pre-concatenated by HostMetric._concat_state
        from ..functional.text.infolm import _infolm_compute

        cat = lambda v: np.asarray(v)
        scores = _infolm_compute(
            self._forward,
            {"input_ids": cat(state["preds_input_ids"]), "attention_mask": cat(state["preds_attention_mask"])},
            {"input_ids": cat(state["target_input_ids"]), "attention_mask": cat(state["target_attention_mask"])},
            self.temperature,
            self.idf,
            self._measure,
            self._special,
            self.batch_size,
        )
        if self.return_sentence_level_score:
            return scores.mean(), scores
        return scores.mean()
