"""Pearson / Concordance correlation metric classes — running parallel moments.
Parity: reference ``regression/pearson.py:100`` (incl. ``_final_aggregation``) and
``regression/concordance.py:28``.

TPU design: the custom ``_merge`` is the exact Chan parallel-moment combination —
associative, so the same code path serves batch folding, commless ``merge_state`` and
cross-device aggregation. States register with ``dist_reduce_fx=None`` so process sync
stacks per-device moments; ``_compute`` detects the stacked leading axis and folds with
``_final_aggregation`` (mirrors the reference's multi-device handling)."""

from __future__ import annotations

from typing import Any

import numpy as np
import jax.numpy as jnp

from ..functional.regression.concordance import _concordance_corrcoef_compute
from ..functional.regression.pearson import (
    _batch_moments,
    _final_aggregation,
    _merge_moments,
    _pearson_corrcoef_compute,
)
from ..functional.regression.utils import _check_data_shape_to_num_outputs
from ..metric import Metric
from ..utilities.checks import _check_same_shape

_MOMENT_KEYS = ("mean_x", "mean_y", "max_abs_dev_x", "max_abs_dev_y", "var_x", "var_y", "corr_xy", "n_total")


class _MomentCorrelationBase(Metric):
    """Shared running-moment machinery for Pearson-style correlations."""

    is_differentiable = True
    full_state_update = True
    plot_lower_bound = -1.0
    plot_upper_bound = 1.0

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(num_outputs, int) or num_outputs < 1:
            raise ValueError("Expected argument `num_outputs` to be an int larger than 0, but got {num_outputs}")
        self.num_outputs = num_outputs
        for key in _MOMENT_KEYS[:-1]:
            self.add_state(key, default=np.zeros(self.num_outputs), dist_reduce_fx=None)
        self.add_state("n_total", default=np.zeros(self.num_outputs), dist_reduce_fx=None)

    def _batch_state(self, preds, target):
        _check_same_shape(preds, target)
        _check_data_shape_to_num_outputs(preds, target, self.num_outputs)
        preds = jnp.reshape(jnp.asarray(preds, jnp.float32), (-1, self.num_outputs))
        target = jnp.reshape(jnp.asarray(target, jnp.float32), (-1, self.num_outputs))
        moments = _batch_moments(preds, target)
        out = dict(zip(_MOMENT_KEYS, moments))
        out["n_total"] = jnp.full((self.num_outputs,), out["n_total"], jnp.float32)
        return out

    def _merge(self, a, b):
        am = tuple(a[k] for k in _MOMENT_KEYS)
        bm = tuple(b[k] for k in _MOMENT_KEYS)
        merged = _merge_moments(am, bm)
        out = dict(a)
        out.update(dict(zip(_MOMENT_KEYS, merged)))
        return out

    def reduce_state(self, state, axis_name):
        """In-graph cross-device reduction: all-gather each moment leaf and fold with
        the exact parallel combination (psum would be wrong for means/vars)."""
        import jax

        gathered = tuple(jax.lax.all_gather(state[k], axis_name, axis=0) for k in _MOMENT_KEYS)
        return dict(zip(_MOMENT_KEYS, _final_aggregation(*gathered)))

    def _final_moments(self, state):
        """Moments ready for compute — folds stacked per-device moments if present."""
        if state["mean_x"].ndim > 1:
            return dict(zip(_MOMENT_KEYS, _final_aggregation(*(state[k] for k in _MOMENT_KEYS))))
        return state


class PearsonCorrCoef(_MomentCorrelationBase):
    """Reference regression/pearson.py:100.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import PearsonCorrCoef
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = PearsonCorrCoef()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.98486954, dtype=float32)
    """

    higher_is_better = None

    def _compute(self, state):
        s = self._final_moments(state)
        return _pearson_corrcoef_compute(
            s["max_abs_dev_x"], s["max_abs_dev_y"], s["var_x"], s["var_y"], s["corr_xy"], s["n_total"]
        )


class ConcordanceCorrCoef(_MomentCorrelationBase):
    """Reference regression/concordance.py:28.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import ConcordanceCorrCoef
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = ConcordanceCorrCoef()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.9777347, dtype=float32)
    """

    higher_is_better = None

    def _compute(self, state):
        s = self._final_moments(state)
        return _concordance_corrcoef_compute(
            s["max_abs_dev_x"], s["max_abs_dev_y"], s["mean_x"], s["mean_y"], s["var_x"], s["var_y"], s["corr_xy"], s["n_total"]
        ).squeeze()
