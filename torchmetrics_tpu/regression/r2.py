"""R2 / RelativeSquaredError / ExplainedVariance metric classes. Parity: reference
``regression/{r2,rse,explained_variance}.py``."""

from __future__ import annotations

from typing import Any

import numpy as np
import jax.numpy as jnp

from ..functional.regression.explained_variance import ALLOWED_MULTIOUTPUT, _explained_variance_compute, _explained_variance_update
from ..functional.regression.r2 import _r2_score_compute, _r2_score_update, _relative_squared_error_compute
from ..metric import Metric


class R2Score(Metric):
    """Reference regression/r2.py:28.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import R2Score
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = R2Score()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.94860816, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(self, num_outputs: int = 1, adjusted: int = 0, multioutput: str = "uniform_average", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_outputs = num_outputs
        if adjusted < 0 or not isinstance(adjusted, int):
            raise ValueError("`adjusted` parameter should be an integer larger or equal to 0.")
        self.adjusted = adjusted
        if multioutput not in ("raw_values", "uniform_average", "variance_weighted"):
            raise ValueError(
                f"Invalid input to argument `multioutput`. Choose one of the following: {('raw_values', 'uniform_average', 'variance_weighted')}"
            )
        self.multioutput = multioutput
        self.add_state("sum_squared_error", default=np.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("sum_error", default=np.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("residual", default=np.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, preds, target):
        sum_squared_obs, sum_obs, rss, num_obs = _r2_score_update(preds, target)
        return {
            "sum_squared_error": sum_squared_obs,
            "sum_error": sum_obs,
            "residual": rss,
            "total": jnp.asarray(num_obs, jnp.float32),
        }

    def _compute(self, state):
        return _r2_score_compute(
            state["sum_squared_error"], state["sum_error"], state["residual"], state["total"], self.adjusted, self.multioutput
        )


class RelativeSquaredError(Metric):
    """Reference regression/rse.py:30.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import RelativeSquaredError
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = RelativeSquaredError()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.05139186, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, num_outputs: int = 1, squared: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_outputs = num_outputs
        self.squared = squared
        self.add_state("sum_squared_obs", default=np.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("sum_obs", default=np.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("sum_squared_error", default=np.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, preds, target):
        sum_squared_obs, sum_obs, rss, num_obs = _r2_score_update(preds, target)
        return {
            "sum_squared_obs": sum_squared_obs,
            "sum_obs": sum_obs,
            "sum_squared_error": rss,
            "total": jnp.asarray(num_obs, jnp.float32),
        }

    def _compute(self, state):
        return _relative_squared_error_compute(
            state["sum_squared_obs"], state["sum_obs"], state["sum_squared_error"], state["total"], self.squared
        )


class ExplainedVariance(Metric):
    """Reference regression/explained_variance.py:33.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import ExplainedVariance
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = ExplainedVariance()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.95717347, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_upper_bound = 1.0

    def __init__(self, multioutput: str = "uniform_average", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if multioutput not in ALLOWED_MULTIOUTPUT:
            raise ValueError(f"Invalid input to argument `multioutput`. Choose one of the following: {ALLOWED_MULTIOUTPUT}")
        self.multioutput = multioutput
        self.add_state("sum_error", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("sum_squared_error", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("sum_target", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("sum_squared_target", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("num_obs", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, preds, target):
        num_obs, sum_error, sum_squared_error, sum_target, sum_squared_target = _explained_variance_update(preds, target)
        return {
            "num_obs": jnp.asarray(num_obs, jnp.float32),
            "sum_error": sum_error,
            "sum_squared_error": sum_squared_error,
            "sum_target": sum_target,
            "sum_squared_target": sum_squared_target,
        }

    def _compute(self, state):
        return _explained_variance_compute(
            state["num_obs"],
            state["sum_error"],
            state["sum_squared_error"],
            state["sum_target"],
            state["sum_squared_target"],
            self.multioutput,
        )
