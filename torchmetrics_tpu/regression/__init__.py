"""Regression metric classes. Parity: reference ``regression/__init__.py`` (23 metrics,
SURVEY §2.4)."""

from .crps import ContinuousRankedProbabilityScore, CriticalSuccessIndex
from .divergence import JensenShannonDivergence, KLDivergence
from .mse import (
    LogCoshError,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    MinkowskiDistance,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
    WeightedMeanAbsolutePercentageError,
)
from .nrmse import NormalizedRootMeanSquaredError
from .pearson import ConcordanceCorrCoef, PearsonCorrCoef
from .r2 import ExplainedVariance, R2Score, RelativeSquaredError
from .rank import CosineSimilarity, KendallRankCorrCoef, SpearmanCorrCoef

__all__ = [
    "ConcordanceCorrCoef",
    "ContinuousRankedProbabilityScore",
    "CosineSimilarity",
    "CriticalSuccessIndex",
    "ExplainedVariance",
    "JensenShannonDivergence",
    "KLDivergence",
    "KendallRankCorrCoef",
    "LogCoshError",
    "MeanAbsoluteError",
    "MeanAbsolutePercentageError",
    "MeanSquaredError",
    "MeanSquaredLogError",
    "MinkowskiDistance",
    "NormalizedRootMeanSquaredError",
    "PearsonCorrCoef",
    "R2Score",
    "RelativeSquaredError",
    "SpearmanCorrCoef",
    "SymmetricMeanAbsolutePercentageError",
    "TweedieDevianceScore",
    "WeightedMeanAbsolutePercentageError",
]
