"""Normalized RMSE metric class. Parity: reference ``regression/nrmse.py:95``
(states :181-187, update :195-209, compute :217-238).

TPU design: running target statistics (min/max/mean/M2/sumsq) merge with exact parallel
formulas in a custom ``_merge`` (same trick as :class:`PearsonCorrCoef`); states register
with ``dist_reduce_fx=None`` so process sync stacks per-device stats and ``_compute``
folds the stack."""

from __future__ import annotations

from typing import Any

import numpy as np
import jax.numpy as jnp

from ..functional.regression.mse import _mean_squared_error_update
from ..functional.regression.nrmse import _normalized_root_mean_squared_error_compute
from ..metric import Metric

_KEYS = ("sum_squared_error", "total", "min_val", "max_val", "mean_val", "var_val", "target_squared")


class NormalizedRootMeanSquaredError(Metric):
    """Reference regression/nrmse.py:95.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import NormalizedRootMeanSquaredError
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = NormalizedRootMeanSquaredError()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.21299912, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = True
    plot_lower_bound = 0.0

    def __init__(self, normalization: str = "mean", num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if normalization not in ("mean", "range", "std", "l2"):
            raise ValueError(
                f"Argument `normalization` should be either 'mean', 'range', 'std' or 'l2', but got {normalization}"
            )
        self.normalization = normalization
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected num_outputs to be a positive integer but got {num_outputs}")
        self.num_outputs = num_outputs
        d = num_outputs
        self.add_state("sum_squared_error", default=np.zeros(d), dist_reduce_fx=None)
        self.add_state("total", default=np.zeros(d), dist_reduce_fx=None)
        self.add_state("min_val", default=np.full((d,), jnp.inf), dist_reduce_fx=None)
        self.add_state("max_val", default=np.full((d,), -jnp.inf), dist_reduce_fx=None)
        self.add_state("mean_val", default=np.zeros(d), dist_reduce_fx=None)
        self.add_state("var_val", default=np.zeros(d), dist_reduce_fx=None)
        self.add_state("target_squared", default=np.zeros(d), dist_reduce_fx=None)

    def _batch_state(self, preds, target):
        sum_squared_error, num_obs = _mean_squared_error_update(preds, target, self.num_outputs)
        target = jnp.asarray(target, jnp.float32)
        target = target.reshape(-1, 1) if self.num_outputs == 1 else target
        mean = target.mean(0)
        centered = target - mean
        return {
            "sum_squared_error": jnp.atleast_1d(sum_squared_error),
            "total": jnp.full((self.num_outputs,), jnp.asarray(num_obs, jnp.float32)),
            "min_val": target.min(0),
            "max_val": target.max(0),
            "mean_val": mean,
            "var_val": (centered * centered).sum(0),
            "target_squared": (target * target).sum(0),
        }

    def _merge(self, a, b):
        n_a, n_b = a["total"], b["total"]
        n = n_a + n_b
        safe_n = jnp.where(n == 0, 1.0, n)
        delta = b["mean_val"] - a["mean_val"]
        out = dict(a)
        out["total"] = n
        out["mean_val"] = a["mean_val"] + delta * n_b / safe_n
        out["var_val"] = a["var_val"] + b["var_val"] + delta * delta * n_a * n_b / safe_n
        out["min_val"] = jnp.minimum(a["min_val"], b["min_val"])
        out["max_val"] = jnp.maximum(a["max_val"], b["max_val"])
        out["sum_squared_error"] = a["sum_squared_error"] + b["sum_squared_error"]
        out["target_squared"] = a["target_squared"] + b["target_squared"]
        return out

    def reduce_state(self, state, axis_name):
        """In-graph cross-device reduction via all-gather + exact parallel fold."""
        import jax

        gathered = {k: jax.lax.all_gather(state[k], axis_name, axis=0) for k in _KEYS}
        acc = {k: gathered[k][0] for k in _KEYS}
        for i in range(1, jax.lax.psum(1, axis_name)):  # static axis size (folds at trace)
            acc = self._merge(acc, {k: gathered[k][i] for k in _KEYS})
        return acc

    def _compute(self, state):
        if state["mean_val"].ndim > 1:  # stacked per-device stats from process sync
            acc = {k: state[k][0] for k in _KEYS}
            for i in range(1, state["mean_val"].shape[0]):
                acc = self._merge(acc, {k: state[k][i] for k in _KEYS})
            state = acc
        if self.normalization == "mean":
            denom = state["mean_val"]
        elif self.normalization == "range":
            denom = state["max_val"] - state["min_val"]
        elif self.normalization == "std":
            denom = jnp.sqrt(state["var_val"] / state["total"])
        else:
            denom = jnp.sqrt(state["target_squared"])
        return _normalized_root_mean_squared_error_compute(state["sum_squared_error"], state["total"], denom).squeeze()
