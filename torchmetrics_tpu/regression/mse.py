"""MSE / MAE / MSLE / MAPE / SMAPE / WMAPE / LogCosh / Minkowski / Tweedie metric
classes — all simple sum-state accumulators. Parity: reference ``regression/{mse,mae,
log_mse,mape,symmetric_mape,wmape,log_cosh,minkowski,tweedie_deviance}.py``."""

from __future__ import annotations

from typing import Any

import numpy as np
import jax.numpy as jnp

from ..functional.regression.log_mse import (
    _log_cosh_error_compute,
    _log_cosh_error_update,
    _mean_squared_log_error_compute,
    _mean_squared_log_error_update,
)
from ..functional.regression.mae import _mean_absolute_error_compute, _mean_absolute_error_update
from ..functional.regression.mape import (
    _mean_absolute_percentage_error_compute,
    _mean_absolute_percentage_error_update,
    _symmetric_mean_absolute_percentage_error_compute,
    _symmetric_mean_absolute_percentage_error_update,
    _weighted_mean_absolute_percentage_error_compute,
    _weighted_mean_absolute_percentage_error_update,
)
from ..functional.regression.minkowski import _minkowski_distance_compute, _minkowski_distance_update
from ..functional.regression.mse import _mean_squared_error_compute, _mean_squared_error_update
from ..functional.regression.tweedie_deviance import (
    _tweedie_deviance_score_compute,
    _tweedie_deviance_score_update,
)
from ..metric import Metric
from ..utilities.exceptions import TorchMetricsUserError


class MeanSquaredError(Metric):
    """MSE (or RMSE with ``squared=False``). Reference regression/mse.py:29.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import MeanSquaredError
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = MeanSquaredError()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.375, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, squared: bool = True, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(squared, bool):
            raise ValueError(f"Expected argument `squared` to be a boolean but got {squared}")
        self.squared = squared
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected num_outputs to be a positive integer but got {num_outputs}")
        self.num_outputs = num_outputs
        self.add_state("sum_squared_error", default=np.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, preds, target):
        sse, n = _mean_squared_error_update(preds, target, self.num_outputs)
        return {"sum_squared_error": sse, "total": jnp.asarray(n, jnp.float32)}

    def _compute(self, state):
        return _mean_squared_error_compute(state["sum_squared_error"], state["total"], self.squared).squeeze()


class MeanAbsoluteError(Metric):
    """Reference regression/mae.py:29.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import MeanAbsoluteError
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = MeanAbsoluteError()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.5, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected num_outputs to be a positive integer but got {num_outputs}")
        self.num_outputs = num_outputs
        self.add_state("sum_abs_error", default=np.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, preds, target):
        sae, n = _mean_absolute_error_update(preds, target, self.num_outputs)
        return {"sum_abs_error": sae, "total": jnp.asarray(n, jnp.float32)}

    def _compute(self, state):
        return _mean_absolute_error_compute(state["sum_abs_error"], state["total"]).squeeze()


class MeanSquaredLogError(Metric):
    """Reference regression/log_mse.py:28.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import MeanSquaredLogError
        >>> preds = jnp.asarray([2.5, 1.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, 1.5, 2.0, 7.0])
        >>> metric = MeanSquaredLogError()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.02037413, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_squared_log_error", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, preds, target):
        s, n = _mean_squared_log_error_update(preds, target)
        return {"sum_squared_log_error": s, "total": jnp.asarray(n, jnp.float32)}

    def _compute(self, state):
        return _mean_squared_log_error_compute(state["sum_squared_log_error"], state["total"])


class MeanAbsolutePercentageError(Metric):
    """Reference regression/mape.py:31.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import MeanAbsolutePercentageError
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = MeanAbsolutePercentageError()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.32738096, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_per_error", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, preds, target):
        s, n = _mean_absolute_percentage_error_update(preds, target)
        return {"sum_abs_per_error": s, "total": jnp.asarray(n, jnp.float32)}

    def _compute(self, state):
        return _mean_absolute_percentage_error_compute(state["sum_abs_per_error"], state["total"])


class SymmetricMeanAbsolutePercentageError(Metric):
    """Reference regression/symmetric_mape.py:31.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import SymmetricMeanAbsolutePercentageError
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = SymmetricMeanAbsolutePercentageError()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.5787879, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_per_error", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, preds, target):
        s, n = _symmetric_mean_absolute_percentage_error_update(preds, target)
        return {"sum_abs_per_error": s, "total": jnp.asarray(n, jnp.float32)}

    def _compute(self, state):
        return _symmetric_mean_absolute_percentage_error_compute(state["sum_abs_per_error"], state["total"])


class WeightedMeanAbsolutePercentageError(Metric):
    """Reference regression/wmape.py:32.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import WeightedMeanAbsolutePercentageError
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = WeightedMeanAbsolutePercentageError()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.16, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_error", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("sum_scale", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, preds, target):
        sae, scale = _weighted_mean_absolute_percentage_error_update(preds, target)
        return {"sum_abs_error": sae, "sum_scale": scale}

    def _compute(self, state):
        return _weighted_mean_absolute_percentage_error_compute(state["sum_abs_error"], state["sum_scale"])


class LogCoshError(Metric):
    """Reference regression/log_cosh.py:29.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import LogCoshError
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = LogCoshError()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.16850246, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected num_outputs to be a positive integer but got {num_outputs}")
        self.num_outputs = num_outputs
        self.add_state("sum_log_cosh_error", default=np.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, preds, target):
        s, n = _log_cosh_error_update(preds, target, self.num_outputs)
        return {"sum_log_cosh_error": s, "total": jnp.asarray(n, jnp.float32)}

    def _compute(self, state):
        return _log_cosh_error_compute(state["sum_log_cosh_error"], state["total"])


class MinkowskiDistance(Metric):
    """Reference regression/minkowski.py:30.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import MinkowskiDistance
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = MinkowskiDistance(p=3)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(1.0772173, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, p: float, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(p, (float, int)) and p >= 1):
            raise TorchMetricsUserError(f"Argument ``p`` must be a float or int greater than 1, but got {p}")
        self.p = p
        self.add_state("minkowski_dist_sum", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, preds, targets):
        return {"minkowski_dist_sum": _minkowski_distance_update(preds, targets, self.p)}

    def _compute(self, state):
        return _minkowski_distance_compute(state["minkowski_dist_sum"], self.p)


class TweedieDevianceScore(Metric):
    """Reference regression/tweedie_deviance.py:32.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import TweedieDevianceScore
        >>> preds = jnp.asarray([2.5, 0.5, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, 0.5, 2.0, 7.0])
        >>> metric = TweedieDevianceScore(power=1.5)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.0262022, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, power: float = 0.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if 0 < power < 1:
            raise ValueError(f"Deviance Score is not defined for power={power}.")
        self.power = power
        self.add_state("sum_deviance_score", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("num_observations", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, preds, targets):
        s, n = _tweedie_deviance_score_update(preds, targets, self.power)
        return {"sum_deviance_score": s, "num_observations": n}

    def _compute(self, state):
        return _tweedie_deviance_score_compute(state["sum_deviance_score"], state["num_observations"])
