"""CRPS + CriticalSuccessIndex metric classes. Parity: reference
``regression/{crps,csi}.py``."""

from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax.numpy as jnp

from ..functional.regression.crps import _crps_update
from ..functional.regression.csi import _critical_success_index_compute, _critical_success_index_update
from ..metric import Metric


class ContinuousRankedProbabilityScore(Metric):
    """Reference regression/crps.py:29. Sum-state formulation: mean(diff−spread) over
    all samples ≡ (Σdiff − Σspread)/N, so three scalar sum states suffice.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import ContinuousRankedProbabilityScore
        >>> preds = jnp.asarray([[1.0, 2.0, 3.0], [2.0, 3.0, 4.0]])
        >>> target = jnp.asarray([2.0, 3.0])
        >>> metric = ContinuousRankedProbabilityScore()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.22222224, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("diff_sum", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("ensemble_sum", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, preds, target):
        batch_size, diff, ensemble_sum = _crps_update(preds, target)
        return {
            "diff_sum": diff.sum(),
            "ensemble_sum": ensemble_sum.sum(),
            "total": jnp.asarray(batch_size, jnp.float32),
        }

    def _compute(self, state):
        return (state["diff_sum"] - state["ensemble_sum"]) / state["total"]


class CriticalSuccessIndex(Metric):
    """Reference regression/csi.py:24.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import CriticalSuccessIndex
        >>> preds = jnp.asarray([0.2, 0.7, 0.9, 0.4])
        >>> target = jnp.asarray([0.1, 0.8, 0.6, 0.7])
        >>> metric = CriticalSuccessIndex(0.5)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.6666667, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(self, threshold: float, keep_sequence_dim: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.threshold = float(threshold)
        if keep_sequence_dim is not None and (not isinstance(keep_sequence_dim, int) or keep_sequence_dim < 0):
            raise ValueError(f"Expected keep_sequence_dim to be int or None but got {keep_sequence_dim}")
        self.keep_sequence_dim = keep_sequence_dim
        if keep_sequence_dim is None:
            self.add_state("hits", default=np.zeros(()), dist_reduce_fx="sum")
            self.add_state("misses", default=np.zeros(()), dist_reduce_fx="sum")
            self.add_state("false_alarms", default=np.zeros(()), dist_reduce_fx="sum")
        else:
            self.add_state("hits", default=[], dist_reduce_fx="cat")
            self.add_state("misses", default=[], dist_reduce_fx="cat")
            self.add_state("false_alarms", default=[], dist_reduce_fx="cat")

    def _batch_state(self, preds, target):
        hits, misses, false_alarms = _critical_success_index_update(preds, target, self.threshold, self.keep_sequence_dim)
        return {
            "hits": hits.astype(jnp.float32),
            "misses": misses.astype(jnp.float32),
            "false_alarms": false_alarms.astype(jnp.float32),
        }

    def _compute(self, state):
        return _critical_success_index_compute(state["hits"], state["misses"], state["false_alarms"])
