"""KL / Jensen-Shannon divergence metric classes. Parity: reference
``regression/{kl_divergence,js_divergence}.py``."""

from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax.numpy as jnp

from ..functional.regression.kl_divergence import _jsd_update, _kld_compute, _kld_update
from ..metric import Metric


class _DivergenceBase(Metric):
    """Shared state plumbing: scalar sum state when reducing, concat state otherwise."""

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, log_prob: bool = False, reduction: Optional[str] = "mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(log_prob, bool):
            raise TypeError(f"Expected argument `log_prob` to be bool but got {log_prob}")
        self.log_prob = log_prob
        allowed_reduction = ("mean", "sum", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction

        if self.reduction in ("mean", "sum"):
            self.add_state("measures", default=np.zeros(()), dist_reduce_fx="sum")
        else:
            self.add_state("measures", default=[], dist_reduce_fx="cat")
        self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")

    def _measures(self, p, q):
        raise NotImplementedError

    def _batch_state(self, p, q):
        measures, total = self._measures(p, q)
        if self.reduction in ("mean", "sum"):
            measures = measures.sum()
        return {"measures": measures, "total": jnp.asarray(total, jnp.float32)}

    def _compute(self, state):
        measures = state["measures"]
        if self.reduction == "mean":
            return measures / state["total"]
        if self.reduction == "sum":
            return measures
        return _kld_compute(measures, state["total"], self.reduction)


class KLDivergence(_DivergenceBase):
    """Reference regression/kl_divergence.py:31.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import KLDivergence
        >>> preds = jnp.asarray([[0.36, 0.48, 0.16]])
        >>> target = jnp.asarray([[1/3, 1/3, 1/3]])
        >>> metric = KLDivergence()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.08529959, dtype=float32)
    """

    def _measures(self, p, q):
        return _kld_update(p, q, self.log_prob)


class JensenShannonDivergence(_DivergenceBase):
    """Reference regression/js_divergence.py:31.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import JensenShannonDivergence
        >>> preds = jnp.asarray([[0.36, 0.48, 0.16]])
        >>> target = jnp.asarray([[1/3, 1/3, 1/3]])
        >>> metric = JensenShannonDivergence()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.02245985, dtype=float32)
    """

    def _measures(self, p, q):
        return _jsd_update(p, q, self.log_prob)
