"""Rank-based correlation metric classes (Spearman, Kendall) + CosineSimilarity —
concat-state metrics (raw samples kept, ranked/scored at compute). Parity: reference
``regression/{spearman,kendall,cosine_similarity}.py``."""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from ..functional.regression.cosine_similarity import _cosine_similarity_compute, _cosine_similarity_update
from ..functional.regression.kendall import (
    _ALLOWED_ALTERNATIVES,
    _ALLOWED_VARIANTS,
    _kendall_corrcoef_compute,
)
from ..functional.regression.spearman import _spearman_corrcoef_compute, _spearman_corrcoef_update
from ..metric import Metric


class SpearmanCorrCoef(Metric):
    """Reference regression/spearman.py:30.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import SpearmanCorrCoef
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = SpearmanCorrCoef()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.9999992, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = -1.0
    plot_upper_bound = 1.0

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(num_outputs, int) or num_outputs < 1:
            raise ValueError("Expected argument `num_outputs` to be an int larger than 0, but got {num_outputs}")
        self.num_outputs = num_outputs
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def _batch_state(self, preds, target):
        preds, target = _spearman_corrcoef_update(preds, target, self.num_outputs)
        return {"preds": preds, "target": target}

    def _compute(self, state):
        return _spearman_corrcoef_compute(state["preds"], state["target"])


class KendallRankCorrCoef(Metric):
    """Reference regression/kendall.py:36.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import KendallRankCorrCoef
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = KendallRankCorrCoef()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = True
    plot_lower_bound = -1.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        variant: str = "b",
        t_test: bool = False,
        alternative: Optional[str] = "two-sided",
        num_outputs: int = 1,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if variant not in _ALLOWED_VARIANTS:
            raise ValueError(f"Argument `variant` is expected to be one of {_ALLOWED_VARIANTS}, but got {variant!r}")
        if not isinstance(t_test, bool):
            raise ValueError(f"Argument `t_test` is expected to be of a type `bool`, but got {t_test}.")
        if t_test and alternative not in _ALLOWED_ALTERNATIVES:
            raise ValueError(f"Argument `alternative` is expected to be one of {_ALLOWED_ALTERNATIVES}, but got {alternative!r}")
        self.variant = variant
        self.alternative = alternative if t_test else None
        self.t_test = t_test
        self.num_outputs = num_outputs
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def _batch_state(self, preds, target):
        return {"preds": jnp.asarray(preds, jnp.float32), "target": jnp.asarray(target, jnp.float32)}

    def _compute(self, state):
        tau, p_value = _kendall_corrcoef_compute(
            state["preds"], state["target"], self.variant, self.t_test, self.alternative
        )
        if p_value is not None:
            return tau, p_value
        return tau


class CosineSimilarity(Metric):
    """Reference regression/cosine_similarity.py:30.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import CosineSimilarity
        >>> preds = jnp.asarray([[1.0, 2.0, 3.0], [1.0, 0.0, 1.0]])
        >>> target = jnp.asarray([[1.0, 2.0, 2.0], [0.5, 0.0, 1.0]])
        >>> metric = CosineSimilarity(reduction='mean')
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.96432054, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, reduction: str = "sum", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        allowed_reduction = ("sum", "mean", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def _batch_state(self, preds, target):
        preds, target = _cosine_similarity_update(preds, target)
        return {"preds": preds, "target": target}

    def _compute(self, state):
        return _cosine_similarity_compute(state["preds"], state["target"], self.reduction)
