"""Retrieval metric classes (reference ``retrieval/*.py``), all over the padded-kernel
base. One class per reference file; top_k/adaptive_k knobs match the reference."""

from __future__ import annotations

from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp

from ..functional.retrieval._kernels import (
    _ap_kernel,
    _auroc_kernel,
    _fall_out_kernel,
    _hit_rate_kernel,
    _ndcg_kernel,
    _precision_kernel,
    _r_precision_kernel,
    _recall_kernel,
    _rr_kernel,
)
from .base import RetrievalMetric, _retrieval_aggregate

Array = jax.Array


def _validate_top_k(top_k: Optional[int]) -> None:
    if top_k is not None and not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")


class _TopKRetrievalMetric(RetrievalMetric):
    """Shared top_k plumbing."""

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation: Union[str, Callable] = "mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k


class RetrievalMAP(_TopKRetrievalMetric):
    """Mean Average Precision (reference retrieval/average_precision.py:29).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalMAP
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> metric = RetrievalMAP()
        >>> metric.update(preds, target, indexes=indexes)
        >>> metric.compute()
        Array(0.7916667, dtype=float32)
    """

    def _metric_padded(self, preds, target, mask):
        return _ap_kernel(preds, target, mask, self.top_k)


class RetrievalMRR(_TopKRetrievalMetric):
    """Mean Reciprocal Rank (reference retrieval/reciprocal_rank.py:29).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalMRR
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> metric = RetrievalMRR()
        >>> metric.update(preds, target, indexes=indexes)
        >>> metric.compute()
        Array(0.75, dtype=float32)
    """

    def _metric_padded(self, preds, target, mask):
        return _rr_kernel(preds, target, mask, self.top_k)


class RetrievalPrecision(_TopKRetrievalMetric):
    """Precision@k (reference retrieval/precision.py:29).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalPrecision
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> metric = RetrievalPrecision(top_k=2)
        >>> metric.update(preds, target, indexes=indexes)
        >>> metric.compute()
        Array(0.5, dtype=float32)
    """

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, adaptive_k: bool = False,
                 aggregation: Union[str, Callable] = "mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, top_k, aggregation, **kwargs)
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.adaptive_k = adaptive_k

    def _metric_padded(self, preds, target, mask):
        return _precision_kernel(preds, target, mask, self.top_k, self.adaptive_k)


class RetrievalRecall(_TopKRetrievalMetric):
    """Recall@k (reference retrieval/recall.py:29).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalRecall
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> metric = RetrievalRecall(top_k=2)
        >>> metric.update(preds, target, indexes=indexes)
        >>> metric.compute()
        Array(0.75, dtype=float32)
    """

    def _metric_padded(self, preds, target, mask):
        return _recall_kernel(preds, target, mask, self.top_k)


class RetrievalHitRate(_TopKRetrievalMetric):
    """HitRate@k (reference retrieval/hit_rate.py:29).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalHitRate
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> metric = RetrievalHitRate(top_k=2)
        >>> metric.update(preds, target, indexes=indexes)
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    def _metric_padded(self, preds, target, mask):
        return _hit_rate_kernel(preds, target, mask, self.top_k)


class RetrievalFallOut(_TopKRetrievalMetric):
    """FallOut@k (reference retrieval/fall_out.py:31). Lower is better; the empty-query
    policy keys on queries with no NEGATIVE targets.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalFallOut
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> metric = RetrievalFallOut(top_k=2)
        >>> metric.update(preds, target, indexes=indexes)
        >>> metric.compute()
        Array(0.5, dtype=float32)
    """

    higher_is_better = False

    def __init__(self, empty_target_action: str = "pos", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation: Union[str, Callable] = "mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, top_k, aggregation, **kwargs)

    def _empty_query_mask(self, target2d, mask):
        return (jnp.where(mask, 1 - target2d, 0) > 0).sum(axis=-1) == 0

    def _metric_padded(self, preds, target, mask):
        return _fall_out_kernel(preds, target, mask, self.top_k)


class RetrievalRPrecision(RetrievalMetric):
    """R-Precision (reference retrieval/r_precision.py:28).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalRPrecision
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> metric = RetrievalRPrecision()
        >>> metric.update(preds, target, indexes=indexes)
        >>> metric.compute()
        Array(0.75, dtype=float32)
    """

    def _metric_padded(self, preds, target, mask):
        return _r_precision_kernel(preds, target, mask)


class RetrievalNormalizedDCG(_TopKRetrievalMetric):
    """NDCG@k; non-binary gains allowed (reference retrieval/ndcg.py:29).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalNormalizedDCG
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> metric = RetrievalNormalizedDCG()
        >>> metric.update(preds, target, indexes=indexes)
        >>> metric.compute()
        Array(0.8467132, dtype=float32)
    """

    allow_non_binary_target = True

    def _metric_padded(self, preds, target, mask):
        return _ndcg_kernel(preds, target, mask, self.top_k)


class RetrievalAUROC(_TopKRetrievalMetric):
    """Per-query AUROC (reference retrieval/auroc.py:29).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalAUROC
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> metric = RetrievalAUROC()
        >>> metric.update(preds, target, indexes=indexes)
        >>> metric.compute()
        Array(0.75, dtype=float32)
    """

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, max_fpr: Optional[float] = None,
                 aggregation: Union[str, Callable] = "mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, top_k, aggregation, **kwargs)
        if max_fpr is not None and not (isinstance(max_fpr, float) and 0 < max_fpr <= 1):
            raise ValueError(f"Argument `max_fpr` should be a float in range (0, 1], but got: {max_fpr}")
        self.max_fpr = max_fpr

    def _metric_padded(self, preds, target, mask):
        if self.max_fpr is not None:
            from ..functional.retrieval import retrieval_auroc
            import numpy as np

            out = []
            for q in range(preds.shape[0]):
                keep = np.asarray(mask[q])
                out.append(retrieval_auroc(preds[q][keep], target[q][keep], self.top_k, self.max_fpr))
            return jnp.stack(out)
        return _auroc_kernel(preds, target, mask, self.top_k)


class RetrievalPrecisionRecallCurve(RetrievalMetric):
    """Averaged precision/recall @ k=1..max_k curves
    (reference retrieval/precision_recall_curve.py:64).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalPrecisionRecallCurve
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> metric = RetrievalPrecisionRecallCurve(max_k=4)
        >>> metric.update(preds, target, indexes=indexes)
        >>> precisions, recalls, top_k = metric.compute()
        >>> precisions
        Array([0.5  , 0.5  , 0.5  , 0.375], dtype=float32)
        >>> recalls
        Array([0.5 , 0.75, 1.  , 1.  ], dtype=float32)
    """

    higher_is_better = None

    def __init__(self, max_k: Optional[int] = None, adaptive_k: bool = False,
                 empty_target_action: str = "neg", ignore_index: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, "mean", **kwargs)
        if max_k is not None and not (isinstance(max_k, int) and max_k > 0):
            raise ValueError("`max_k` has to be a positive integer or None")
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.max_k = max_k
        self.adaptive_k = adaptive_k

    def _compute(self, state):
        from ..functional.retrieval.utils import _pad_queries

        preds2d, target2d, mask = _pad_queries(state["indexes"], state["preds"], state["target"])
        q, n = preds2d.shape
        max_k = self.max_k or n
        if self.adaptive_k and max_k > n:
            max_k = n
        ks = jnp.arange(1, max_k + 1)
        tgt = jnp.where(preds2d > 0, target2d, 0)
        from ..functional.retrieval.utils import _ranked_by_preds

        ranked, rmask = _ranked_by_preds(preds2d, tgt, mask)
        rel = ((ranked > 0) & rmask).astype(jnp.float32)
        cum = jnp.cumsum(rel, axis=-1)
        cum_k = cum[:, jnp.minimum(ks - 1, n - 1)]  # (Q, K)
        denom = jnp.minimum(ks.astype(jnp.float32), mask.sum(-1, keepdims=True).astype(jnp.float32)) if self.adaptive_k else ks.astype(jnp.float32)[None, :]
        precision_q = cum_k / denom
        total = (jnp.where(mask, target2d, 0) > 0).sum(axis=-1, keepdims=True).astype(jnp.float32)
        recall_q = jnp.where(total > 0, cum_k / jnp.maximum(total, 1.0), 0.0)
        empty = self._empty_query_mask(target2d, mask)
        if self.empty_target_action == "error" and bool(empty.any()):
            raise ValueError("`compute` method was provided with a query with no positive target.")
        if self.empty_target_action == "pos":
            precision_q = jnp.where(empty[:, None], 1.0, precision_q)
            recall_q = jnp.where(empty[:, None], 1.0, recall_q)
        elif self.empty_target_action == "neg":
            precision_q = jnp.where(empty[:, None], 0.0, precision_q)
            recall_q = jnp.where(empty[:, None], 0.0, recall_q)
        elif self.empty_target_action == "skip":
            keep = ~empty
            precision_q, recall_q = precision_q[keep], recall_q[keep]
            if precision_q.shape[0] == 0:
                z = jnp.zeros(max_k)
                return z, z, ks
        return precision_q.mean(axis=0), recall_q.mean(axis=0), ks


class RetrievalRecallAtFixedPrecision(RetrievalPrecisionRecallCurve):
    """Max recall@k with averaged precision@k >= floor
    (reference retrieval/precision_recall_curve.py:297).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalRecallAtFixedPrecision
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> metric = RetrievalRecallAtFixedPrecision(min_precision=0.5, max_k=4)
        >>> metric.update(preds, target, indexes=indexes)
        >>> metric.compute()
        (Array(1., dtype=float32), Array(3, dtype=int32))
    """

    higher_is_better = True

    def __init__(self, min_precision: float = 0.0, max_k: Optional[int] = None, adaptive_k: bool = False,
                 empty_target_action: str = "neg", ignore_index: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(max_k, adaptive_k, empty_target_action, ignore_index, **kwargs)
        if not (isinstance(min_precision, float) and 0.0 <= min_precision <= 1.0):
            raise ValueError("`min_precision` has to be a positive float between 0 and 1")
        self.min_precision = min_precision

    def _compute(self, state):
        precision, recall, ks = super()._compute(state)
        feasible = precision >= self.min_precision
        masked = jnp.where(feasible, recall, -jnp.inf)
        best_r = masked.max()
        if not bool(feasible.any()):
            return jnp.zeros(()), jnp.asarray(self.max_k or int(ks[-1]))
        # reference max((r, k)) tuple-max: among max-recall ties pick the LARGEST k
        # (recall is non-decreasing in k, so ties at the max are the norm)
        ties = masked == best_r
        best_k = ks[int(jnp.max(jnp.where(ties, jnp.arange(ks.shape[0]), -1)))]
        if float(best_r) == 0.0:
            # reference clamps best_k to max_k when no recall is achievable
            best_k = jnp.asarray(self.max_k or int(ks[-1]))
        return best_r, best_k
