"""Retrieval domain (reference ``src/torchmetrics/retrieval/``)."""

from .base import RetrievalMetric
from .metrics import (
    RetrievalAUROC,
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalPrecisionRecallCurve,
    RetrievalRPrecision,
    RetrievalRecall,
    RetrievalRecallAtFixedPrecision,
)

__all__ = [
    "RetrievalAUROC",
    "RetrievalFallOut",
    "RetrievalHitRate",
    "RetrievalMAP",
    "RetrievalMRR",
    "RetrievalMetric",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalPrecisionRecallCurve",
    "RetrievalRPrecision",
    "RetrievalRecall",
    "RetrievalRecallAtFixedPrecision",
]
