"""RetrievalMetric base (reference ``retrieval/base.py:43``).

State: cat-lists of flat (indexes, preds, target). Compute: pad queries into a dense
``(Q, L)`` matrix and run ONE vectorized masked kernel for all queries — the TPU-native
replacement for the reference's sort → bincount → host split-loop
(retrieval/base.py:148-182). Empty-target policy and aggregation applied on the
resulting ``(Q,)`` score vector.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp

from ..functional.retrieval.utils import _check_retrieval_inputs, _pad_queries
from ..metric import Metric

Array = jax.Array


def _retrieval_aggregate(values: Array, aggregation: Union[str, Callable]) -> Array:
    """Reduce per-query scores (reference retrieval/base.py:_retrieval_aggregate)."""
    if callable(aggregation):
        return aggregation(values)
    if aggregation == "mean":
        return values.mean()
    if aggregation == "median":
        return jnp.median(values)
    if aggregation == "min":
        return values.min()
    if aggregation == "max":
        return values.max()
    raise ValueError(f"Unknown aggregation {aggregation}")


class RetrievalMetric(Metric):
    """Base class: group-by-query scoring with empty-target policy.

    Subclasses implement ``_metric_padded(preds, target, mask) -> (Q,)``.
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    allow_non_binary_target = False
    _jittable_compute = False

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        aggregation: Union[str, Callable] = "mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index
        if not (aggregation in ("mean", "median", "min", "max") or callable(aggregation)):
            raise ValueError(
                "Argument `aggregation` must be one of `mean`, `median`, `min`, `max` or a custom callable function"
                f"which takes tensor of values, but got {aggregation}."
            )
        self.aggregation = aggregation
        self.add_state("indexes", default=[], dist_reduce_fx="cat")
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def _prepare_inputs(self, preds, target, indexes):
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        indexes, preds, target = _check_retrieval_inputs(
            indexes, preds, target, allow_non_binary_target=self.allow_non_binary_target,
            ignore_index=self.ignore_index,
        )
        return (preds, target, indexes), {}

    def _batch_state(self, preds, target, indexes):
        return {"indexes": indexes, "preds": preds, "target": target}

    def _empty_query_mask(self, target2d: Array, mask: Array) -> Array:
        """(Q,) bool — queries lacking a positive target (subclasses may invert)."""
        return (jnp.where(mask, target2d, 0) > 0).sum(axis=-1) == 0

    def _metric_padded(self, preds: Array, target: Array, mask: Array) -> Array:
        raise NotImplementedError

    def _metric(self, preds: Array, target: Array) -> Array:
        """Single-query score (parity hook; the padded kernel is the fast path)."""
        p = jnp.asarray(preds)[None, :]
        t = jnp.asarray(target)[None, :]
        return self._metric_padded(p, t, jnp.ones(p.shape, bool))[0]

    def _compute(self, state):
        preds2d, target2d, mask = _pad_queries(state["indexes"], state["preds"], state["target"])
        scores = self._metric_padded(preds2d, target2d, mask)
        empty = self._empty_query_mask(target2d, mask)
        if self.empty_target_action == "error" and bool(empty.any()):
            raise ValueError("`compute` method was provided with a query with no positive target.")
        if self.empty_target_action == "pos":
            scores = jnp.where(empty, 1.0, scores)
        elif self.empty_target_action == "neg":
            scores = jnp.where(empty, 0.0, scores)
        elif self.empty_target_action == "skip":
            keep = ~empty  # host-side boolean filter (compute is a host path)
            scores = scores[keep]
            if scores.size == 0:
                return jnp.zeros(())
        return _retrieval_aggregate(scores, self.aggregation)
