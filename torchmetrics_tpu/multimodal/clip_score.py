"""CLIPScore metric class (reference ``multimodal/clip_score.py:49``; states ``:193-195``)."""

from __future__ import annotations

from typing import Any, Union

import jax.numpy as jnp

from ..functional.multimodal.clip_score import _clip_score_update, _resolve_clip
from ..metric import HostMetric


class CLIPScore(HostMetric):
    """Running-mean CLIP score (two sum states; sync is two psums). The embedder is a
    HF checkpoint (local cache only — no egress) or a custom object with
    ``get_image_features``/``get_text_features`` (e.g. a jitted flax CLIP apply)."""
    # extractor attribute FeatureShare dedupes (reference declares the same name)
    feature_network: str = "model"

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 100.0

    def __init__(
        self,
        model_name_or_path: Union[str, Any] = "openai/clip-vit-large-patch14",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.model = _resolve_clip(model_name_or_path)
        self.add_state("score", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("n_samples", jnp.zeros((), jnp.int32), dist_reduce_fx="sum")

    def _host_batch_state(self, source, target):
        score, n_samples = _clip_score_update(source, target, self.model)
        return {"score": score.sum(), "n_samples": jnp.asarray(n_samples, jnp.int32)}

    def _compute(self, state):
        return jnp.maximum(state["score"] / state["n_samples"], 0.0)

    def __hash__(self) -> int:
        return hash((self.__class__.__name__, id(self)))
