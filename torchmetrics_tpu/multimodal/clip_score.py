"""CLIPScore metric class (reference ``multimodal/clip_score.py:49``; states ``:193-195``)."""

from __future__ import annotations

from typing import Any, Dict, Tuple, Union

import jax.numpy as jnp
import numpy as np

from ..functional.detection._map_eval import _bucket
from ..functional.multimodal.clip_score import _clip_score_features, _resolve_clip
from ..metric import Metric


class CLIPScore(Metric):
    """Running-mean CLIP score (two sum states; sync is two psums). The embedder is a
    HF checkpoint (local cache only — no egress) or a custom object with
    ``get_image_features``/``get_text_features`` (e.g. a jitted flax CLIP apply).

    Re-homed from the eager host path: the embedder runs in ``_prepare_inputs`` (it is
    arbitrary host code), but the scoring half — normalize + paired cosine x 100 —
    traces into the standard donated "update" program, so it jit-compiles once per
    bucketed batch size and AOT-caches like any device metric. Feature batches are
    zero-padded to power-of-two buckets with an explicit validity mask; padded rows
    score 0 and are excluded from the sample count.
    """

    # extractor attribute FeatureShare dedupes (reference declares the same name)
    feature_network: str = "model"

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 100.0

    def __init__(
        self,
        model_name_or_path: Union[str, Any] = "openai/clip-vit-large-patch14",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.model = _resolve_clip(model_name_or_path)
        self.add_state("score", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("n_samples", jnp.zeros((), jnp.int32), dist_reduce_fx="sum")

    def _prepare_inputs(self, source, target) -> Tuple[tuple, dict]:
        src, tgt = _clip_score_features(source, target, self.model)
        src = np.asarray(src, np.float32)
        tgt = np.asarray(tgt, np.float32)
        n = src.shape[0]
        cap = _bucket(max(n, 1), floor=4)
        src_p = np.zeros((cap, src.shape[1]), np.float32)
        tgt_p = np.zeros((cap, tgt.shape[1]), np.float32)
        mask = np.zeros((cap,), np.float32)
        src_p[:n], tgt_p[:n], mask[:n] = src, tgt, 1.0
        return (jnp.asarray(src_p), jnp.asarray(tgt_p), jnp.asarray(mask)), {}

    def _batch_state(self, source_features, target_features, mask) -> Dict[str, jnp.ndarray]:
        # the norm guard only engages on zero-padded rows (real embeddings have
        # norms far above 1e-8); padded rows then contribute exactly 0
        s = source_features / jnp.maximum(jnp.linalg.norm(source_features, axis=-1, keepdims=True), 1e-8)
        t = target_features / jnp.maximum(jnp.linalg.norm(target_features, axis=-1, keepdims=True), 1e-8)
        score = (100 * (s * t).sum(axis=-1) * mask).sum()
        return {"score": score, "n_samples": mask.sum().astype(jnp.int32)}

    def _compute(self, state):
        return jnp.maximum(state["score"] / state["n_samples"], 0.0)

    def __hash__(self) -> int:
        return hash((self.__class__.__name__, id(self)))
