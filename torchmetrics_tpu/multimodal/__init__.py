"""Multimodal tower — stateful metric classes (reference ``src/torchmetrics/multimodal/``)."""

from .clip_iqa import CLIPImageQualityAssessment
from .clip_score import CLIPScore
from .lve import LipVertexError

__all__ = ["CLIPImageQualityAssessment", "CLIPScore", "LipVertexError"]
