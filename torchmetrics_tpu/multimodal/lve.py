"""LipVertexError metric class (reference ``multimodal/lve.py:28``)."""

from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp

from ..functional.multimodal.lve import lip_vertex_error
from ..metric import Metric


class LipVertexError(Metric):
    """Running-mean LVE over update calls (sum + count states).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.multimodal import LipVertexError
        >>> vertices_pred = (jnp.arange(90, dtype=jnp.float32).reshape(5, 6, 3) * 37 % 19) / 19
        >>> vertices_gt = (jnp.arange(90, dtype=jnp.float32).reshape(5, 6, 3) * 31 % 17) / 17
        >>> metric = LipVertexError(mouth_map=[1, 2, 3])
        >>> metric.update(vertices_pred, vertices_gt)
        >>> metric.compute()
        Array(0.9050102, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, mouth_map: Sequence[int], validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(mouth_map, (list, tuple)) or len(mouth_map) == 0:
            raise ValueError(f"Expected argument `mouth_map` to be a non-empty list but got {mouth_map}")
        self.mouth_map = list(mouth_map)
        self.validate_args = validate_args
        self.add_state("sum_lve", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), jnp.int32), dist_reduce_fx="sum")

    def _prepare_inputs(self, vertices_pred, vertices_gt):
        value = lip_vertex_error(vertices_pred, vertices_gt, self.mouth_map, self.validate_args)
        return (value,), {}

    def _batch_state(self, value):
        return {"sum_lve": value, "total": jnp.asarray(1, jnp.int32)}

    def _compute(self, state):
        return state["sum_lve"] / state["total"]
