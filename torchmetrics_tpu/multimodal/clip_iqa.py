"""CLIPImageQualityAssessment (reference ``multimodal/clip_iqa.py:57``).

CLIP-IQA scores an image against positive/negative prompt pairs via softmax over the
two prompt similarities. The prompt machinery is implemented; the embedder follows the
same pluggable protocol as CLIPScore (HF local cache or custom object).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..functional.multimodal.clip_score import _resolve_clip
from ..metric import HostMetric

_PROMPTS: Dict[str, Tuple[str, str]] = {
    "quality": ("Good photo.", "Bad photo."),
    "brightness": ("Bright photo.", "Dark photo."),
    "noisiness": ("Clean photo.", "Noisy photo."),
    "colorfullness": ("Colorful photo.", "Dull photo."),
    "sharpness": ("Sharp photo.", "Blurry photo."),
    "contrast": ("High contrast photo.", "Low contrast photo."),
    "complexity": ("Complex photo.", "Simple photo."),
    "natural": ("Natural photo.", "Synthetic photo."),
    "happy": ("Happy photo.", "Sad photo."),
    "scary": ("Scary photo.", "Peaceful photo."),
    "new": ("New photo.", "Old photo."),
    "real": ("Real photo.", "Abstract photo."),
    "beautiful": ("Beautiful photo.", "Ugly photo."),
    "lonely": ("Lonely photo.", "Sociable photo."),
    "relaxing": ("Relaxing photo.", "Stressful photo."),
}


class CLIPImageQualityAssessment(HostMetric):
    """Per-image softmax(pos, neg) prompt-pair probabilities (reference
    ``multimodal/clip_iqa.py:216-221``: ``(N,)`` for one prompt, else
    ``{prompt: (N,)}``). ``prompts`` entries are built-in names or custom
    (positive, negative) tuples."""
    # extractor attribute FeatureShare dedupes (reference declares the same name)
    feature_network: str = "model"

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        model_name_or_path: Union[str, Any] = "clip_iqa",
        data_range: float = 1.0,
        prompts: Tuple[Union[str, Tuple[str, str]], ...] = ("quality",),
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not (isinstance(data_range, (int, float)) and data_range > 0):
            raise ValueError("Argument `data_range` should be a positive number.")
        self.data_range = data_range
        if model_name_or_path == "clip_iqa":
            raise ModuleNotFoundError(
                "The default `clip_iqa` checkpoint requires downloading CLIP-IQA weights, which "
                "an air-gapped environment cannot do. Pass a HF checkpoint present in the local "
                "cache or a custom embedder with get_image_features/get_text_features."
            )
        self.model = _resolve_clip(model_name_or_path)
        self.prompt_names = []
        self.prompt_pairs = []
        num_user_defined = 0
        for p in prompts:
            if isinstance(p, str):
                if p not in _PROMPTS:
                    raise ValueError(f"Unknown prompt {p}. Available: {sorted(_PROMPTS)}")
                self.prompt_names.append(p)
                self.prompt_pairs.append(_PROMPTS[p])
            elif isinstance(p, tuple) and len(p) == 2:
                # reference numbers user prompts among themselves (clip_iqa.py:139)
                self.prompt_names.append(f"user_defined_{num_user_defined}")
                num_user_defined += 1
                self.prompt_pairs.append(p)
            else:
                raise ValueError("Argument `prompts` must contain prompt names or (positive, negative) tuples")
        self._anchors = None
        self.add_state("probs_list", default=[], dist_reduce_fx="cat")

    def _prompt_anchors(self) -> jnp.ndarray:
        if self._anchors is None:
            texts = [t for pair in self.prompt_pairs for t in pair]
            feats = jnp.asarray(self.model.get_text_features(texts))
            feats = feats / jnp.linalg.norm(feats, axis=-1, keepdims=True)
            self._anchors = feats.reshape(len(self.prompt_pairs), 2, -1)
        return self._anchors

    def _per_image_probs(self, images) -> jnp.ndarray:
        """(N, P) prompt probabilities — shared with the functional one-shot form."""
        from ..functional.multimodal.clip_iqa import _prompt_pair_probs

        return _prompt_pair_probs(self.model, self._prompt_anchors(), images, self.data_range)

    def _host_batch_state(self, images):
        return {"probs_list": np.asarray(self._per_image_probs(images))}

    def _compute(self, state):
        # per-image scores, like the reference (multimodal/clip_iqa.py:216-221):
        # (N,) for a single prompt, else {prompt: (N,)}
        probs = state["probs_list"].reshape(-1, len(self.prompt_names))
        if len(self.prompt_names) == 1:
            return jnp.asarray(probs).squeeze()  # 0-d for a single image, like the reference
        return {name: jnp.asarray(probs[:, i]) for i, name in enumerate(self.prompt_names)}

    def __hash__(self) -> int:
        return hash((self.__class__.__name__, id(self)))
