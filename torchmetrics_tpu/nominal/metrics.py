"""Nominal association metric classes (reference ``src/torchmetrics/nominal/*.py``).

All four contingency metrics share one design: a ``(C, C)`` sum-reduced confusion
state (one psum to sync) accumulated by the jitted bincount kernel, with NaN policy
applied host-side in ``_prepare_inputs`` and the scalar statistic computed host-side
from the tiny table.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from ..functional.nominal._association import (
    _cramers_v_compute,
    _nominal_update,
    _pearsons_contingency_coefficient_compute,
    _theils_u_compute,
    _tschuprows_t_compute,
)
from ..functional.nominal.fleiss_kappa import _fleiss_kappa_compute, _fleiss_kappa_update
from ..functional.nominal.utils import _nominal_input_validation
from ..metric import Metric


class _ContingencyMetric(Metric):
    """Shared shell for the confusion-state nominal metrics."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    _jittable_compute = False

    def __init__(
        self,
        num_classes: int,
        nan_strategy: str = "replace",
        nan_replace_value: Optional[float] = 0.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(num_classes, int) or num_classes < 1:
            raise ValueError(f"Expected argument `num_classes` to be a positive integer, but got {num_classes}")
        _nominal_input_validation(nan_strategy, nan_replace_value)
        self.num_classes = num_classes
        self.nan_strategy = nan_strategy
        self.nan_replace_value = nan_replace_value
        self.add_state("confmat", jnp.zeros((num_classes, num_classes), jnp.float32), dist_reduce_fx="sum")

    def _prepare_inputs(self, preds, target):
        # NaN policy + argmax collapse run host-side ('drop' is dynamic-shape)
        confmat = _nominal_update(preds, target, self.num_classes, self.nan_strategy, self.nan_replace_value)
        return (confmat,), {}

    def _batch_state(self, confmat):
        return {"confmat": confmat}


class CramersV(_ContingencyMetric):
    """Cramer's V association statistic (reference ``nominal/cramers.py:31``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.nominal import CramersV
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0, 1, 2, 1, 0])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0, 2, 2, 1, 0])
        >>> metric = CramersV(num_classes=3)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.6846532, dtype=float32)
    """

    def __init__(
        self,
        num_classes: int,
        bias_correction: bool = True,
        nan_strategy: str = "replace",
        nan_replace_value: Optional[float] = 0.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes, nan_strategy, nan_replace_value, **kwargs)
        self.bias_correction = bias_correction

    def _compute(self, state):
        return _cramers_v_compute(state["confmat"], self.bias_correction)


class PearsonsContingencyCoefficient(_ContingencyMetric):
    """Pearson's contingency coefficient (reference ``nominal/pearson.py:34``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.nominal import PearsonsContingencyCoefficient
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0, 1, 2, 1, 0])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0, 2, 2, 1, 0])
        >>> metric = PearsonsContingencyCoefficient(num_classes=3)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.73480344, dtype=float32)
    """

    def _compute(self, state):
        return _pearsons_contingency_coefficient_compute(state["confmat"])


class TheilsU(_ContingencyMetric):
    """Theil's U uncertainty coefficient (reference ``nominal/theils_u.py:31``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.nominal import TheilsU
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0, 1, 2, 1, 0])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0, 2, 2, 1, 0])
        >>> metric = TheilsU(num_classes=3)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.61806566, dtype=float32)
    """

    def _compute(self, state):
        return _theils_u_compute(state["confmat"])


class TschuprowsT(_ContingencyMetric):
    """Tschuprow's T association statistic (reference ``nominal/tschuprows.py:31``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.nominal import TschuprowsT
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0, 1, 2, 1, 0])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0, 2, 2, 1, 0])
        >>> metric = TschuprowsT(num_classes=3)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.6846532, dtype=float32)
    """

    def __init__(
        self,
        num_classes: int,
        bias_correction: bool = True,
        nan_strategy: str = "replace",
        nan_replace_value: Optional[float] = 0.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes, nan_strategy, nan_replace_value, **kwargs)
        self.bias_correction = bias_correction

    def _compute(self, state):
        return _tschuprows_t_compute(state["confmat"], self.bias_correction)


class FleissKappa(Metric):
    """Fleiss' kappa inter-rater agreement (reference ``nominal/fleiss_kappa.py:30``).

    The per-sample counts table is a cat state — kappa is not decomposable into
    fixed-size sufficient statistics because the rater normalization depends on the
    global max rater count.


    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.nominal import FleissKappa
        >>> ratings = jnp.asarray([[0, 4, 1], [2, 2, 1], [4, 0, 1], [1, 3, 1]])
        >>> metric = FleissKappa(mode='counts')
        >>> metric.update(ratings)
        >>> metric.compute()
        Array(0.09448675, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, mode: str = "counts", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if mode not in ["counts", "probs"]:
            raise ValueError("Argument ``mode`` must be one of ['counts', 'probs'].")
        self.mode = mode
        self.add_state("counts", default=[], dist_reduce_fx="cat")

    def _batch_state(self, ratings):
        return {"counts": _fleiss_kappa_update(ratings, self.mode)}

    def _compute(self, state):
        return _fleiss_kappa_compute(jnp.asarray(state["counts"]))
