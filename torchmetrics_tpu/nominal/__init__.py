"""Nominal tower — stateful metric classes (reference ``src/torchmetrics/nominal/``)."""

from .metrics import CramersV, FleissKappa, PearsonsContingencyCoefficient, TheilsU, TschuprowsT

__all__ = ["CramersV", "FleissKappa", "PearsonsContingencyCoefficient", "TheilsU", "TschuprowsT"]
