"""Transient-failure classification and bounded retry.

Motivation (round 5 postmortem): the flagship FID bench config died on a transient
remote-compile infra error (``JaxRuntimeError: INTERNAL: ... response body closed
before all bytes were read``) and nothing retried it, so the adopted headline number
exists in docs but in no driver-captured BENCH json. A production eval stack on
preemptible TPU pods must survive exactly this class of fault — *without* ever
retrying deterministic user errors (bad shapes, bad dtypes, API misuse), which would
just re-raise the same exception N times slower, and without retrying state
corruption, which would launder garbage into a "successful" eval.

Two pieces:

- an exception **classifier** (:func:`classify_exception`): transient infrastructure
  faults (RPC/compile-service/transport errors, host dropout) vs deterministic errors.
  Unknown exceptions classify deterministic — never retry what you can't name.
- a :class:`RetryPolicy`: bounded attempts, exponential backoff with **deterministic**
  jitter (no wall-clock or RNG dependence — the same failure sequence produces the
  same schedule on every host, keeping multi-controller ranks in lockstep when they
  share a policy).

Both are wired behind the opt-in :class:`ReliabilityConfig` (``Metric(...,
reliability=...)``) so the default hot path is byte-for-byte today's behavior.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, List, Optional, Tuple

from ..utilities.exceptions import (
    StateCorruptionError,
    TorchMetricsUserError,
    TransientRuntimeError,
)

TRANSIENT = "transient"
DETERMINISTIC = "deterministic"

# Status prefixes / message fragments that mark an infrastructure fault. The list is
# grounded in real failures: the round-5 bench crash ("INTERNAL: ... response body
# closed before all bytes were read"), gRPC status codes the TPU compile/dispatch
# services surface through JaxRuntimeError, and plain socket-level transport errors.
_TRANSIENT_MESSAGE_MARKERS: Tuple[str, ...] = (
    "internal:",
    "unavailable:",
    "deadline_exceeded",
    "deadline exceeded",
    "aborted:",
    "cancelled:",
    "response body closed",
    "connection reset",
    "connection refused",
    "connection closed",
    "broken pipe",
    "socket closed",
    "transport closed",
    "stream terminated",
    "stream removed",
    "rst_stream",
    "failed to connect",
    "temporarily unavailable",
    "preempted",
    "host dropped",
    "participant dropped",
    "heartbeat timeout",
    "coordination service",
)

# Status prefixes that mark a *deterministic* runtime error even though they arrive
# wrapped in the same JaxRuntimeError type as the transient ones. These win over any
# transient marker appearing later in the message.
_DETERMINISTIC_MESSAGE_MARKERS: Tuple[str, ...] = (
    "invalid_argument",
    "invalid argument:",
    "not_found",
    "unimplemented",
    "failed_precondition",
    "out_of_range",
    "permission_denied",
    "unauthenticated",
    # on TPU/XLA, RESOURCE_EXHAUSTED is the out-of-memory status: deterministic
    # for a fixed workload — retrying an OOM just re-OOMs, slower
    "resource_exhausted",
)

# Exception types that are transient by construction (transport-level).
_TRANSIENT_TYPES: Tuple[type, ...] = (
    TransientRuntimeError,
    ConnectionError,  # covers ConnectionResetError/RefusedError/Aborted, BrokenPipeError
    TimeoutError,
)

# Exception types that are deterministic by construction: user/API errors and state
# corruption. Checked BEFORE any message heuristics.
_DETERMINISTIC_TYPES: Tuple[type, ...] = (
    TorchMetricsUserError,
    StateCorruptionError,
    ValueError,
    TypeError,
    KeyError,
    IndexError,
    AttributeError,
    AssertionError,
    NotImplementedError,
    ZeroDivisionError,
)


def is_transient_error_text(text: str) -> bool:
    """Classify an error *message* (e.g. a crashed bench subprocess's stderr tail)."""
    low = text.lower()
    if any(marker in low for marker in _DETERMINISTIC_MESSAGE_MARKERS):
        return False
    return any(marker in low for marker in _TRANSIENT_MESSAGE_MARKERS)


def classify_exception(exc: BaseException) -> str:
    """``"transient"`` (safe to retry with the same inputs) or ``"deterministic"``.

    Order matters: typed user/corruption errors are deterministic even if their
    message happens to contain a transient-looking fragment; typed transport errors
    are transient regardless of message; everything else (``JaxRuntimeError`` /
    ``XlaRuntimeError`` arrive as plain ``RuntimeError`` subclasses with a gRPC
    status prefix) is classified by message. Unknown exceptions are deterministic —
    never retry what you can't name.
    """
    if isinstance(exc, _DETERMINISTIC_TYPES):
        return DETERMINISTIC
    if isinstance(exc, _TRANSIENT_TYPES):
        return TRANSIENT
    if isinstance(exc, (RuntimeError, OSError)):
        return TRANSIENT if is_transient_error_text(str(exc)) else DETERMINISTIC
    return DETERMINISTIC


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with exponential backoff and deterministic jitter.

    Args:
        max_attempts: total attempts including the first (``3`` = 2 retries).
        backoff_base: delay before the first retry, seconds.
        backoff_factor: multiplier per subsequent retry.
        max_backoff: cap on any single delay, seconds.
        jitter: fraction of the delay perturbed deterministically per attempt
            (golden-ratio hash of the attempt number — reproducible everywhere,
            no RNG, no wall-clock). NOTE: this de-rounds the schedule away from
            exact power-of-two boundaries; it does NOT spread simultaneous
            retriers — every rank computes the identical delay for attempt N,
            which is exactly the lockstep the multi-controller sync path needs.
        classify: exception classifier; only ``"transient"`` outcomes retry.
        sleep_fn: injection seam for tests (defaults to ``time.sleep``).
    """

    max_attempts: int = 3
    backoff_base: float = 0.05
    backoff_factor: float = 2.0
    max_backoff: float = 2.0
    jitter: float = 0.1
    classify: Callable[[BaseException], str] = classify_exception
    sleep_fn: Callable[[float], None] = time.sleep

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.backoff_base < 0 or self.max_backoff < 0:
            raise ValueError("backoff delays must be non-negative")
        if not 0 <= self.jitter <= 1:
            raise ValueError(f"jitter must be in [0, 1], got {self.jitter}")

    def delay_for(self, attempt: int) -> float:
        """Delay after failed attempt ``attempt`` (1-based), jitter included."""
        raw = min(self.backoff_base * self.backoff_factor ** (attempt - 1), self.max_backoff)
        if self.jitter == 0:
            return raw
        # deterministic jitter in [-jitter, +jitter): Weyl sequence on the attempt
        # number — de-rounds the schedule off exact backoff boundaries while every
        # rank still computes the same delay (lockstep retries, no RNG/host state)
        frac = (attempt * 0.6180339887498949) % 1.0
        return raw * (1.0 + self.jitter * (2.0 * frac - 1.0))

    def schedule(self) -> List[float]:
        """The full backoff schedule (one delay per possible retry) — for tests/docs."""
        return [self.delay_for(a) for a in range(1, self.max_attempts)]

    def call(
        self,
        thunk: Callable[[], Any],
        on_retry: Optional[Callable[[BaseException, int], None]] = None,
        describe: str = "",
    ) -> Any:
        """Run ``thunk``, retrying transient failures per the policy.

        ``on_retry(exc, attempt)`` runs after a transient failure is accepted for
        retry and after the backoff sleep — the seam where callers restore
        donated/consumed buffers before the next attempt. Deterministic failures
        and exhausted budgets re-raise the original exception unchanged.
        """
        last_outcome = _RetryOutcome()
        return self._call(thunk, on_retry, describe, last_outcome)

    def call_with_outcome(
        self,
        thunk: Callable[[], Any],
        on_retry: Optional[Callable[[BaseException, int], None]] = None,
        describe: str = "",
    ) -> Tuple[Any, "_RetryOutcome"]:
        """Like :meth:`call` but also returns attempt accounting (bench driver)."""
        outcome = _RetryOutcome()
        return self._call(thunk, on_retry, describe, outcome), outcome

    @staticmethod
    def _warn_nonfatal(message: str) -> None:
        """Warn without letting a warnings-as-errors filter (``python -W error``,
        pytest ``filterwarnings = error``) convert the advisory into an exception
        inside the retry loop's except handler — that would mask the original
        transient failure and abort every retry, defeating the feature the
        warning merely narrates."""
        from ..utilities.prints import rank_zero_warn

        try:
            rank_zero_warn(message, UserWarning)
        except Exception:  # noqa: BLE001 — the warning must never outrank the retry
            pass

    def _call(self, thunk, on_retry, describe, outcome: "_RetryOutcome") -> Any:
        from ..observability import active as _telemetry_active

        while True:
            outcome.attempts += 1
            try:
                return thunk()
            except Exception as exc:  # noqa: BLE001 — classifier decides
                transient = self.classify(exc) == TRANSIENT
                if not transient or outcome.attempts >= self.max_attempts:
                    if transient:
                        # exhausted budget on a transient fault: the moment the
                        # failure becomes final must not pass silently — warn and
                        # record before the original exception re-raises
                        self._warn_nonfatal(
                            f"Retry budget exhausted for {describe or 'metric dispatch'} "
                            f"after {outcome.attempts} attempts; giving up on transient "
                            f"failure: {exc!r}"
                        )
                        rec = _telemetry_active()
                        if rec is not None:
                            rec.record_retry_exhausted(
                                describe or "metric dispatch", outcome.attempts, exc
                            )
                    raise
                outcome.recovered_from.append(f"{type(exc).__name__}: {exc}"[:240])
                delay = self.delay_for(outcome.attempts)
                self._warn_nonfatal(
                    f"Transient failure in {describe or 'metric dispatch'} "
                    f"(attempt {outcome.attempts}/{self.max_attempts}): {exc!r}. "
                    f"Retrying in {delay:.3f}s."
                )
                rec = _telemetry_active()
                if rec is not None:
                    # the accepted backoff delay feeds the retry_backoff
                    # histogram — wall-clock a fleet spends waiting out
                    # transient faults, not just how often it retried
                    rec.record_retry(
                        describe or "metric dispatch", outcome.attempts, exc, delay_s=delay
                    )
                if delay > 0:
                    self.sleep_fn(delay)
                if on_retry is not None:
                    on_retry(exc, outcome.attempts)


@dataclasses.dataclass
class _RetryOutcome:
    """Attempt accounting for one retried call."""

    attempts: int = 0
    recovered_from: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass(frozen=True)
class ReliabilityConfig:
    """Opt-in reliability knobs for a :class:`~torchmetrics_tpu.Metric`.

    Passed as ``Metric(..., reliability=ReliabilityConfig(...))``. ``None`` (the
    default everywhere) keeps today's zero-overhead behavior exactly.

    Args:
        retry: policy applied at the jit-dispatch boundaries of ``update`` /
            ``forward`` / ``compute`` and around ``process_sync``. ``None``
            disables retry (guards can still be active).
        validate_on_sync: run :func:`~torchmetrics_tpu.reliability.validate_state`
            on the synced state before it replaces the local one.
        validate_on_merge: validate an incoming state before ``merge_state`` folds
            it in (a corrupt shard must not poison the accumulator).
        validate_on_restore: validate finiteness of leaves restored by
            ``load_state_dict`` (structural shape/key checks always run there).
        check_finite: include NaN/Inf scans in the validations above — scoped to
            AGGREGATE (``sum``/``mean``/``min``/``max``) leaves, where non-finite
            values are always corruption; raw-data leaves (``cat`` lists,
            ``None``-tagged gathers) may carry NaN by construction and are never
            scanned at sync/merge. Costs one device→host readback per scanned
            leaf — fine at sync/checkpoint boundaries, which is why guards do
            not run per-update.
    """

    retry: Optional[RetryPolicy] = None
    validate_on_sync: bool = True
    validate_on_merge: bool = True
    validate_on_restore: bool = True
    check_finite: bool = True
