"""Reliability layer: transient-failure retry, fault injection, state-integrity guards.

Grown out of the round-5 postmortem (the flagship FID bench config crashed on a
transient remote-compile infra error and nothing retried it): a production eval
stack on preemptible TPU pods must classify failures, retry the transient ones,
guard state integrity at trust boundaries, and degrade gracefully instead of
letting one bad metric kill the whole eval loop. See ``docs/reliability.md``.

Everything here is opt-in: without a :class:`ReliabilityConfig` the metric runtime
is byte-for-byte unchanged.
"""

from .faults import (
    ROUND5_CRASH_MESSAGE,
    DeadRank,
    DispatchFaultHook,
    FlakyGather,
    inject_dispatch_fault,
    make_transient_error,
    poison_state_leaf,
    truncate_state_dict,
)
from .guards import validate_restored, validate_state
from .retry import (
    DETERMINISTIC,
    TRANSIENT,
    ReliabilityConfig,
    RetryPolicy,
    classify_exception,
    is_transient_error_text,
)

__all__ = [
    "DETERMINISTIC",
    "TRANSIENT",
    "ROUND5_CRASH_MESSAGE",
    "DeadRank",
    "DispatchFaultHook",
    "FlakyGather",
    "ReliabilityConfig",
    "RetryPolicy",
    "classify_exception",
    "inject_dispatch_fault",
    "is_transient_error_text",
    "make_transient_error",
    "poison_state_leaf",
    "truncate_state_dict",
    "validate_restored",
    "validate_state",
]
