"""Deterministic fault injection — every recovery path testable on CPU.

The real failure modes this harness reproduces:

- a transient remote-compile/dispatch error on the Nth jitted call (the round-5
  bench crash: ``JaxRuntimeError: INTERNAL: ... response body closed before all
  bytes were read``) → :func:`inject_dispatch_fault`;
- NaN/Inf corruption of a named state leaf (bad collective, HBM bitflip, buggy
  custom merge) → :func:`poison_state_leaf`;
- a participant dropping out of ``gather_all_arrays`` mid-sync (host preemption)
  → :class:`FlakyGather`;
- a truncated / partially-written checkpoint → :func:`truncate_state_dict`.

Everything is deterministic (counters, not clocks or RNG) so recovery tests are
exact: a retried run must be *bitwise identical* to an uninterrupted one.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional

import jax.numpy as jnp

from ..utilities.exceptions import TransientRuntimeError

# the round-5 crash message, verbatim shape — classifier fixtures and docs use it
ROUND5_CRASH_MESSAGE = (
    "INTERNAL: stream terminated by RST_STREAM: response body closed before all bytes were read"
)


def make_transient_error(message: str = ROUND5_CRASH_MESSAGE) -> TransientRuntimeError:
    """A synthetic transient infra error with a realistic status-prefixed message."""
    return TransientRuntimeError(message)


class DispatchFaultHook:
    """Callable installed as ``metric._fault_hook``: raises on configured dispatches.

    Counts every dispatch attempt of the matching ``tag`` (``"update"``,
    ``"forward"``, ``"compute"``, ``"sync"``; ``None`` matches all) and raises
    ``exc_factory()`` for attempts ``fail_on .. fail_on+times-1`` (1-based). With a
    retry policy active the failed attempt is re-dispatched, which increments the
    counter again — so ``times=1`` means "fail once, recover on the next attempt".
    """

    def __init__(
        self,
        fail_on: int = 1,
        times: int = 1,
        tag: Optional[str] = None,
        exc_factory: Callable[[], BaseException] = make_transient_error,
    ) -> None:
        self.fail_on = fail_on
        self.times = times
        self.tag = tag
        self.exc_factory = exc_factory
        self.calls = 0
        self.raised = 0

    def __call__(self, tag: str) -> None:
        if self.tag is not None and tag != self.tag:
            return
        self.calls += 1
        if self.fail_on <= self.calls < self.fail_on + self.times:
            self.raised += 1
            raise self.exc_factory()


@contextlib.contextmanager
def inject_dispatch_fault(
    metric: Any,
    fail_on: int = 1,
    times: int = 1,
    tag: Optional[str] = None,
    exc_factory: Callable[[], BaseException] = make_transient_error,
) -> Iterator[DispatchFaultHook]:
    """Inject a fault into a metric's dispatch seam for the duration of the block.

    The hook fires inside the metric's per-attempt dispatch path (before the XLA
    call), so a retrying metric sees the error exactly where a remote-compile
    failure would surface, with its state buffers still intact.
    """
    hook = DispatchFaultHook(fail_on=fail_on, times=times, tag=tag, exc_factory=exc_factory)
    prev = getattr(metric, "_fault_hook", None)
    metric._fault_hook = hook
    try:
        yield hook
    finally:
        metric._fault_hook = prev


def poison_state_leaf(metric: Any, name: str, kind: str = "nan") -> None:
    """Overwrite a named state leaf with NaN or Inf (in place, deterministic).

    Tensor leaves are replaced wholesale; list (concat) leaves get every element
    poisoned. ``kind`` is ``"nan"`` or ``"inf"``.
    """
    if name not in metric._state:
        raise KeyError(f"{type(metric).__name__} has no state {name!r}")
    fill = jnp.nan if kind == "nan" else jnp.inf
    current = metric._state[name]

    def _poison(x):
        x = jnp.asarray(x)
        if not jnp.issubdtype(x.dtype, jnp.floating):
            x = x.astype(jnp.float32)  # corruption does not respect dtypes either
        return jnp.full_like(x, fill)

    metric._state[name] = [_poison(x) for x in current] if isinstance(current, list) else _poison(current)
    metric._computed = None


class FlakyGather:
    """A ``dist_sync_fn`` wrapper simulating a participant dropping out of the
    gather: the configured calls raise *before* any collective is entered (every
    rank shares the same deterministic counter, so in a real cluster all ranks fail
    and retry in lockstep — no desynchronized collectives).

    Wraps the production :func:`~torchmetrics_tpu.parallel.sync.gather_all_arrays`
    by default; pass ``inner`` to wrap a test-world fake gather instead.
    """

    def __init__(
        self,
        inner: Optional[Callable] = None,
        fail_times: int = 1,
        exc_factory: Callable[[], BaseException] = lambda: TransientRuntimeError(
            "UNAVAILABLE: participant dropped during gather_all_arrays"
        ),
    ) -> None:
        if inner is None:
            from ..parallel.sync import gather_all_arrays as inner  # late: avoids cycle
        self.inner = inner
        self.fail_times = fail_times
        self.exc_factory = exc_factory
        self.calls = 0
        self.failures = 0

    def __call__(self, value, group=None):
        self.calls += 1
        if self.failures < self.fail_times:
            self.failures += 1
            raise self.exc_factory()
        return self.inner(value, group)


class DeadRank:
    """A ``dist_sync_fn`` wrapper simulating a rank DYING mid-collective in a
    ``world``-rank fleet — the failure the degraded-sync plane
    (``parallel/coalesce.py`` v8) exists to survive.

    Every gathered result is widened to ``world`` rows by mirroring the local
    row for the simulated peers (the world-of-one test-fleet trick); while
    rank ``rank`` is dead its row in EVERY collective result is zeroed —
    exactly the all-zero metadata tombstone and zero bucket payload a real
    lost participant leaves behind. The coalesced plane must complete the
    sync over the survivor quorum and mark it degraded. :meth:`revive` brings
    the rank back: its rows mirror the live ones again, so the next coalesced
    sync observes the rejoin and reconciles its contribution.

    Deterministic (counters, not clocks): ``calls`` counts collectives
    served, ``zeroed`` the rows tombstoned while dead.
    """

    def __init__(self, inner: Optional[Callable] = None, world: int = 2, rank: int = 1) -> None:
        if inner is None:
            from ..parallel.sync import gather_all_arrays as inner  # late: avoids cycle
        if world < 2:
            raise ValueError(f"DeadRank needs a world of at least 2, got {world}")
        if not 0 <= rank < world:
            raise ValueError(f"rank must be in [0, {world}), got {rank}")
        self.inner = inner
        self.world = world
        self.rank = rank
        self.dead = True
        self.calls = 0
        self.zeroed = 0

    def revive(self) -> None:
        """Bring the dead rank back — its next rows are live mirrors, which a
        coalesced sync sees as the rejoin."""
        self.dead = False

    def kill(self) -> None:
        self.dead = True

    def __call__(self, value, group=None):
        self.calls += 1
        rows = [jnp.asarray(r) for r in self.inner(value, group)]
        while len(rows) < self.world:  # mirror the local row for simulated peers
            rows.append(jnp.asarray(rows[0]))
        if self.dead:
            rows[self.rank] = jnp.zeros_like(rows[self.rank])
            self.zeroed += 1
        return rows


def truncate_state_dict(
    state_dict: Dict[str, Any],
    drop_keys: Optional[Iterable[str]] = None,
    slice_keys: Optional[Iterable[str]] = None,
) -> Dict[str, Any]:
    """A damaged copy of a checkpoint dict: ``drop_keys`` removed entirely
    (lost keys), ``slice_keys``' arrays cut to half length along axis 0 when
    possible (partially-written buffers). Original dict is untouched.
    """
    import numpy as np

    out = dict(state_dict)
    for key in drop_keys or ():
        out.pop(key, None)
    for key in slice_keys or ():
        if key in out:
            arr = np.asarray(out[key])
            if arr.ndim > 0 and arr.shape[0] > 1:
                out[key] = arr[: arr.shape[0] // 2]
            else:
                out[key] = arr.reshape(arr.shape + (1,))  # rank damage for scalars
    return out
