"""State-integrity guards.

``validate_state`` checks a metric's state dict against the invariants its
``init_state()`` spec implies — every registered leaf present, tensor leaves with
shape-preserving reduction tags matching the default's shape/dtype, floating leaves
finite — and raises :class:`~torchmetrics_tpu.utilities.exceptions.StateCorruptionError`
naming the offending leaf.

Guards run at the *boundaries* where corrupt state crosses trust domains — sync
(another host's contribution), merge (another shard's accumulator), checkpoint
restore (bytes from disk) — never per-update: the finiteness scan needs a
device→host readback, which per-update would flip tunneled TPU runtimes into
synchronous dispatch (metric.py's standing constraint).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _observability
from ..utilities.exceptions import StateCorruptionError


def _mark_finite_scan() -> None:
    """Each finiteness scan reads ONE bool back from device — the readback the
    guards' boundary-only placement exists to amortize. Counted so a telemetry
    trace shows exactly where the D2H budget goes."""
    rec = _observability._ACTIVE
    if rec is not None:
        rec.record_d2h("finiteness_guard", 1)

# reduction tags under which a tensor leaf keeps its default shape forever
_SHAPE_PRESERVING = ("sum", "mean", "min", "max")


def _spec_leaf(default: Any):
    """Shape/dtype the live leaf must carry, derived the same way the live state is
    born (``_fresh_leaf``): through ``jnp.asarray``, so x64-truncation matches."""
    return jnp.asarray(default)


def _check_tensor_leaf(
    name: str, value: Any, default: Any, fx: Any, context: str, check_finite: bool
) -> None:
    if isinstance(value, list):
        raise StateCorruptionError(
            f"{context}: state '{name}' is a list but its spec is a tensor state."
        )
    if not hasattr(value, "shape") and not np.isscalar(value):
        raise StateCorruptionError(
            f"{context}: state '{name}' is {type(value).__name__}, expected an array."
        )
    value = jnp.asarray(value)
    if isinstance(fx, str) and fx in _SHAPE_PRESERVING:
        spec = _spec_leaf(default)
        if tuple(value.shape) != tuple(spec.shape):
            raise StateCorruptionError(
                f"{context}: state '{name}' has shape {tuple(value.shape)}, "
                f"spec requires {tuple(spec.shape)} (reduction '{fx}' preserves shape)."
            )
        if value.dtype != spec.dtype:
            raise StateCorruptionError(
                f"{context}: state '{name}' has dtype {value.dtype}, spec requires {spec.dtype}."
            )
        # finiteness is an invariant only for AGGREGATE leaves: a NaN in a
        # sum/mean/min/max accumulator is always corruption, while raw-data
        # leaves (cat lists, None-tagged gathers) may carry NaN by construction
        # (e.g. masked user preds) — scanning those would reject healthy state
        if check_finite and jnp.issubdtype(value.dtype, jnp.floating):
            _mark_finite_scan()
            if not bool(jnp.isfinite(value).all()):
                raise StateCorruptionError(
                    f"{context}: state '{name}' contains non-finite values (NaN/Inf)."
                )


def validate_state(
    metric: Any,
    state: Optional[Dict[str, Any]] = None,
    context: str = "validate_state",
    check_finite: bool = True,
) -> None:
    """Validate ``state`` (default: the metric's live state) against the metric's
    ``init_state()`` spec. Raises :class:`StateCorruptionError` naming the first
    violated leaf; returns ``None`` on a clean state.

    Sync can legitimately reshape ``None``-tagged leaves (world-stacked gather) and
    grow ``cat`` leaves, so shape/dtype is enforced only for the shape-preserving
    reduction tags; presence is enforced for every leaf; finiteness only for
    aggregate (shape-preserving) leaves — raw-data leaves may carry NaN by
    construction.
    """
    state = metric._state if state is None else state
    for name, default in metric._defaults.items():
        if name not in state:
            raise StateCorruptionError(
                f"{context}: state '{name}' of {type(metric).__name__} is missing "
                f"(truncated or partially-written state)."
            )
        value = state[name]
        fx = metric._reductions.get(name)
        if isinstance(default, list):
            # list (cat) leaves hold RAW user data — NaN can be legitimate there
            # (masked preds), so only presence/type are enforced, never finiteness
            elems = value if isinstance(value, list) else [value]
            for i, elem in enumerate(elems):
                if not hasattr(elem, "shape") and not np.isscalar(elem):
                    raise StateCorruptionError(
                        f"{context}: state '{name}[{i}]' is {type(elem).__name__}, expected an array."
                    )
        else:
            _check_tensor_leaf(name, value, default, fx, context, check_finite)


def validate_restored(
    metric: Any,
    state_dict: Mapping[str, Any],
    prefix: str = "",
    check_finite: bool = False,
) -> None:
    """Structural validation of a checkpoint slice BEFORE it is adopted.

    A truncated/partial checkpoint must raise instead of silently loading garbage:
    when the checkpoint's ``_update_count`` metadata proves this metric *was* saved,
    every registered state must either be wholly present or wholly absent — some
    present and some missing means the file lost keys. Present tensor leaves with
    shape-preserving tags must match the spec's shape (a sliced/partially-written
    array is corruption, not a resume).

    ``check_finite=False`` by default: a legitimately saved state may carry NaN by
    construction (e.g. raw user preds in a cat state); opt in via
    ``ReliabilityConfig(validate_on_restore=True)``.
    """
    meta_key = prefix + "_update_count"
    manifest_key = prefix + "_saved_states"
    names = list(metric._defaults)
    present = [n for n in names if prefix + n in state_dict]
    if manifest_key in state_dict:
        # the save recorded how many state leaves it wrote: fewer surviving means
        # the file lost keys, while a partial-but-complete save (mixed persistent/
        # non-persistent states) validates cleanly
        expected = int(state_dict[manifest_key])
        if len(present) < expected:
            raise StateCorruptionError(
                f"Checkpoint slice '{prefix}*' for {type(metric).__name__} is truncated: "
                f"{expected} state(s) were saved but only {len(present)} "
                f"({sorted(present)}) survived. Pass validate=False to force a partial load."
            )
    elif present and meta_key in state_dict:
        # pre-manifest checkpoint: all-or-nothing heuristic (can false-positive on
        # metrics mixing persistent and non-persistent states — re-save to fix)
        missing = [n for n in names if prefix + n not in state_dict]
        if missing:
            raise StateCorruptionError(
                f"Checkpoint slice '{prefix}*' for {type(metric).__name__} is truncated: "
                f"has {sorted(present)} but is missing {sorted(missing)} "
                f"(its '_update_count' metadata proves the metric was saved whole). "
                f"Pass validate=False to force a partial load."
            )
    if not present:
        return  # metric absent from this checkpoint — load_state_dict no-ops
    for name in present:
        default = metric._defaults[name]
        value = state_dict[prefix + name]
        fx = metric._reductions.get(name)
        if isinstance(default, list):
            if not isinstance(value, (list, tuple)):
                raise StateCorruptionError(
                    f"Checkpoint state '{prefix}{name}' should be a list of arrays, "
                    f"got {type(value).__name__}."
                )
            if check_finite:
                for i, elem in enumerate(value):
                    arr = jnp.asarray(elem)
                    if jnp.issubdtype(arr.dtype, jnp.floating):
                        _mark_finite_scan()
                        if not bool(jnp.isfinite(arr).all()):
                            raise StateCorruptionError(
                                f"Checkpoint state '{prefix}{name}[{i}]' contains non-finite values."
                            )
        else:
            _check_tensor_leaf(
                name, value, default, fx, f"checkpoint restore ('{prefix}{name}')", check_finite
            )
