"""VideoMultiMethodAssessmentFusion (reference ``video/vmaf.py:27``).

VMAF fuses elementary video-quality features through a pretrained SVM; the reference
delegates wholesale to the optional ``vmaf_torch`` wheel (its own gate raises without
it, ``video/vmaf.py``). The wheel and its model files are not available in this
environment, so the class gates with the same contract.
"""

from __future__ import annotations

from typing import Any

from ..metric import HostMetric
from ..utilities.imports import _module_available

_VMAF_TORCH_AVAILABLE = _module_available("vmaf_torch")


class VideoMultiMethodAssessmentFusion(HostMetric):
    """VMAF over video pairs (gated on the optional ``vmaf_torch`` wheel, exactly as
    the reference is)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 100.0

    def __init__(self, elementary_features: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not _VMAF_TORCH_AVAILABLE:
            raise ModuleNotFoundError(
                "vmaf metric requires that vmaf-torch is installed."
                " Install with `pip install vmaf-torch` (not available on PyPI for all platforms)."
            )
        raise NotImplementedError(
            "vmaf-torch is importable but the TPU-native VMAF pipeline has not been ported; "
            "the fusion SVM model files also require a download."
        )  # pragma: no cover - unreachable without the wheel
