"""VideoMultiMethodAssessmentFusion (reference ``video/vmaf.py:27``).

The reference delegates wholesale to the optional ``vmaf_torch`` wheel. Here the
elementary features (motion2, 4-scale VIF, DLM/ADM) are in-tree jnp conv
pipelines (``functional/video/vmaf.py``) and the class computes on either of two
paths: the ``vmaf_torch`` host callback when that wheel is present (reference
parity), or the in-tree features + NuSVR fusion when a libvmaf model JSON is
supplied via ``model_path``. Only when neither path exists does construction
raise — the trained SVM weights are an artifact that cannot be derived offline.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Union

import jax.numpy as jnp
import numpy as np

from ..functional.video.vmaf import (
    _VMAF_FEATURE_ORDER,
    _VMAF_TORCH_AVAILABLE,
    video_multi_method_assessment_fusion,
)
from ..metric import HostMetric


class VideoMultiMethodAssessmentFusion(HostMetric):
    """VMAF over ``(batch, 3, frames, H, W)`` RGB videos in [0, 1].

    Args:
        features: return the elementary-feature dict alongside the score
            (reference ``video/vmaf.py:129``).
        model_path: path to a libvmaf model JSON (e.g. ``vmaf_v0.6.1.json``) for
            the in-tree fusion path when ``vmaf_torch`` is absent. In-tree
            features are float pipelines — scores track, but do not bit-match,
            libvmaf's fixed-point integer feature variants.
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 100.0

    def __init__(
        self, features: bool = False, model_path: Optional[str] = None, **kwargs: Any
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(features, bool):
            raise ValueError(f"Argument `features` should be a boolean, but got {features}.")
        if not _VMAF_TORCH_AVAILABLE and model_path is None:
            raise ModuleNotFoundError(
                "vmaf metric requires either the vmaf-torch wheel (`pip install "
                "torchmetrics[video]`) or a libvmaf model JSON via `model_path=`."
            )
        self.features = features
        self.model_path = model_path
        self.add_state("vmaf_score", default=[], dist_reduce_fx="cat")
        if features:
            for key in _VMAF_FEATURE_ORDER:
                self.add_state(key, default=[], dist_reduce_fx="cat")

    def _host_batch_state(self, preds, target) -> Dict[str, np.ndarray]:
        out = video_multi_method_assessment_fusion(
            jnp.asarray(preds), jnp.asarray(target), features=self.features, model_path=self.model_path
        )
        if self.features:
            state = {"vmaf_score": np.asarray(out["vmaf"]).reshape(-1)}
            for key in _VMAF_FEATURE_ORDER:
                state[key] = np.asarray(out[key]).reshape(-1)
            return state
        return {"vmaf_score": np.asarray(out).reshape(-1)}

    def _compute(self, state) -> Union[jnp.ndarray, Dict[str, jnp.ndarray]]:
        if self.features:
            return {
                "vmaf": jnp.asarray(np.asarray(state["vmaf_score"])),
                **{k: jnp.asarray(np.asarray(state[k])) for k in _VMAF_FEATURE_ORDER},
            }
        return jnp.asarray(np.asarray(state["vmaf_score"]))
