"""Video tower — stateful metric classes (reference ``src/torchmetrics/video/``)."""

from .vmaf import VideoMultiMethodAssessmentFusion

__all__ = ["VideoMultiMethodAssessmentFusion"]
