"""Input-transforming wrappers (reference wrappers/transformations.py:23,84,137)."""

from __future__ import annotations

from typing import Any, Callable, Optional, Union

import jax.numpy as jnp

from ..collections import MetricCollection
from ..metric import Metric
from .abstract import WrapperMetric


class MetricInputTransformer(WrapperMetric):
    """Base class: preprocess (preds, target) before delegating to the wrapped metric.

    Subclasses override ``transform_pred`` and/or ``transform_target``.
    """

    def __init__(self, wrapped_metric: Union[Metric, MetricCollection], **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(wrapped_metric, (Metric, MetricCollection)):
            raise TypeError(
                "Expected wrapped metric to be an instance of `torchmetrics_tpu.Metric` or "
                f"`torchmetrics_tpu.MetricCollection` but received {wrapped_metric}"
            )
        self.wrapped_metric = wrapped_metric

    def _merge_children(self):
        return [self.wrapped_metric]

    def transform_pred(self, pred):
        """Identity by default."""
        return pred

    def transform_target(self, target):
        """Identity by default."""
        return target

    def _wrap_transform(self, *args: Any) -> tuple:
        if len(args) == 1:
            return (self.transform_pred(args[0]),)
        if len(args) >= 2:
            return (self.transform_pred(args[0]), self.transform_target(args[1]), *args[2:])
        return args

    def update(self, *args: Any, **kwargs: Any) -> None:
        self.wrapped_metric.update(*self._wrap_transform(*args), **kwargs)
        self._update_count += 1
        self._computed = None

    def compute(self) -> Any:
        return self.wrapped_metric.compute()

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        self._update_count += 1
        return self.wrapped_metric.forward(*self._wrap_transform(*args), **kwargs)

    __call__ = forward

    def reset(self) -> None:
        self.wrapped_metric.reset()
        self._update_count = 0
        self._computed = None

    def _filter_kwargs(self, **kwargs: Any):
        return kwargs


class LambdaInputTransformer(MetricInputTransformer):
    """Transform inputs with user-provided callables (transformations.py:84).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import LambdaInputTransformer
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> metric = LambdaInputTransformer(BinaryAccuracy(), transform_pred=lambda p: 1 - p)
        >>> metric.update(jnp.asarray([0.2, 0.8, 0.1]), jnp.asarray([1, 0, 1]))
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    def __init__(
        self,
        wrapped_metric: Union[Metric, MetricCollection],
        transform_pred: Optional[Callable] = None,
        transform_target: Optional[Callable] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(wrapped_metric, **kwargs)
        if transform_pred is not None and not callable(transform_pred):
            raise TypeError(f"Expected `transform_pred` to be a callable, but got {type(transform_pred)}")
        if transform_target is not None and not callable(transform_target):
            raise TypeError(f"Expected `transform_target` to be a callable, but got {type(transform_target)}")
        if transform_pred is not None:
            self.transform_pred = transform_pred  # type: ignore[method-assign]
        if transform_target is not None:
            self.transform_target = transform_target  # type: ignore[method-assign]


class BinaryTargetTransformer(MetricInputTransformer):
    """Binarize targets at ``threshold`` (transformations.py:137).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import BinaryTargetTransformer
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> metric = BinaryTargetTransformer(BinaryAccuracy(), threshold=2)
        >>> metric.update(jnp.asarray([0.8, 0.2, 0.9]), jnp.asarray([3.0, 1.0, 5.0]))
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    def __init__(
        self, wrapped_metric: Union[Metric, MetricCollection], threshold: float = 0, **kwargs: Any
    ) -> None:
        super().__init__(wrapped_metric, **kwargs)
        if not isinstance(threshold, (int, float)):
            raise TypeError(f"Expected `threshold` to be a float, but got {type(threshold)}")
        self.threshold = threshold

    def transform_target(self, target):
        return (jnp.asarray(target) > self.threshold).astype(jnp.int32)
