"""MetricTracker (reference wrappers/tracker.py:33).

Tracks a metric (or collection) across time steps: ``increment()`` starts a new step
with a fresh clone; ``compute_all()`` stacks per-step values; ``best_metric()`` picks
the best step per the ``maximize`` flag(s).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np

from ..collections import MetricCollection
from ..metric import Metric
from ..utilities.prints import rank_zero_warn


class MetricTracker:
    """List of per-step metric clones with best-value bookkeeping.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import MetricTracker
        >>> from torchmetrics_tpu.classification import MulticlassAccuracy
        >>> tracker = MetricTracker(MulticlassAccuracy(num_classes=3))
        >>> for epoch in range(2):
        ...     tracker.increment()
        ...     tracker.update(jnp.asarray([[0.8, 0.1, 0.1], [0.2, 0.7, 0.1]]), jnp.asarray([0, epoch]))
        >>> best, which = tracker.best_metric(return_step=True)
        >>> round(float(best), 4), which
        (1.0, 1)
    """

    def __init__(
        self, metric: Union[Metric, MetricCollection], maximize: Union[bool, List[bool], None] = None
    ) -> None:
        if not isinstance(metric, (Metric, MetricCollection)):
            raise TypeError(
                "Metric arg need to be an instance of a torchmetrics_tpu"
                f" `Metric` or `MetricCollection` but got {metric}"
            )
        self._base_metric = metric
        if maximize is None:
            if isinstance(metric, Metric):
                if getattr(metric, "higher_is_better", None) is None:
                    raise AttributeError(
                        f"The metric '{type(metric).__name__}' does not have a 'higher_is_better' attribute set,"
                        " and the `maximize` argument was not provided."
                    )
                maximize = bool(metric.higher_is_better)
            else:
                maximize = []
                for name, m in metric.items(keep_base=True):
                    if getattr(m, "higher_is_better", None) is None:
                        raise AttributeError(
                            f"The metric '{name}' does not have a 'higher_is_better' attribute set,"
                            " and the `maximize` argument was not provided."
                        )
                    maximize.append(bool(m.higher_is_better))
        if not isinstance(maximize, (bool, list)):
            raise ValueError("Argument `maximize` should either be a single bool or list of bool")
        if isinstance(maximize, list) and not (
            isinstance(metric, MetricCollection) and len(maximize) == len(metric)
        ):
            raise ValueError(
                "The len of argument `maximize` should match the length of the metric collection"
            )
        if isinstance(metric, Metric) and not isinstance(maximize, bool):
            raise ValueError("Argument `maximize` should be a single bool when `metric` is a single Metric")
        self.maximize = maximize
        self._steps: List[Union[Metric, MetricCollection]] = []
        self._increment_called = False

    @property
    def n_steps(self) -> int:
        """Number of tracked steps."""
        return len(self._steps)

    def increment(self) -> None:
        """Start a new time step with a fresh (reset) clone."""
        self._increment_called = True
        clone = self._base_metric.clone()
        clone.reset()
        self._steps.append(clone)

    def _check_for_increment(self, method: str) -> None:
        if not self._increment_called:
            raise ValueError(f"`{method}` cannot be called before `.increment()` has been called.")

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        self._check_for_increment("forward")
        return self._steps[-1](*args, **kwargs)

    __call__ = forward

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._check_for_increment("update")
        self._steps[-1].update(*args, **kwargs)

    def compute(self) -> Any:
        self._check_for_increment("compute")
        return self._steps[-1].compute()

    def compute_all(self) -> Any:
        """Stack values from all steps (tracker.py:188)."""
        self._check_for_increment("compute_all")
        res = [step.compute() for step in self._steps]
        if res and isinstance(res[0], dict):
            keys = res[0].keys()
            return {k: jnp.stack([jnp.asarray(r[k]) for r in res], axis=0) for k in keys}
        return jnp.stack([jnp.asarray(r) for r in res], axis=0)

    def reset(self) -> None:
        """Reset the current step."""
        self._steps[-1].reset()

    def reset_all(self) -> None:
        """Drop all tracked steps."""
        self._steps = []
        self._increment_called = False

    def best_metric(
        self, return_step: bool = False
    ) -> Union[Any, Tuple[Any, Any]]:
        """Best value (and optionally its step index) over time (tracker.py:238)."""
        res = self.compute_all()
        if isinstance(res, dict):
            maximize = self.maximize if isinstance(self.maximize, list) else [self.maximize] * len(res)
            value: Dict[str, Any] = {}
            idx: Dict[str, Any] = {}
            for i, (k, v) in enumerate(res.items()):
                try:
                    arr = np.asarray(v)
                    best = int(np.argmax(arr)) if maximize[i] else int(np.argmin(arr))
                    value[k], idx[k] = float(arr[best]), best
                except (ValueError, TypeError) as err:
                    rank_zero_warn(
                        f"Encountered the following error when trying to get the best metric for metric {k}:"
                        f"{err}. Returning `None` instead.",
                        UserWarning,
                    )
                    value[k], idx[k] = None, None
            return (value, idx) if return_step else value
        try:
            arr = np.asarray(res)
            best = int(np.argmax(arr)) if self.maximize else int(np.argmin(arr))
            return (float(arr[best]), best) if return_step else float(arr[best])
        except (ValueError, TypeError) as err:
            rank_zero_warn(
                f"Encountered the following error when trying to get the best metric: {err}."
                " Returning `None` instead.",
                UserWarning,
            )
            return (None, None) if return_step else None

    def __getitem__(self, idx: int) -> Union[Metric, MetricCollection]:
        return self._steps[idx]

    def __len__(self) -> int:
        return len(self._steps)
