"""FeatureShare (reference wrappers/feature_share.py:27,46).

A ``MetricCollection`` where all member metrics share ONE feature-extractor forward:
each metric declares ``feature_network = "<attr name>"`` pointing at its extractor
callable; the wrapper swaps every member's extractor for a single shared, memoized one
so e.g. FID+KID+IS run one Inception forward per batch instead of three.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional, Sequence, Union

from ..collections import MetricCollection
from ..metric import Metric


class NetworkCache:
    """Memoizing wrapper around a feature-extractor callable (feature_share.py:27).

    Results are cached per input-buffer identity (`id` of the unwrapped arrays), which
    is exactly the sharing pattern of a collection update: every member metric calls
    the extractor with the *same* array objects within one ``update`` call.
    """

    def __init__(self, network: Any, max_size: int = 100) -> None:
        self.network = network
        self.max_size = max_size
        # entries hold strong refs to the input arrays: an id() key is only valid while
        # the object it names is alive, so inputs must outlive their cache entry
        self._cache: Dict[tuple, tuple] = {}

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        key = tuple(id(a) for a in args) + tuple((k, id(v)) for k, v in sorted(kwargs.items()))
        if key in self._cache:
            return self._cache[key][-1]
        out = self.network(*args, **kwargs)
        if len(self._cache) >= self.max_size:
            self._cache.pop(next(iter(self._cache)))
        self._cache[key] = (args, kwargs, out)
        return out

    def __getattr__(self, name: str) -> Any:
        return getattr(self.__dict__["network"], name)


class FeatureShare(MetricCollection):
    """MetricCollection that dedupes the members' shared feature extractor.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import FeatureShare
        >>> from torchmetrics_tpu.image import FrechetInceptionDistance, KernelInceptionDistance
        >>> def tiny_extractor(imgs):
        ...     return imgs.reshape(imgs.shape[0], -1)[:, :8].astype(jnp.float32)
        >>> fs = FeatureShare([FrechetInceptionDistance(feature=tiny_extractor), KernelInceptionDistance(feature=tiny_extractor, subset_size=2)])
        >>> imgs_a = (jnp.arange(2 * 3 * 16 * 16).reshape(2, 3, 16, 16) * 37 % 255).astype(jnp.uint8)
        >>> imgs_b = (jnp.arange(2 * 3 * 16 * 16).reshape(2, 3, 16, 16) * 31 % 255).astype(jnp.uint8)
        >>> fs.update(imgs_a, real=True)
        >>> fs.update(imgs_b, real=False)
        >>> sorted(fs.compute())
        ['FrechetInceptionDistance', 'KernelInceptionDistance']
    """

    def __init__(
        self,
        metrics: Union[Metric, Sequence[Metric], Mapping[str, Metric]],
        max_cache_size: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(metrics, compute_groups=False, **kwargs)
        if max_cache_size is None:
            max_cache_size = len(self)
        if not isinstance(max_cache_size, int):
            raise TypeError(f"max_cache_size should be an integer, but got {max_cache_size}")

        try:
            first = next(iter(self.values()))
            network_name = str(first.feature_network)
        except AttributeError as err:
            raise AttributeError(
                "Tried to extract the network to share from the first metric, but it did not have a"
                " `feature_network` attribute. Please make sure that the metric has an attribute with that name,"
                " else it cannot be shared."
            ) from err
        shared = NetworkCache(getattr(first, network_name), max_size=max_cache_size)
        for metric in self.values():
            if not hasattr(metric, "feature_network"):
                raise AttributeError(
                    "Tried to set the cached network to all metrics, but one of the metrics did not have a"
                    " `feature_network` attribute. Please make sure that all metrics have that attribute,"
                    " else the network cannot be shared."
                )
            setattr(metric, str(metric.feature_network), shared)
