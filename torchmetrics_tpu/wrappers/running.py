"""Running (reference wrappers/running.py:28).

Metric value over the last ``window`` updates. The reference stores ``window`` extra
copies of every state inside the base metric (``key_{i}`` states, cyclic overwrite);
the pure-state design here keeps a ring of ``window`` full state pytrees captured per
update and folds them at compute — same memory, no name mangling.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Any

from ..metric import Metric
from ..utilities.exceptions import TorchMetricsUserError
from .abstract import WrapperMetric


def _snapshot(metric: Metric) -> dict:
    return {k: (list(v) if isinstance(v, list) else v) for k, v in metric._state.items()}


class Running(WrapperMetric):
    """Wrap a metric so ``compute()`` covers only the last ``window`` updates.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import Running
        >>> from torchmetrics_tpu.aggregation import SumMetric
        >>> metric = Running(SumMetric(), window=2)
        >>> for batch in [1.0, 2.0, 3.0]:
        ...     metric.update(batch)
        >>> metric.compute()
        Array(5., dtype=float32)
    """

    def __init__(self, base_metric: Metric, window: int = 5) -> None:
        super().__init__()
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected argument `base_metric` to be an instance of `torchmetrics_tpu.Metric` but got {base_metric}"
            )
        if not (isinstance(window, int) and window > 0):
            raise ValueError(f"Expected argument `window` to be a positive integer but got {window}")
        self.base_metric = base_metric
        self.window = window
        self._ring: list = []  # newest-last list of per-update state pytrees

    @contextmanager
    def _scratch_base(self):
        """Run the base metric from a fresh state, restoring its real state after."""
        saved, saved_count = _snapshot(self.base_metric), self.base_metric._update_count
        self.base_metric.reset()
        try:
            yield self.base_metric
        finally:
            self.base_metric._state = saved
            self.base_metric._update_count = saved_count
            self.base_metric._computed = None

    def _push(self, contrib: dict) -> None:
        self._ring.append(contrib)
        if len(self._ring) > self.window:
            self._ring.pop(0)
        self._update_count += 1
        self._computed = None

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Capture this update's isolated state contribution into the ring."""
        with self._scratch_base() as probe:
            probe.update(*args, **kwargs)
            self._push(_snapshot(probe))

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Batch value from the base metric; ring updated as in ``update``."""
        with self._scratch_base() as probe:
            val = probe.forward(*args, **kwargs)
            self._push(_snapshot(probe))
        return val

    __call__ = forward

    def compute(self) -> Any:
        """Fold the ring into a fresh state and compute."""
        with self._scratch_base() as probe:
            for contrib in self._ring:
                probe.merge_state({k: (list(v) if isinstance(v, list) else v) for k, v in contrib.items()})
            probe._update_count = max(1, len(self._ring))
            return probe.compute()

    def merge_state(self, incoming_state) -> None:
        """A running window is a property of ONE update stream: merging two ranks'
        windows has no defined order (whose last-``window`` updates win?), so this
        raises instead of silently interleaving. Sync the base metric directly if
        cross-rank values are needed."""
        raise TorchMetricsUserError(
            "Running metrics hold a stream-local window of the last updates; merging windows across "
            "ranks has no defined update order. Compute per-rank or wrap an unsynced base metric."
        )

    # ------------------------------------------------------------- checkpoint
    # The wrapper's real state is the ring of per-update state pytrees, not a
    # child Metric (WrapperMetric's child recursion does not apply — there are
    # no merge children; window merging is undefined, see merge_state). The
    # ring is flattened to "<prefix>_ring{i}.{key}[.{j}]" leaves so it rides a
    # plain array-pytree checkpoint (orbax-friendly, tests/test_orbax_checkpoint.py).

    def persistent(self, mode: bool = False) -> None:
        self._wrapper_persistent = mode
        self.base_metric.persistent(mode)

    def state_dict(self, destination=None, prefix: str = "") -> dict:
        import numpy as np

        destination = {} if destination is None else destination
        if not self._wrapper_persistent:
            return destination
        for i, contrib in enumerate(self._ring):
            for key, value in contrib.items():
                if isinstance(value, list):
                    destination[f"{prefix}_ring{i}.{key}._len"] = len(value)
                    for j, row in enumerate(value):
                        destination[f"{prefix}_ring{i}.{key}.{j}"] = np.asarray(row)
                else:
                    destination[f"{prefix}_ring{i}.{key}"] = np.asarray(value)
        destination[prefix + "_ring_len"] = len(self._ring)
        destination[prefix + "_wrapper_update_count"] = int(self._update_count)
        return destination

    def load_state_dict(self, state_dict: dict, prefix: str = "", validate: bool = True) -> None:
        import jax.numpy as jnp

        from ..utilities.exceptions import StateCorruptionError

        if prefix + "_ring_len" not in state_dict:
            if validate and prefix + "_wrapper_update_count" in state_dict:
                # the update-count metadata proves this wrapper WAS saved — a
                # missing ring length means the checkpoint lost keys
                raise StateCorruptionError(
                    f"Checkpoint slice '{prefix}*' for {type(self).__name__} is truncated: "
                    f"'_wrapper_update_count' is present but '_ring_len' is missing. "
                    f"Pass validate=False to skip the load."
                )
            return
        ring = []
        try:
            for i in range(int(state_dict[prefix + "_ring_len"])):
                contrib = {}
                for key, default in self.base_metric._defaults.items():
                    stem = f"{prefix}_ring{i}.{key}"
                    if isinstance(default, list):
                        contrib[key] = [
                            jnp.asarray(state_dict[f"{stem}.{j}"])
                            for j in range(int(state_dict[f"{stem}._len"]))
                        ]
                    else:
                        contrib[key] = jnp.asarray(state_dict[stem])
                ring.append(contrib)
        except KeyError as err:
            if validate:
                raise StateCorruptionError(
                    f"Checkpoint slice '{prefix}*' for {type(self).__name__} is truncated: "
                    f"ring entry key {err} is missing (partially-written ring)."
                ) from err
            raise
        count_key = prefix + "_wrapper_update_count"
        if count_key not in state_dict and validate:
            raise StateCorruptionError(
                f"Checkpoint slice '{prefix}*' for {type(self).__name__} is truncated: "
                f"the ring is present but '_wrapper_update_count' is missing."
            )
        self._ring = ring
        if count_key in state_dict:
            self._update_count = int(state_dict[count_key])
        self._computed = None

    def reset(self) -> None:
        self.base_metric.reset()
        self._ring = []
        self._update_count = 0
        self._computed = None

    def _filter_kwargs(self, **kwargs: Any):
        return self.base_metric._filter_kwargs(**kwargs)
