"""MinMaxMetric (reference wrappers/minmax.py:30).

Tracks the running min/max of the wrapped metric's compute value over time.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from ..metric import Metric
from .abstract import WrapperMetric


class MinMaxMetric(WrapperMetric):
    """Report ``{"raw": value, "min": lowest-seen, "max": highest-seen}``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import MinMaxMetric
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> metric = MinMaxMetric(BinaryAccuracy())
        >>> out1 = metric(jnp.asarray([0.9, 0.1]), jnp.asarray([1, 0]))
        >>> out2 = metric(jnp.asarray([0.9, 0.1]), jnp.asarray([0, 0]))
        >>> {k: round(float(v), 4) for k, v in out2.items()}
        {'raw': 0.5, 'max': 1.0, 'min': 0.5}
    """

    def __init__(self, base_metric: Metric, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of `torchmetrics_tpu.Metric` but received {base_metric}"
            )
        self._base_metric = base_metric
        self.min_val = jnp.asarray(jnp.inf)
        self.max_val = jnp.asarray(-jnp.inf)

    @staticmethod
    def _is_suitable_val(val: Any) -> bool:
        if isinstance(val, (int, float)):
            return True
        if hasattr(val, "shape"):
            return val.size == 1
        return False

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._base_metric.update(*args, **kwargs)
        self._update_count += 1
        self._computed = None

    def compute(self) -> Dict[str, jax.Array]:
        val = self._base_metric.compute()
        if not self._is_suitable_val(val):
            raise RuntimeError(f"Returned value from base metric should be a float or scalar tensor, but got {val}.")
        self.max_val = jnp.maximum(self.max_val, val)
        self.min_val = jnp.minimum(self.min_val, val)
        return {"raw": val, "max": self.max_val, "min": self.min_val}

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, jax.Array]:
        val = self._base_metric.forward(*args, **kwargs)
        self._update_count += 1
        if self._is_suitable_val(val):
            self.max_val = jnp.maximum(self.max_val, val)
            self.min_val = jnp.minimum(self.min_val, val)
        return {"raw": val, "max": self.max_val, "min": self.min_val}

    __call__ = forward

    def _merge_children(self):
        return [self._base_metric]

    def _merge_wrapper_extra(self, incoming: "MinMaxMetric") -> None:
        # running extrema fold by min/max — the natural cross-rank semantics
        self.min_val = jnp.minimum(self.min_val, incoming.min_val)
        self.max_val = jnp.maximum(self.max_val, incoming.max_val)

    def _checkpoint_extra(self):
        return {"min_val": self.min_val, "max_val": self.max_val}

    def _load_checkpoint_extra(self, extra) -> None:
        self.min_val = extra["min_val"]
        self.max_val = extra["max_val"]

    def reset(self) -> None:
        self._base_metric.reset()
        self.min_val = jnp.asarray(jnp.inf)
        self.max_val = jnp.asarray(-jnp.inf)
        self._update_count = 0
        self._computed = None

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        return self._base_metric._filter_kwargs(**kwargs)
