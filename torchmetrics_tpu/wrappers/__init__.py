__all__: list = []
