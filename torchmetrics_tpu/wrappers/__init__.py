"""Composition-layer wrappers (reference src/torchmetrics/wrappers/)."""

from .abstract import WrapperMetric
from .bootstrapping import BootStrapper
from .classwise import ClasswiseWrapper
from .feature_share import FeatureShare, NetworkCache
from .minmax import MinMaxMetric
from .multioutput import MultioutputWrapper
from .multitask import MultitaskWrapper
from .running import Running
from .tracker import MetricTracker
from .transformations import BinaryTargetTransformer, LambdaInputTransformer, MetricInputTransformer

__all__ = [
    "BinaryTargetTransformer",
    "BootStrapper",
    "ClasswiseWrapper",
    "FeatureShare",
    "LambdaInputTransformer",
    "MetricInputTransformer",
    "MetricTracker",
    "MinMaxMetric",
    "MultioutputWrapper",
    "MultitaskWrapper",
    "NetworkCache",
    "Running",
    "WrapperMetric",
]
