"""BootStrapper (reference wrappers/bootstrapping.py:55).

Maintains ``num_bootstraps`` independent copies of a base metric; every ``update``
feeds each copy a resampled-with-replacement view of the batch; ``compute`` reports
mean/std/quantile/raw over the replica values.

TPU-first notes (SURVEY §7 step 5): for tensor-state base metrics the replicas live as
ONE stacked ``(k, ...)`` state pytree, and every update is a single jitted call that
vmaps the base metric's pure ``update_state`` over a ``(k, batch)`` resample-index
matrix — strictly better than the reference's k deepcopies + k sequential updates
(``wrappers/bootstrapping.py:74-97``). Metrics with concat states (or the ``poisson``
sampler, whose variable-length index sets are a dynamic-shape recompile trap) fall
back to per-replica clones. The default sampler mirrors the reference
(``poisson``); pass ``sampling_strategy="multinomial"`` for the static-shape draws
that unlock the vmapped fast path.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..metric import Metric
from .abstract import WrapperMetric


def _bootstrap_sampler(
    rng: np.random.Generator, size: int, sampling_strategy: str = "multinomial"
) -> np.ndarray:
    """Resample-with-replacement row indices (reference bootstrapping.py:32)."""
    if sampling_strategy == "poisson":
        counts = rng.poisson(1.0, size=size)
        return np.repeat(np.arange(size), counts)
    if sampling_strategy == "multinomial":
        return rng.integers(0, size, size=size)
    raise ValueError("Unknown sampling strategy")


class BootStrapper(WrapperMetric):
    """Bootstrap resampling wrapper for confidence estimation.

    Args:
        base_metric: metric instance to bootstrap.
        num_bootstraps: number of replicas.
        mean/std: include mean/std over replicas in output dict.
        quantile: optional quantile(s) to report (float or sequence).
        raw: include the raw per-replica values.
        sampling_strategy: ``"poisson"`` (reference default) or ``"multinomial"``.
            Multinomial draws are static-shape, which unlocks the single-call
            vmapped stacked-state fast path; poisson resamples per replica on
            the list path.
        seed: host RNG seed for the resampler.


    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import BootStrapper
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> preds = jnp.asarray([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 0, 1, 1, 0, 1])
        >>> metric = BootStrapper(BinaryAccuracy(), num_bootstraps=4, sampling_strategy='multinomial', seed=7)
        >>> metric.update(preds, target)
        >>> {k: round(float(v), 4) for k, v in metric.compute().items()}
        {'mean': 1.0, 'std': 0.0}
    """

    def __init__(
        self,
        base_metric: Metric,
        num_bootstraps: int = 10,
        mean: bool = True,
        std: bool = True,
        quantile: Optional[Union[float, Sequence[float]]] = None,
        raw: bool = False,
        sampling_strategy: str = "poisson",
        seed: int = 0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of torchmetrics_tpu.Metric but received {base_metric}"
            )
        allowed_sampling = ("poisson", "multinomial")
        if sampling_strategy not in allowed_sampling:
            raise ValueError(
                f"Expected argument ``sampling_strategy`` to be one of {allowed_sampling}"
                f" but received {sampling_strategy}"
            )
        self.base_metric = base_metric.clone()
        self.num_bootstraps = num_bootstraps
        self.mean = mean
        self.std = std
        self.quantile = quantile
        self.raw = raw
        self.sampling_strategy = sampling_strategy
        self._rng = np.random.default_rng(seed)
        # vmapped stacked-state fast path: tensor states + jittable compute only
        self._use_vmap = (
            sampling_strategy == "multinomial"
            and not base_metric._list_state_names
            and base_metric._jittable_compute
            # bare "mean" states cannot fold statelessly (update_state would raise)
            and (base_metric._has_custom_merge() or not any(fx == "mean" for fx in base_metric._reductions.values()))
        )
        if self._use_vmap:
            self.metrics = []  # replicas live as one stacked pytree instead
            self._stacked = jax.tree.map(
                lambda leaf: jnp.broadcast_to(jnp.asarray(leaf), (num_bootstraps, *jnp.asarray(leaf).shape)).copy(),
                {k: v for k, v in self.base_metric.init_state().items()},
            )
            self._vmap_update = None
        else:
            self.metrics = [base_metric.clone() for _ in range(num_bootstraps)]

    def _get_vmap_update(self):
        if self._vmap_update is None:
            base = self.base_metric

            def step(stacked, idx_mat, *args, **kwargs):
                def one(state_k, row):
                    new_args = tuple(a[row] if hasattr(a, "shape") else a for a in args)
                    new_kwargs = {k: (v[row] if hasattr(v, "shape") else v) for k, v in kwargs.items()}
                    return base.update_state(state_k, *new_args, **new_kwargs)

                return jax.vmap(one)(stacked, idx_mat)

            self._vmap_update = jax.jit(step, donate_argnums=0)
        return self._vmap_update

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Feed each replica a resampled view of this batch (bootstrapping.py:126).

        Tensors resample along dim 0. When the inputs are SAMPLE LISTS instead
        (detection's list-of-image-dicts, text's list-of-sentences), the list
        elements are the resampling unit — bootstrapping over images/sentences.
        The reference's tensor-only resampler recurses into detection dicts and
        resamples boxes WITHIN images (wrappers/bootstrapping.py:172-178), which
        is not a bootstrap of the evaluation sample; this is a deliberate,
        tested divergence (tests/test_wrapper_detection_fuzz.py)."""
        sizes = [len(a) for a in args if hasattr(a, "shape")]
        sizes += [len(v) for v in kwargs.values() if hasattr(v, "shape")]
        if not sizes:
            sizes = [len(a) for a in args if isinstance(a, (list, tuple))]
            sizes += [len(v) for v in kwargs.values() if isinstance(v, (list, tuple))]
        if not sizes:
            raise ValueError("None of the input contained tensors, so could not determine the sampling size")
        size = sizes[0]
        if self._use_vmap:
            # ONE jitted call: vmap the pure update over a (k, batch) index matrix
            idx_mat = jnp.asarray(self._rng.integers(0, size, size=(self.num_bootstraps, size)))
            args = tuple(jnp.asarray(a) if hasattr(a, "shape") else a for a in args)
            kwargs = {k: (jnp.asarray(v) if hasattr(v, "shape") else v) for k, v in kwargs.items()}
            self._stacked = self._get_vmap_update()(self._stacked, idx_mat, *args, **kwargs)
            self._update_count += 1
            self._computed = None
            return
        for idx in range(self.num_bootstraps):
            sample_idx = _bootstrap_sampler(self._rng, size, self.sampling_strategy)
            if sample_idx.size == 0:
                continue
            idx_arr = jnp.asarray(sample_idx)

            def take(a):
                if hasattr(a, "shape"):
                    return a[idx_arr]
                if isinstance(a, (list, tuple)):
                    return [a[int(i)] for i in sample_idx]
                return a

            self.metrics[idx].update(*(take(a) for a in args), **{k: take(v) for k, v in kwargs.items()})
        self._update_count += 1
        self._computed = None

    def compute(self) -> Dict[str, jax.Array]:
        """Aggregate replica values (bootstrapping.py:149).

        Dict-returning bases (detection's mAP) aggregate leaf-wise: each output
        key gets its own mean/std/... over replicas (requires per-replica
        outputs of matching shape — with per-class outputs, data where a
        bootstrap draw can drop a class entirely makes shapes ragged)."""
        if self._use_vmap:
            computed_vals = jax.vmap(self.base_metric.compute_state)(self._stacked)
        else:
            vals = [m.compute() for m in self.metrics]
            computed_vals = jax.tree.map(lambda *xs: jnp.stack([jnp.asarray(x) for x in xs], axis=0), *vals)
        output: Dict[str, jax.Array] = {}
        if self.mean:
            output["mean"] = jax.tree.map(lambda v: v.mean(axis=0), computed_vals)
        if self.std:
            output["std"] = jax.tree.map(lambda v: v.astype(jnp.float32).std(axis=0, ddof=1), computed_vals)
        if self.quantile is not None:
            output["quantile"] = jax.tree.map(
                lambda v: jnp.quantile(v.astype(jnp.float32), jnp.asarray(self.quantile), axis=0), computed_vals
            )
        if self.raw:
            output["raw"] = computed_vals
        return output

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, jax.Array]:
        """Global accumulate AND batch-only bootstrap dict (reference forward contract:
        the returned value covers this batch alone, like every other metric)."""
        self.update(*args, **kwargs)
        if self._use_vmap:
            saved_stacked = self._stacked
            self._stacked = jax.tree.map(
                lambda leaf: jnp.broadcast_to(
                    jnp.asarray(leaf), (self.num_bootstraps, *jnp.asarray(leaf).shape)
                ).copy(),
                {k: v for k, v in self.base_metric.init_state().items()},
            )
            self.update(*args, **kwargs)  # fresh resample for the batch-only estimate
            self._update_count -= 1
            out = self.compute()
            self._computed = None
            self._stacked = saved_stacked
            return out
        saved = [
            {k: (list(v) if isinstance(v, list) else v) for k, v in m._state.items()} for m in self.metrics
        ]
        saved_counts = [m._update_count for m in self.metrics]
        for m in self.metrics:
            m.reset()
        self.update(*args, **kwargs)  # fresh resample for the batch-only estimate
        self._update_count -= 1
        out = self.compute()
        self._computed = None
        for m, st, cnt in zip(self.metrics, saved, saved_counts):
            m._state = st
            m._update_count = cnt
            m._computed = None
        return out

    __call__ = forward

    def _merge_children(self):
        if self._use_vmap:
            return []  # stacked pytree handled in _merge_wrapper_extra
        return list(self.metrics)

    def _merge_wrapper_extra(self, incoming: "BootStrapper") -> None:
        if not self._use_vmap:
            return
        # fold the (k, ...) stacked replica states replica-wise — exactly the
        # per-child merge of the list path, one vectorized fold. Bases with a
        # custom _merge (dist_reduce_fx=None states, e.g. Pearson's Chan moments)
        # MUST go through it: their reduction tags are None, which merge_states
        # would resolve by keeping the left side only.
        if self.base_metric._has_custom_merge():
            self._stacked = jax.vmap(self.base_metric._merge)(self._stacked, incoming._stacked)
        else:
            from ..parallel import sync as _sync

            self._stacked = _sync.merge_states(
                self._stacked,
                incoming._stacked,
                self.base_metric._reductions,
                weights=(float(self._update_count), float(incoming._update_count)),
            )

    def _checkpoint_extra(self):
        # the vmapped fast path accumulates in the stacked (k, ...) pytree, not
        # in child Metric instances — persist it alongside the children
        return dict(self._stacked) if self._use_vmap else {}

    def _load_checkpoint_extra(self, extra) -> None:
        if self._use_vmap:
            self._stacked = {
                k: jnp.asarray(extra[k]).astype(jnp.asarray(v).dtype)
                for k, v in self._stacked.items()
            }

    def reset(self) -> None:
        if self._use_vmap:
            self._stacked = jax.tree.map(
                lambda leaf: jnp.broadcast_to(
                    jnp.asarray(leaf), (self.num_bootstraps, *jnp.asarray(leaf).shape)
                ).copy(),
                {k: v for k, v in self.base_metric.init_state().items()},
            )
        for m in self.metrics:
            m.reset()
        self._update_count = 0
        self._computed = None

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        return self.metrics[0]._filter_kwargs(**kwargs)
