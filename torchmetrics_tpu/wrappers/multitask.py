"""MultitaskWrapper (reference wrappers/multitask.py:31).

Applies a dict of task-name → metric to dicts of task-name → preds/targets.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Union

from ..collections import MetricCollection
from ..metric import Metric
from .abstract import WrapperMetric


class MultitaskWrapper(WrapperMetric):
    """Compute different metrics on different tasks.

    Args:
        task_metrics: dict of task name → ``Metric`` or ``MetricCollection``.
        prefix / postfix: added to task keys in the output dict.


    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import MultitaskWrapper
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> from torchmetrics_tpu.regression import MeanSquaredError
        >>> metric = MultitaskWrapper({'cls': BinaryAccuracy(), 'reg': MeanSquaredError()})
        >>> metric.update({'cls': jnp.asarray([0.9, 0.1]), 'reg': jnp.asarray([2.5, 1.0])}, {'cls': jnp.asarray([1, 0]), 'reg': jnp.asarray([3.0, 1.0])})
        >>> {k: round(float(v), 4) for k, v in metric.compute().items()}
        {'cls': 1.0, 'reg': 0.125}
    """

    def __init__(
        self,
        task_metrics: Dict[str, Union[Metric, MetricCollection]],
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(task_metrics, dict):
            raise TypeError(f"Expected argument `task_metrics` to be a dict. Found task_metrics = {task_metrics}")
        for metric in task_metrics.values():
            if not isinstance(metric, (Metric, MetricCollection)):
                raise TypeError(
                    "Expected each task's metric to be a Metric or a MetricCollection. "
                    f"Found a metric of type {type(metric)}"
                )
        if prefix is not None and not isinstance(prefix, str):
            raise TypeError(f"Expected argument `prefix` to either be `None` or a string but got {prefix}")
        if postfix is not None and not isinstance(postfix, str):
            raise TypeError(f"Expected argument `postfix` to either be `None` or a string but got {postfix}")
        self.task_metrics = task_metrics
        self._prefix = prefix or ""
        self._postfix = postfix or ""

    def _convert(self, d: Dict[str, Any]) -> Dict[str, Any]:
        return {f"{self._prefix}{k}{self._postfix}": v for k, v in d.items()}

    @staticmethod
    def _check_keys(task_metrics: dict, task_preds: dict, task_targets: dict) -> None:
        if task_metrics.keys() != task_preds.keys() or task_metrics.keys() != task_targets.keys():
            raise ValueError(
                "Expected arguments `task_preds` and `task_targets` to have the same keys as the wrapped `task_metrics`. "
                f"Found task_preds.keys() = {task_preds.keys()}, task_targets.keys() = {task_targets.keys()} "
                f"and self.task_metrics.keys() = {task_metrics.keys()}"
            )

    def update(self, task_preds: Dict[str, Any], task_targets: Dict[str, Any]) -> None:
        self._check_keys(self.task_metrics, task_preds, task_targets)
        for name, metric in self.task_metrics.items():
            metric.update(task_preds[name], task_targets[name])
        self._update_count += 1
        self._computed = None

    def compute(self) -> Dict[str, Any]:
        return self._convert({name: metric.compute() for name, metric in self.task_metrics.items()})

    def forward(self, task_preds: Dict[str, Any], task_targets: Dict[str, Any]) -> Dict[str, Any]:
        self._check_keys(self.task_metrics, task_preds, task_targets)
        self._update_count += 1
        return self._convert(
            {name: metric.forward(task_preds[name], task_targets[name]) for name, metric in self.task_metrics.items()}
        )

    __call__ = forward

    def _merge_children(self):
        return [self.task_metrics[k] for k in sorted(self.task_metrics)]

    def merge_state(self, incoming_state) -> None:
        # positional pairing of sorted children is only sound when the task key
        # sets agree — unequal sets would silently cross-fold different tasks
        if isinstance(incoming_state, MultitaskWrapper) and set(self.task_metrics) != set(
            incoming_state.task_metrics
        ):
            raise ValueError(
                "Cannot merge MultitaskWrappers with different tasks: "
                f"{sorted(set(self.task_metrics) ^ set(incoming_state.task_metrics))}"
            )
        super().merge_state(incoming_state)

    def reset(self) -> None:
        for metric in self.task_metrics.values():
            metric.reset()
        self._update_count = 0
        self._computed = None

    def clone(self, prefix: Optional[str] = None, postfix: Optional[str] = None) -> "MultitaskWrapper":
        import copy

        new = copy.deepcopy(self)
        if prefix is not None:
            new._prefix = prefix
        if postfix is not None:
            new._postfix = postfix
        return new

    def keys(self):
        return self.task_metrics.keys()

    def items(self):
        return self.task_metrics.items()

    def values(self):
        return self.task_metrics.values()

    def __getitem__(self, key: str):
        return self.task_metrics[key]
