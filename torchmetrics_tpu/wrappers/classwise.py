"""ClasswiseWrapper (reference wrappers/classwise.py:32).

Splits a per-class tensor output (``average=None`` metrics) into a labeled dict.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax

from ..metric import Metric
from .abstract import WrapperMetric


class ClasswiseWrapper(WrapperMetric):
    """Wrap a metric returning a per-class vector into a ``{label: scalar}`` dict.

    Args:
        metric: base metric returning a tensor with one element per class.
        labels: list of class label strings; defaults to class indices.
        prefix: key prefix; defaults to ``<metricname>_`` when neither prefix nor
            postfix is given (reference classwise.py:156).
        postfix: key postfix.


    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import ClasswiseWrapper
        >>> from torchmetrics_tpu.classification import MulticlassAccuracy
        >>> preds = jnp.asarray([[0.75, 0.05, 0.20], [0.10, 0.80, 0.10], [0.20, 0.30, 0.50], [0.25, 0.40, 0.35]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> metric = ClasswiseWrapper(MulticlassAccuracy(num_classes=3, average=None))
        >>> metric.update(preds, target)
        >>> {k: round(float(v), 4) for k, v in metric.compute().items()}
        {'multiclassaccuracy_0': 1.0, 'multiclassaccuracy_1': 1.0, 'multiclassaccuracy_2': 1.0}
    """

    def __init__(
        self,
        metric: Metric,
        labels: Optional[List[str]] = None,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
    ) -> None:
        super().__init__()
        if not isinstance(metric, Metric):
            raise ValueError(f"Expected argument `metric` to be an instance of `Metric` but got {metric}")
        if labels is not None and not (isinstance(labels, list) and all(isinstance(lab, str) for lab in labels)):
            raise ValueError(f"Expected argument `labels` to either be `None` or a list of strings but got {labels}")
        if prefix is not None and not isinstance(prefix, str):
            raise ValueError(f"Expected argument `prefix` to either be `None` or a string but got {prefix}")
        if postfix is not None and not isinstance(postfix, str):
            raise ValueError(f"Expected argument `postfix` to either be `None` or a string but got {postfix}")
        self.metric = metric
        self.labels = labels
        if prefix is None and postfix is None:
            prefix = f"{type(metric).__name__.lower()}_"
        self._prefix = prefix or ""
        self._postfix = postfix or ""

    def _convert_output(self, x) -> Dict[str, jax.Array]:
        if isinstance(x, dict):
            # dict-returning metrics (detection): label the `*_per_class` vectors
            # per class — the reference's tensor-only wrapper degenerates to
            # enumerating dict KEYS here (classwise.py:154-166), which is never
            # what a detection user wants; scalars pass through under their own
            # names. Class labels come from `labels`, else the metric's
            # `classes` output, else indices.
            out: Dict[str, jax.Array] = {}
            for key, val in x.items():
                if key.endswith("_per_class") and getattr(val, "ndim", 0) == 1:
                    stem = key[: -len("_per_class")]
                    # per-class vectors align with the metric's OBSERVED class
                    # ids (`classes`), which may be sparse — user labels are
                    # indexed BY CLASS ID, never positionally (a positional zip
                    # would silently mislabel every class when ids skip 0)
                    if "classes" in x and getattr(x["classes"], "ndim", 0) == 1 and x["classes"].shape[0] == val.shape[0]:
                        class_ids = [int(c) for c in x["classes"]]
                    else:
                        class_ids = list(range(int(val.shape[0])))
                    if self.labels is not None:
                        if class_ids and max(class_ids) >= len(self.labels):
                            raise ValueError(
                                f"Metric reported class id {max(class_ids)} but only "
                                f"{len(self.labels)} labels were given for key {key!r}."
                            )
                        labels = [self.labels[c] for c in class_ids]
                    else:
                        labels = class_ids
                    for i, lab in enumerate(labels):
                        out[f"{self._prefix}{stem}_{lab}{self._postfix}"] = val[i]
                else:
                    # `classes` is consumed for labeling above but still passes
                    # through under its prefixed name — downstream consumers need
                    # the observed-class-id vector to interpret sparse outputs
                    # (ADVICE round 5: dropping it silently lost information)
                    out[f"{self._prefix}{key}{self._postfix}"] = val
            return out
        n = int(x.shape[0]) if getattr(x, "ndim", 0) > 0 else 1
        labels = self.labels if self.labels is not None else list(range(n))
        if len(labels) != n:
            # jnp indexing clamps out-of-bounds, which would silently duplicate values
            raise ValueError(
                f"Expected number of labels ({len(labels)}) to match the metric output length ({n})."
            )
        return {f"{self._prefix}{lab}{self._postfix}": x[i] for i, lab in enumerate(labels)}

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        return self.metric._filter_kwargs(**kwargs)

    def update(self, *args: Any, **kwargs: Any) -> None:
        self.metric.update(*args, **kwargs)
        self._update_count += 1
        self._computed = None

    def compute(self) -> Dict[str, jax.Array]:
        return self._convert_output(self.metric.compute())

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, jax.Array]:
        val = self.metric.forward(*args, **kwargs)
        self._update_count += 1
        return self._convert_output(val)

    __call__ = forward

    def _merge_children(self):
        return [self.metric]

    def reset(self) -> None:
        self.metric.reset()
        self._update_count = 0
        self._computed = None

    @property
    def metric_state(self):
        return self.metric.metric_state
