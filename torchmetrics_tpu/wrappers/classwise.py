"""ClasswiseWrapper (reference wrappers/classwise.py:32).

Splits a per-class tensor output (``average=None`` metrics) into a labeled dict.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax

from ..metric import Metric
from .abstract import WrapperMetric


class ClasswiseWrapper(WrapperMetric):
    """Wrap a metric returning a per-class vector into a ``{label: scalar}`` dict.

    Args:
        metric: base metric returning a tensor with one element per class.
        labels: list of class label strings; defaults to class indices.
        prefix: key prefix; defaults to ``<metricname>_`` when neither prefix nor
            postfix is given (reference classwise.py:156).
        postfix: key postfix.
    """

    def __init__(
        self,
        metric: Metric,
        labels: Optional[List[str]] = None,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
    ) -> None:
        super().__init__()
        if not isinstance(metric, Metric):
            raise ValueError(f"Expected argument `metric` to be an instance of `Metric` but got {metric}")
        if labels is not None and not (isinstance(labels, list) and all(isinstance(lab, str) for lab in labels)):
            raise ValueError(f"Expected argument `labels` to either be `None` or a list of strings but got {labels}")
        if prefix is not None and not isinstance(prefix, str):
            raise ValueError(f"Expected argument `prefix` to either be `None` or a string but got {prefix}")
        if postfix is not None and not isinstance(postfix, str):
            raise ValueError(f"Expected argument `postfix` to either be `None` or a string but got {postfix}")
        self.metric = metric
        self.labels = labels
        if prefix is None and postfix is None:
            prefix = f"{type(metric).__name__.lower()}_"
        self._prefix = prefix or ""
        self._postfix = postfix or ""

    def _convert_output(self, x: jax.Array) -> Dict[str, jax.Array]:
        n = int(x.shape[0]) if getattr(x, "ndim", 0) > 0 else 1
        labels = self.labels if self.labels is not None else list(range(n))
        if len(labels) != n:
            # jnp indexing clamps out-of-bounds, which would silently duplicate values
            raise ValueError(
                f"Expected number of labels ({len(labels)}) to match the metric output length ({n})."
            )
        return {f"{self._prefix}{lab}{self._postfix}": x[i] for i, lab in enumerate(labels)}

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        return self.metric._filter_kwargs(**kwargs)

    def update(self, *args: Any, **kwargs: Any) -> None:
        self.metric.update(*args, **kwargs)
        self._update_count += 1
        self._computed = None

    def compute(self) -> Dict[str, jax.Array]:
        return self._convert_output(self.metric.compute())

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, jax.Array]:
        val = self.metric.forward(*args, **kwargs)
        self._update_count += 1
        return self._convert_output(val)

    __call__ = forward

    def _merge_children(self):
        return [self.metric]

    def reset(self) -> None:
        self.metric.reset()
        self._update_count = 0
        self._computed = None

    @property
    def metric_state(self):
        return self.metric.metric_state
