"""Wrapper base class (reference wrappers/abstract.py:19).

The reference's ``WrapperMetric`` exists to undo ``forward``'s double-update caching
trickery for metrics that wrap other metrics. Our core is pure (no cache/restore
gymnastics), so the base here only marks the class as a wrapper and provides the
delegation-friendly defaults: wrappers own no jitted ``_batch_state``; they drive their
children's public APIs directly.
"""

from __future__ import annotations

from typing import Any

from ..metric import Metric


class WrapperMetric(Metric):
    """Abstract base class for wrapper metrics."""

    def _wrap_children_kwargs(self, **kwargs: Any) -> Any:
        return kwargs

    # ------------------------------------------------------------------ merge
    # The base Metric.merge_state folds `self._state` — which for wrappers is
    # empty; their accumulation lives in child Metric instances. Without this
    # override, merging two wrapper shards silently kept only the left shard's
    # data (caught by tests/test_wrapper_merge_fuzz.py).

    def _merge_children(self):
        """Ordered child Metric instances to pair-merge; wrappers override."""
        raise NotImplementedError(
            f"{type(self).__name__} does not define its children for merge_state."
        )

    def _merge_wrapper_extra(self, incoming: "WrapperMetric") -> None:
        """Hook for wrapper-level non-child state (e.g. MinMax's running extrema)."""

    def merge_state(self, incoming_state) -> None:
        if not isinstance(incoming_state, WrapperMetric) or type(incoming_state) is not type(self):
            raise ValueError(
                f"Expected incoming state to be an instance of {type(self).__name__}; wrapper metrics "
                "merge wrapper-to-wrapper (their accumulation lives in child metrics, not a state dict)."
            )
        mine = list(self._merge_children())
        theirs = list(incoming_state._merge_children())
        if len(mine) != len(theirs):
            raise ValueError(
                f"Cannot merge {type(self).__name__}: child metric counts differ ({len(mine)} vs {len(theirs)})."
            )
        for child, other in zip(mine, theirs):
            child.merge_state(other)
        self._merge_wrapper_extra(incoming_state)
        self._update_count += incoming_state._update_count
        self._computed = None

    # ------------------------------------------------------------- checkpoint
    # The reference's wrappers checkpoint for free through nn.Module recursion
    # (reference metric.py:919-990 + Module.state_dict). Here the children are
    # plain attributes, so the wrapper recurses explicitly: children are saved
    # under their `_merge_children()` order (stable per wrapper type), and
    # wrapper-level non-child state rides through the `_checkpoint_extra` hook.
    # Persistence mirrors the base Metric contract: nothing is written (and no
    # update count is stamped) unless `persistent(True)` was called, so a
    # default-persistence wrapper restores as cleanly fresh instead of as an
    # updated metric with empty children.

    _wrapper_persistent = False

    def persistent(self, mode: bool = False) -> None:
        super().persistent(mode)
        self._wrapper_persistent = mode
        for child in self._merge_children():
            child.persistent(mode)

    def _checkpoint_extra(self) -> dict:
        """Wrapper-level non-child state to persist (e.g. MinMax extrema)."""
        return {}

    def _load_checkpoint_extra(self, extra: dict) -> None:
        """Restore what `_checkpoint_extra` saved; wrappers with extra override."""

    def state_dict(self, destination=None, prefix: str = "") -> dict:
        import numpy as np

        destination = {} if destination is None else destination
        before = len(destination)
        super().state_dict(destination, prefix)
        for i, child in enumerate(self._merge_children()):
            child.state_dict(destination, f"{prefix}_child{i}.")
        if self._wrapper_persistent:
            for k, v in self._checkpoint_extra().items():
                destination[f"{prefix}_wrapper_extra.{k}"] = np.asarray(v)
        if len(destination) > before:
            destination[prefix + "_wrapper_update_count"] = int(self._update_count)
        return destination

    def load_state_dict(self, state_dict: dict, prefix: str = "", validate: bool = True) -> None:
        import jax.numpy as jnp

        super().load_state_dict(state_dict, prefix, validate=validate)
        for i, child in enumerate(self._merge_children()):
            child.load_state_dict(state_dict, f"{prefix}_child{i}.", validate=validate)
        count_key = prefix + "_wrapper_update_count"
        if count_key in state_dict:
            self._update_count = int(state_dict[count_key])
            self._computed = None
        extra_prefix = prefix + "_wrapper_extra."
        extra = {
            k[len(extra_prefix):]: jnp.asarray(v)
            for k, v in state_dict.items()
            if k.startswith(extra_prefix)
        }
        if extra:
            self._load_checkpoint_extra(extra)
            self._computed = None

    def _batch_state(self, *args: Any, **kwargs: Any):  # pragma: no cover - wrappers bypass
        raise NotImplementedError(f"{type(self).__name__} drives its children directly.")

    def _compute(self, state):  # pragma: no cover - wrappers bypass
        raise NotImplementedError(f"{type(self).__name__} drives its children directly.")

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Wrappers define forward in terms of their children's forward."""
        raise NotImplementedError

    __call__ = forward
