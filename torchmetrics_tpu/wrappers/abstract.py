"""Wrapper base class (reference wrappers/abstract.py:19).

The reference's ``WrapperMetric`` exists to undo ``forward``'s double-update caching
trickery for metrics that wrap other metrics. Our core is pure (no cache/restore
gymnastics), so the base here only marks the class as a wrapper and provides the
delegation-friendly defaults: wrappers own no jitted ``_batch_state``; they drive their
children's public APIs directly.
"""

from __future__ import annotations

from typing import Any

from ..metric import Metric


class WrapperMetric(Metric):
    """Abstract base class for wrapper metrics."""

    def _wrap_children_kwargs(self, **kwargs: Any) -> Any:
        return kwargs

    def _batch_state(self, *args: Any, **kwargs: Any):  # pragma: no cover - wrappers bypass
        raise NotImplementedError(f"{type(self).__name__} drives its children directly.")

    def _compute(self, state):  # pragma: no cover - wrappers bypass
        raise NotImplementedError(f"{type(self).__name__} drives its children directly.")

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Wrappers define forward in terms of their children's forward."""
        raise NotImplementedError

    __call__ = forward
