"""MultioutputWrapper (reference wrappers/multioutput.py:44).

Computes one copy of a single-output metric per slice of an output dimension, with
optional NaN-row removal per output.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

from ..metric import Metric
from .abstract import WrapperMetric


def _nan_rows(*arrays: jax.Array) -> jax.Array:
    """Rows (dim-0 indices) where any input holds a NaN."""
    mask = None
    for a in arrays:
        flat = jnp.isnan(a.reshape(a.shape[0], -1)).any(axis=-1) if a.ndim > 1 else jnp.isnan(a)
        mask = flat if mask is None else (mask | flat)
    return mask


class MultioutputWrapper(WrapperMetric):
    """Evaluate ``base_metric`` independently along ``output_dim`` slices.

    Args:
        base_metric: single-output metric to replicate.
        num_outputs: number of slices along ``output_dim``.
        output_dim: dimension to slice inputs along.
        remove_nans: drop dim-0 rows containing NaN in any input (per output slice).
        squeeze_outputs: squeeze the selected slice's output dim before updating.


    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.wrappers import MultioutputWrapper
        >>> from torchmetrics_tpu.regression import MeanSquaredError
        >>> preds = jnp.asarray([[1.0, 10.0], [2.0, 20.0], [3.0, 30.0]])
        >>> target = jnp.asarray([[1.0, 11.0], [2.0, 22.0], [3.0, 33.0]])
        >>> metric = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array([0.       , 4.6666665], dtype=float32)
    """

    is_differentiable = False

    def __init__(
        self,
        base_metric: Metric,
        num_outputs: int,
        output_dim: int = -1,
        remove_nans: bool = True,
        squeeze_outputs: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.metrics = [base_metric.clone() for _ in range(num_outputs)]
        self.output_dim = output_dim
        self.remove_nans = remove_nans
        self.squeeze_outputs = squeeze_outputs

    def _slice_inputs(self, *args: Any, **kwargs: Any) -> List[Tuple[tuple, dict]]:
        out = []
        for i in range(len(self.metrics)):
            sel = lambda a: jnp.take(a, jnp.asarray([i]), axis=self.output_dim) if hasattr(a, "shape") else a
            sargs = tuple(sel(a) for a in args)
            skwargs = {k: sel(v) for k, v in kwargs.items()}
            if self.remove_nans:
                tensors = [a for a in (*sargs, *skwargs.values()) if hasattr(a, "shape")]
                nan_idx = _nan_rows(*tensors)
                keep = jnp.flatnonzero(~nan_idx)  # dynamic shape: host-side filter (eval path)
                sargs = tuple(a[keep] if hasattr(a, "shape") else a for a in sargs)
                skwargs = {k: (v[keep] if hasattr(v, "shape") else v) for k, v in skwargs.items()}
            if self.squeeze_outputs:
                sargs = tuple(jnp.squeeze(a, self.output_dim) if hasattr(a, "shape") else a for a in sargs)
                skwargs = {k: (jnp.squeeze(v, self.output_dim) if hasattr(v, "shape") else v) for k, v in skwargs.items()}
            out.append((sargs, skwargs))
        return out

    def update(self, *args: Any, **kwargs: Any) -> None:
        for metric, (sargs, skwargs) in zip(self.metrics, self._slice_inputs(*args, **kwargs)):
            metric.update(*sargs, **skwargs)
        self._update_count += 1
        self._computed = None

    def compute(self) -> jax.Array:
        return jnp.stack([m.compute() for m in self.metrics], axis=0)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        results = [
            metric.forward(*sargs, **skwargs)
            for metric, (sargs, skwargs) in zip(self.metrics, self._slice_inputs(*args, **kwargs))
        ]
        self._update_count += 1
        if any(r is None for r in results):
            return None
        return jnp.stack(results, 0)

    __call__ = forward

    def _merge_children(self):
        return list(self.metrics)

    def reset(self) -> None:
        for m in self.metrics:
            m.reset()
        self._update_count = 0
        self._computed = None

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        return self.metrics[0]._filter_kwargs(**kwargs)
