"""Deterministic tenant→host placement: weighted rendezvous hashing.

Every router must answer "which host owns tenant T?" identically, with no
coordination and no shared table — the placement is a pure function of the
tenant id and the live host set. Rendezvous (highest-random-weight) hashing
gives exactly that: score every ``(host, tenant)`` pair with a keyed hash
and seat the tenant on the highest-scoring host. Its defining property is
*minimal disruption*: when a host joins or leaves, the only tenants that
move are the ones whose argmax changed — on a leave, exactly the dead
host's tenants (they redistribute across the survivors in hash proportion);
on a join, an ≈``w/(W+w)`` fraction of everyone's tenants (the new host's
fair share) and nobody else.

Weights use the classical ``-w / ln(u)`` transform (Thaler & Ravishankar):
``u`` is the pair hash mapped into ``(0, 1)``, so a host with twice the
weight wins twice the tenants in expectation, and weight changes reshuffle
only the proportional difference. Hashes are sha256 over the UTF-8 encoded
``host\\x00tenant`` pair — deterministic across processes and platforms
(no ``PYTHONHASHSEED`` dependence), which the fleet soak's determinism
contract requires.

:func:`rebalance_plan` turns a membership change into the explicit minimal
move set: recompute the placement under the new host set, diff against the
current assignment, and emit one :class:`Move` per tenant whose owner
changed. The controller feeds these straight into ``migrate``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import math
from typing import Dict, Hashable, List, Mapping, Optional, Sequence

from ..utilities.exceptions import TorchMetricsUserError

__all__ = ["Move", "placement_score", "place", "place_all", "rebalance_plan"]


def _pair_hash(host_id: str, tenant_id: Hashable) -> float:
    """Keyed hash of one (host, tenant) pair mapped into the open interval
    (0, 1). Tenant ids hash by ``repr`` so ints and strs cannot collide
    (``1`` vs ``"1"``), matching the durability plane's id discipline."""
    h = hashlib.sha256(f"{host_id}\x00{tenant_id!r}".encode("utf-8")).digest()
    # 53 bits -> the full double mantissa; +1/+2 keeps u strictly inside (0,1)
    u = (int.from_bytes(h[:8], "big") >> 11) + 1
    return u / float((1 << 53) + 2)


def placement_score(host_id: str, tenant_id: Hashable, weight: float = 1.0) -> float:
    """The weighted rendezvous score of seating ``tenant_id`` on ``host_id``
    (higher wins). ``-w / ln(u)`` preserves proportional balance: doubling a
    host's weight doubles its expected tenant share without moving any
    tenant whose argmax did not change."""
    if not weight > 0:
        raise TorchMetricsUserError(f"host weight must be > 0, got {weight}")
    return -float(weight) / math.log(_pair_hash(host_id, tenant_id))


def place(tenant_id: Hashable, hosts: Mapping[str, float]) -> str:
    """The owning host for ``tenant_id`` under the live ``hosts`` (host id →
    weight) map. Ties (practically impossible with a 53-bit hash) break by
    host id so every router still agrees."""
    if not hosts:
        raise TorchMetricsUserError("cannot place a tenant on an empty host set")
    return max(
        sorted(hosts),
        key=lambda h: (placement_score(h, tenant_id, hosts[h]), h),
    )


def place_all(
    tenant_ids: Sequence[Hashable], hosts: Mapping[str, float]
) -> Dict[Hashable, str]:
    """Vector form of :func:`place` (one deterministic pass)."""
    return {tid: place(tid, hosts) for tid in tenant_ids}


@dataclasses.dataclass(frozen=True)
class Move:
    """One tenant the rebalance must migrate: ``src`` currently holds it,
    ``dst`` owns it under the new placement. ``src`` is ``None`` for a
    tenant whose current host is gone (a failover adoption, not a live
    migration — there is nothing to drain)."""

    tenant_id: Hashable
    src: Optional[str]
    dst: str


def rebalance_plan(
    assignment: Mapping[Hashable, str], hosts: Mapping[str, float]
) -> List[Move]:
    """The minimal move set from the current ``assignment`` (tenant → host)
    to the rendezvous placement under ``hosts``.

    Rendezvous hashing guarantees minimality by construction: a tenant moves
    only if its argmax host changed, so the plan after a join is the new
    host's fair share and after a leave exactly the lost host's roster.
    Moves sort by ``(dst, repr(tenant))`` — a deterministic migration order
    for the soak's determinism contract."""
    moves = [
        Move(tenant_id=tid, src=cur if cur in hosts else None, dst=want)
        for tid, cur in assignment.items()
        for want in (place(tid, hosts),)
        if want != cur
    ]
    moves.sort(key=lambda m: (m.dst, repr(m.tenant_id)))
    return moves
