"""Fleet failover plane: multi-host tenant placement, live migration, and
host-loss recovery over N serving engines (``docs/fleet.md``).

- :mod:`~torchmetrics_tpu.fleet.placement` — deterministic weighted
  rendezvous-hash tenant→host map and the minimal-move rebalance planner;
- :mod:`~torchmetrics_tpu.fleet.membership` — lease/heartbeat liveness on
  the injectable virtual clock (alive → suspect → dead);
- :mod:`~torchmetrics_tpu.fleet.controller` — the routing surface:
  ``serve`` by placement, ``migrate`` with the drain → snapshot-slice →
  transfer → restore → cutover protocol, and lease-expiry failover from
  each host's snapshot generation + journal tail.
"""

from .controller import (
    MIGRATION_STAGES,
    FleetController,
    MigrationAborted,
    active_controller,
    tenant_state_digest,
)
from .membership import LEASE_STATES, LeaseConfig, Member, Membership
from .placement import Move, place, place_all, placement_score, rebalance_plan

__all__ = [
    "MIGRATION_STAGES",
    "LEASE_STATES",
    "FleetController",
    "MigrationAborted",
    "active_controller",
    "LeaseConfig",
    "Member",
    "Membership",
    "Move",
    "place",
    "place_all",
    "placement_score",
    "rebalance_plan",
    "tenant_state_digest",
]
