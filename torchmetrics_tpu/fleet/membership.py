"""Lease/heartbeat membership on the injectable virtual clock.

Each member host holds a lease it renews by heartbeating. Liveness is a
pure function of ``clock() - last_heartbeat`` against two thresholds, so a
host is in exactly one of three states:

- **alive** — heartbeat within ``suspect_after`` seconds;
- **suspect** — silent past ``suspect_after`` but inside ``dead_after``:
  the host keeps its tenants (routing is NOT disturbed — a suspect that
  revives must cause no spurious failover);
- **dead** — silent past ``dead_after``: the lease expired. :meth:`expire`
  reports the transition exactly once and the controller adopts the dead
  host's tenants from its durable state.

The clock is injected (``ServingConfig(clock=)`` discipline), so the chaos
soak drives expiry deterministically in virtual seconds — no wall-clock in
the membership verdicts.

Epoch bookkeeping mirrors ``parallel/coalesce`` v8 rank liveness: every
member carries a liveness epoch, bumped when a host rejoins after its lease
expired. A peer can therefore tell a rejoin (same id, higher epoch — fold
its state exactly once, the ``rank_rejoin`` discipline) from a host that
never died (same epoch).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

from ..utilities.exceptions import TorchMetricsUserError

__all__ = ["LEASE_STATES", "LeaseConfig", "Member", "Membership"]

LEASE_STATES = ("alive", "suspect", "dead")


@dataclasses.dataclass(frozen=True)
class LeaseConfig:
    """Liveness thresholds in (virtual) seconds.

    Args:
        heartbeat_interval: the cadence hosts are expected to renew at —
            advisory (the controller heartbeats on its traffic steps), but
            the thresholds should be comfortable multiples of it.
        suspect_after: silence before a host turns suspect (routing
            undisturbed; the flap window).
        dead_after: silence before the lease expires and survivors adopt
            the host's tenants. Must exceed ``suspect_after``: the suspect
            state exists so a flapping host can revive WITHOUT a failover.
    """

    heartbeat_interval: float = 1.0
    suspect_after: float = 3.0
    dead_after: float = 6.0

    def __post_init__(self) -> None:
        if not self.heartbeat_interval > 0:
            raise ValueError(
                f"heartbeat_interval must be > 0, got {self.heartbeat_interval}"
            )
        if not self.suspect_after > 0:
            raise ValueError(f"suspect_after must be > 0, got {self.suspect_after}")
        if not self.dead_after > self.suspect_after:
            raise ValueError(
                f"dead_after ({self.dead_after}) must exceed suspect_after "
                f"({self.suspect_after}) — without a suspect window every "
                "missed heartbeat would be a failover"
            )


@dataclasses.dataclass
class Member:
    """One host's lease bookkeeping."""

    host_id: str
    weight: float = 1.0
    last_heartbeat: float = 0.0
    epoch: int = 1  # liveness epoch — bumps on rejoin-after-expiry
    heartbeats: int = 0
    expired: bool = False  # lease expiry already reported by expire()


class Membership:
    """The fleet's lease table. All verdicts derive from the injected clock;
    nothing here touches wall-clock or threads."""

    def __init__(
        self,
        clock: Callable[[], float],
        config: Optional[LeaseConfig] = None,
    ) -> None:
        if not callable(clock):
            raise TorchMetricsUserError(
                f"clock must be a zero-arg callable returning seconds, got {clock!r}"
            )
        self.clock = clock
        self.config = config if config is not None else LeaseConfig()
        self._members: Dict[str, Member] = {}

    # ------------------------------------------------------------- lifecycle

    def join(self, host_id: str, weight: float = 1.0) -> Member:
        """Register a host (or re-register one whose lease expired — that is
        a REJOIN and bumps its liveness epoch, the coalesce-v8 discipline
        that lets peers fold a rejoiner exactly once)."""
        if not weight > 0:
            raise TorchMetricsUserError(f"host weight must be > 0, got {weight}")
        m = self._members.get(host_id)
        if m is None:
            m = Member(host_id=host_id, weight=float(weight), last_heartbeat=self.clock())
            self._members[host_id] = m
        else:
            if self.state(host_id) == "dead":
                m.epoch += 1  # rejoin after expiry — a NEW incarnation
            m.weight = float(weight)
            m.last_heartbeat = self.clock()
            m.expired = False
        return m

    def leave(self, host_id: str) -> None:
        """Graceful departure: the host is removed without an expiry (its
        tenants migrate out first — the controller's job, not ours)."""
        self._members.pop(host_id, None)

    def heartbeat(self, host_id: str) -> None:
        """Renew one host's lease. Heartbeats from a host whose lease
        ALREADY expired are ignored — it must :meth:`join` again (rejoin
        epoch bump), never silently resurrect."""
        m = self._members.get(host_id)
        if m is None:
            raise TorchMetricsUserError(f"unknown host {host_id!r} (join first)")
        if self.state(host_id) == "dead":
            return
        m.last_heartbeat = self.clock()
        m.heartbeats += 1

    # --------------------------------------------------------------- queries

    def state(self, host_id: str) -> str:
        """``"alive"`` / ``"suspect"`` / ``"dead"`` for one host, computed
        from the clock (never cached — a revived clock revives the host as
        long as the lease has not expired)."""
        m = self._members.get(host_id)
        if m is None:
            raise TorchMetricsUserError(f"unknown host {host_id!r}")
        if m.expired:
            return "dead"  # expiry is terminal until an explicit rejoin
        silence = self.clock() - m.last_heartbeat
        if silence >= self.config.dead_after:
            return "dead"
        if silence >= self.config.suspect_after:
            return "suspect"
        return "alive"

    def members(self) -> Dict[str, Member]:
        return dict(self._members)

    def hosts(self, states: tuple = ("alive", "suspect")) -> Dict[str, float]:
        """Host → weight map for placement. Default includes suspects: a
        suspect keeps its tenants until its lease actually expires, so
        routing must keep targeting it (no spurious failover)."""
        return {
            h: m.weight for h, m in sorted(self._members.items())
            if self.state(h) in states
        }

    def expire(self) -> List[str]:
        """Report leases that expired since the last call (each host exactly
        once, in sorted order — the controller's failover trigger)."""
        out: List[str] = []
        for h in sorted(self._members):
            m = self._members[h]
            if not m.expired and self.state(h) == "dead":
                m.expired = True
                out.append(h)
        return out
