"""The fleet controller: N serving engines behind one routing surface.

Each member host is a full :class:`~torchmetrics_tpu.serving.ServingEngine`
with its own durability plane (``<root>/<host>/journal`` write-ahead journal,
``<root>/<host>/snapshots`` generation store) — the simulated multi-host
world pattern the replay-world sync tests use, one process, N engines.
The controller owns three verbs:

- :meth:`FleetController.serve` routes ``(tenant_id, batch)`` by weighted
  rendezvous placement over the live membership and journals on the owning
  host. Traffic for a host that died but whose lease has not yet expired
  parks in arrival order and replays to the adopting host after failover —
  no admitted batch is dropped in the suspicion window.

- :meth:`FleetController.migrate` moves tenants host-to-host with a
  drain → snapshot-slice → transfer → restore → cutover protocol. Ownership
  flips only at the single commit point: any failure before it aborts
  cleanly (partial destination state scrubbed, transfer artifacts deleted,
  the source still authoritative), so a kill at ANY stage leaves every
  tenant whole on exactly one host. A torn transfer artifact is caught by
  the snapshot container's sha256 at restore and aborts the same way.

- lease expiry (:meth:`FleetController.poll`) triggers failover: survivors
  adopt the dead host's tenants by restoring its latest snapshot
  generation, replaying its journal tail (exactly-once via the engine's
  seq cursors), and seating each tenant on its new rendezvous owner. The
  reconstruction is bitwise (restore + replay → pre-crash state); RPO is
  bounded by the journal fsync window (0 records at ``fsync_every=1``).

Durability barrier: every committed migration and every failover adoption
snapshots the hosts it touched, so "latest snapshot + own journal tail"
stays a complete recovery recipe on every host — a later crash can neither
resurrect a migrated-away tenant nor lose an adopted one.
"""

from __future__ import annotations

import dataclasses
import os
import time
import weakref
from typing import Any, Callable, Dict, Hashable, Iterable, List, Optional, Tuple

import numpy as np

from .. import observability as _observability
from ..observability import spans as _spans
from ..serving import ServingConfig, ServingEngine
from ..serving import durability as _durability
from ..utilities.exceptions import StateCorruptionError, TorchMetricsUserError
from .membership import LeaseConfig, Membership
from .placement import Move, place, rebalance_plan

__all__ = [
    "MIGRATION_STAGES",
    "MigrationAborted",
    "FleetController",
    "active_controller",
    "tenant_state_digest",
]

# the most recently constructed live controller (weak — close() clears it);
# the health plane's /fleetz endpoint and the flight recorder's seating
# snapshot answer from here without holding the fleet alive
_ACTIVE_CONTROLLER: Optional["weakref.ReferenceType[FleetController]"] = None


def active_controller() -> Optional["FleetController"]:
    """The live :class:`FleetController`, if one exists (else ``None``)."""
    ref = _ACTIVE_CONTROLLER
    if ref is None:
        return None
    return ref()

# the migrate protocol's stages, in order; the post-stage hook fires after
# each stage's effect lands (kill-point fuzz drives every boundary)
MIGRATION_STAGES = ("drain", "snapshot", "transfer", "restore", "cutover")


class MigrationAborted(TorchMetricsUserError):
    """A migration failed before its commit point and was rolled back: the
    source host still owns every tenant, the destination holds nothing.
    ``__cause__`` carries the original failure."""


def tenant_state_digest(engine: ServingEngine, tenant_id: Hashable) -> str:
    """Canonical digest of ONE tenant's state on ``engine`` — every state
    leaf's dtype/shape/bytes plus the update count, the per-tenant unit of
    the fleet parity gates (host-independent: two hosts holding bitwise the
    same tenant produce the same digest)."""
    import hashlib

    sd = engine.state_dict(tenant_id)
    h = hashlib.sha256()
    h.update(str(int(sd.get("_update_count", 0))).encode("utf-8"))
    for name in sorted(sd):
        if name.startswith("_"):
            continue
        arr = np.asarray(sd[name])
        h.update(name.encode("utf-8"))
        h.update(str(arr.dtype).encode("utf-8"))
        h.update(str(arr.shape).encode("utf-8"))
        h.update(arr.tobytes())
    return h.hexdigest()


class _Host:
    """One member host: its engine, durability directories, and the retained
    admitted batches its journal records refer to (the replay fetch source,
    pruned at every snapshot — the soak's retention discipline)."""

    __slots__ = ("host_id", "engine", "journal_dir", "snap_dir", "outbox_dir",
                 "inbox_dir", "retained", "killed", "pre_kill_seq", "kill_trace")

    def __init__(self, host_id: str, engine: ServingEngine, root: str) -> None:
        self.host_id = host_id
        self.engine = engine
        self.journal_dir = os.path.join(root, host_id, "journal")
        self.snap_dir = os.path.join(root, host_id, "snapshots")
        self.outbox_dir = os.path.join(root, host_id, "outbox")
        self.inbox_dir = os.path.join(root, host_id, "inbox")
        self.retained: Dict[int, Tuple[tuple, dict]] = {}
        self.killed = False
        self.pre_kill_seq = 0
        # the span active at kill time (the fault-ledger trace) — the later
        # failover chains its adoption spans off this, linking cause to effect
        self.kill_trace: Optional[_spans.SpanContext] = None


class FleetController:
    """Route, migrate, and fail over tenants across N member engines.

    Args:
        metric_factory: zero-arg callable building one metric template per
            host engine (every host must serve the same template — restore
            and migration require identical engine geometry).
        root: fleet durability root; each host gets ``<root>/<host_id>/``.
        hosts: initial host ids (an int ``n`` means ``host-0 .. host-n-1``).
        serving: per-host :class:`ServingConfig` template; ``journal`` and
            ``clock`` are overridden per host / by the fleet clock.
        lease: the membership thresholds.
        clock: injectable virtual clock shared by admission and leases
            (defaults to ``time.monotonic``).
    """

    def __init__(
        self,
        metric_factory: Callable[[], Any],
        root: str,
        hosts: Any = 3,
        serving: Optional[ServingConfig] = None,
        lease: Optional[LeaseConfig] = None,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        if isinstance(hosts, int):
            if hosts < 1:
                raise TorchMetricsUserError(f"need at least one host, got {hosts}")
            hosts = [f"host-{i}" for i in range(hosts)]
        self._metric_factory = metric_factory
        self.root = str(root)
        self.clock = clock if clock is not None else time.monotonic
        self.serving = serving if serving is not None else ServingConfig()
        self.membership = Membership(self.clock, lease)
        self._hosts: Dict[str, _Host] = {}
        self._owner: Dict[Hashable, str] = {}
        # traffic addressed to a killed-but-not-yet-expired host, in arrival
        # order — redelivered to the adopting host after failover
        self._parked: List[Tuple[Hashable, tuple, dict]] = []
        self.stats: Dict[str, Any] = {
            "served": 0, "parked": 0, "replayed_parked": 0,
            "migrations": 0, "migrated_tenants": 0, "migration_parity_failures": 0,
            "aborted_migrations": 0, "failovers": 0, "adopted_tenants": 0,
            "failover_replayed": 0, "rpo_records": 0, "lease_expiries": 0,
            "dropped_quarantined_adoptions": 0,
        }
        self._serve_seq = 0  # request-span sequence (telemetry-only, deterministic)
        for h in hosts:
            self.add_host(str(h), rebalance=False)
        global _ACTIVE_CONTROLLER
        _ACTIVE_CONTROLLER = weakref.ref(self)

    # --------------------------------------------------------------- hosts

    def _engine_config(self, host_id: str) -> ServingConfig:
        return dataclasses.replace(
            self.serving,
            journal=os.path.join(self.root, host_id, "journal"),
            clock=self.clock,
        )

    def hosts(self) -> Dict[str, str]:
        """host id → lease state for every registered (non-dead) host."""
        return {h: self.membership.state(h) for h in sorted(self._hosts)}

    def add_host(self, host_id: str, weight: float = 1.0, rebalance: bool = True) -> List[Move]:
        """Bring up a member host (join). With ``rebalance`` (the default
        for late joins) the rendezvous fair share of existing tenants
        migrates onto it — the minimal move set, nothing else relocates."""
        if host_id in self._hosts:
            raise TorchMetricsUserError(f"host {host_id!r} already in the fleet")
        engine = ServingEngine(self._metric_factory(), self._engine_config(host_id))
        self._hosts[host_id] = _Host(host_id, engine, self.root)
        self.membership.join(host_id, weight)
        if not rebalance or not self._owner:
            return []
        plan = rebalance_plan(self._owner, self.membership.hosts())
        by_src: Dict[str, List[Hashable]] = {}
        for m in plan:
            if m.src is not None:
                by_src.setdefault(m.src, []).append(m.tenant_id)
        for src in sorted(by_src):
            self.migrate(by_src[src], host_id)
        return plan

    def kill_host(self, host_id: str) -> None:
        """Simulate a host crash: the journal tears at its last fsync (the
        real loss window), the engine stops serving, heartbeats stop. The
        lease runs to expiry — failover happens at :meth:`poll` after
        ``dead_after``, not here (the suspicion window is the point)."""
        h = self._require_host(host_id)
        if h.killed:
            return
        h.pre_kill_seq = int(h.engine._applied_seq)
        if h.engine._journal is not None:
            h.engine._journal.crash()
        h.killed = True
        if _observability._ACTIVE is not None:
            h.kill_trace = _spans.current()

    def heartbeat_all(self) -> None:
        """One heartbeat round: every non-killed host renews its lease."""
        rec = _observability._ACTIVE
        for host_id in sorted(self._hosts):
            if not self._hosts[host_id].killed:
                self.membership.heartbeat(host_id)
                if rec is not None:
                    rec.record_fleet_heartbeat(host_id)

    def poll(self) -> List[str]:
        """Check leases; fail over every host whose lease expired since the
        last poll. Returns the hosts failed over (the soak's resolution
        signal for ``host_loss``)."""
        expired = self.membership.expire()
        rec = _observability._ACTIVE
        for host_id in expired:
            self.stats["lease_expiries"] += 1
            if rec is not None:
                rec.record_lease_expiry(host_id)
            self._failover(host_id)
        return expired

    def _require_host(self, host_id: str) -> _Host:
        h = self._hosts.get(host_id)
        if h is None:
            raise TorchMetricsUserError(f"unknown host {host_id!r}")
        return h

    # --------------------------------------------------------------- serve

    def owner(self, tenant_id: Hashable) -> str:
        """The host currently seating ``tenant_id`` (placing it now if it
        has never been seen)."""
        host = self._owner.get(tenant_id)
        if host is None:
            host = place(tenant_id, self.membership.hosts())
            self._owner[tenant_id] = host
        return host

    def serve(self, tenant_id: Hashable, *args: Any, **kwargs: Any) -> bool:
        """Route one batch to its owner and fold it (journal-first on the
        owning host). Returns the engine's admission verdict; batches for a
        crashed-but-unexpired owner park and count as admitted (they replay
        to the adopting host — the suspicion window drops nothing)."""
        ctx = None
        if _observability._ACTIVE is not None:
            # request span: everything the routed batch triggers (admission,
            # journal append, the megabatch dispatch it seats into) links
            # back to this deterministic per-request trace
            self._serve_seq += 1
            ctx = _spans.enter("serve", repr(tenant_id), self._serve_seq)
        try:
            host = self.owner(tenant_id)
            h = self._hosts[host]
            if h.killed:
                # the owner is down but its lease has not expired: hold the
                # batch (arrival order) until failover reseats the tenant
                self._parked.append((tenant_id, args, dict(kwargs)))
                self.stats["parked"] += 1
                return True
            ok = h.engine.update(tenant_id, *args, **kwargs)
            if ok:
                self.stats["served"] += 1
                if h.engine._journal is not None:
                    h.retained[h.engine._applied_seq] = (args, dict(kwargs))
            return ok
        finally:
            if ctx is not None:
                _spans.exit(ctx)

    def _drain_parked(self) -> None:
        """Redeliver parked traffic whose tenant has a live owner again."""
        parked, self._parked = self._parked, []
        for tenant_id, args, kwargs in parked:
            host = self.owner(tenant_id)
            if self._hosts[host].killed:
                self._parked.append((tenant_id, args, kwargs))
                continue
            self.stats["replayed_parked"] += 1
            ok = self._hosts[host].engine.update(tenant_id, *args, **kwargs)
            if ok:
                self.stats["served"] += 1
                eng = self._hosts[host].engine
                if eng._journal is not None:
                    self._hosts[host].retained[eng._applied_seq] = (args, kwargs)

    # ----------------------------------------------------------- durability

    def snapshot_host(self, host_id: str) -> Dict[str, Any]:
        """Snapshot one host and prune its retained-batch buffer to the new
        cursor (everything the snapshot covers never replays)."""
        h = self._require_host(host_id)
        info = h.engine.snapshot(h.snap_dir)
        cutoff = int(h.engine._applied_seq)
        for seq in [s for s in h.retained if s <= cutoff]:
            del h.retained[seq]
        return info

    def snapshot_all(self) -> Dict[str, Dict[str, Any]]:
        return {
            host_id: self.snapshot_host(host_id)
            for host_id in sorted(self._hosts)
            if not self._hosts[host_id].killed
        }

    # ------------------------------------------------------------- failover

    def _failover(self, host_id: str) -> None:
        """Survivors adopt a dead host's tenants: restore its latest
        snapshot generation into a recovery engine, replay its journal tail
        (exactly-once seq cursors), then seat each tenant on its new
        rendezvous owner and snapshot the adopters (durability barrier)."""
        h = self._hosts.pop(host_id)
        survivors = self.membership.hosts()
        if not survivors or all(self._hosts[s].killed for s in survivors):
            self._hosts[host_id] = h  # put it back: nothing can adopt
            raise TorchMetricsUserError(
                f"host {host_id!r} expired but no live host remains to adopt its tenants"
            )
        survivors = {s: w for s, w in survivors.items() if not self._hosts[s].killed}
        rec = _observability._ACTIVE
        ctx = None
        if rec is not None:
            # child of the kill-time span when one was recorded: the fault-
            # ledger trace id flows through restore/replay/adoption events
            ctx = _spans.enter("failover", host_id, parent=h.kill_trace)
        try:
            self._failover_adopt(host_id, h, survivors, rec)
        finally:
            if ctx is not None:
                _spans.exit(ctx)
        self._drain_parked()

    def _failover_adopt(
        self, host_id: str, h: _Host, survivors: Dict[str, float],
        rec: Optional[Any],
    ) -> None:
        # bitwise reconstruction: latest snapshot + journal tail
        recovery = ServingEngine(
            self._metric_factory(),
            dataclasses.replace(self.serving, journal=None, clock=self.clock),
        )
        if _durability.SnapshotStore(h.snap_dir).generations():
            recovery.restore(h.snap_dir)
        records = _durability.TrafficJournal.read(h.journal_dir)
        replayed = recovery.replay_journal(records, lambda r: h.retained[r.seq])
        recovery.flush()
        rpo = max(0, h.pre_kill_seq - int(recovery._applied_seq))
        # adoption: every tenant moves to its new rendezvous owner
        roster = recovery.tenants()
        adopted = 0
        adopted_ids: List[str] = []
        touched: List[str] = []
        for tenant_id in sorted(roster, key=repr):
            if roster[tenant_id]["quarantined"]:
                # a quarantined tenant's state is frozen garbage by contract —
                # adopting it would launder a contained fault into a clean host
                self._owner.pop(tenant_id, None)
                self.stats["dropped_quarantined_adoptions"] += 1
                continue
            dst = place(tenant_id, survivors)
            self._hosts[dst].engine.load_state_dict(
                tenant_id, recovery.state_dict(tenant_id)
            )
            self._owner[tenant_id] = dst
            adopted += 1
            adopted_ids.append(repr(tenant_id))
            if dst not in touched:
                touched.append(dst)
        for dst in touched:
            self.snapshot_host(dst)
        # a tenant routed to the dead host but never durably folded (first
        # seen inside the suspicion window, batches all parked) has no state
        # to adopt — drop its stale route so the next serve re-places it
        for tenant_id in [t for t, owner in self._owner.items() if owner == host_id]:
            del self._owner[tenant_id]
        self.stats["failovers"] += 1
        self.stats["adopted_tenants"] += adopted
        self.stats["failover_replayed"] += replayed
        self.stats["rpo_records"] = max(self.stats["rpo_records"], rpo)
        if rec is not None:
            rec.record_host_failover(
                host_id, host_id, adopted, replayed, rpo, roster=adopted_ids,
            )

    # ------------------------------------------------------------ migration

    def migrate(
        self,
        tenants: Iterable[Hashable],
        dst: str,
        _stage_hook: Optional[Callable[[str], None]] = None,
    ) -> Dict[str, Any]:
        """Move ``tenants`` onto host ``dst`` with the staged protocol.

        ``_stage_hook(stage)`` fires after each stage's effect (test seam —
        the kill-point fuzz raises or tears the transfer artifact here).
        Any failure before the cutover commit rolls back completely and
        raises :class:`MigrationAborted`; after the commit the migration is
        final. Returns ``{"moved", "src_hosts", "parity_failures"}``."""
        hook = _stage_hook if _stage_hook is not None else (lambda stage: None)
        dst_h = self._require_host(dst)
        if dst_h.killed:
            raise TorchMetricsUserError(f"cannot migrate onto dead host {dst!r}")
        tenants = list(tenants)
        by_src: Dict[str, List[Hashable]] = {}
        for tid in tenants:
            src = self._owner.get(tid)
            if src is None:
                raise TorchMetricsUserError(f"unknown tenant {tid!r}")
            if src == dst:
                continue
            if self._hosts[src].killed:
                raise TorchMetricsUserError(
                    f"tenant {tid!r} lives on dead host {src!r} — failover, not migration"
                )
            by_src.setdefault(src, []).append(tid)
        t0 = time.perf_counter()
        moved = 0
        parity_failures = 0
        ctx = None
        if _observability._ACTIVE is not None:
            ctx = _spans.enter("migration", ",".join(sorted(by_src)), dst, len(tenants))
        try:
            for src in sorted(by_src):
                moved_n, bad = self._migrate_group(src, by_src[src], dst, hook)
                moved += moved_n
                parity_failures += bad
            duration = time.perf_counter() - t0
            if moved:
                self.stats["migrations"] += 1
                self.stats["migrated_tenants"] += moved
                self.stats["migration_parity_failures"] += parity_failures
                rec = _observability._ACTIVE
                if rec is not None:
                    rec.record_migration(
                        "fleet", ",".join(sorted(by_src)), dst, moved, duration
                    )
        finally:
            if ctx is not None:
                _spans.exit(ctx)
        return {"moved": moved, "src_hosts": sorted(by_src), "parity_failures": parity_failures}

    def _migrate_group(
        self,
        src: str,
        tids: List[Hashable],
        dst: str,
        hook: Callable[[str], None],
    ) -> Tuple[int, int]:
        src_h = self._hosts[src]
        dst_h = self._hosts[dst]
        outbox_path: Optional[str] = None
        inbox_path: Optional[str] = None
        generation: Optional[int] = None
        restored: List[Hashable] = []
        # per-stage child spans of the ambient migration span: events a stage
        # triggers (snapshots, dispatches) attribute to THEIR stage boundary
        stage_ctx: List[Optional[_spans.SpanContext]] = [None]

        def _stage_enter(name: str) -> None:
            if _observability._ACTIVE is not None:
                stage_ctx[0] = _spans.enter("migrate_stage", src, dst, name)

        def _stage_exit() -> None:
            if stage_ctx[0] is not None:
                _spans.exit(stage_ctx[0])
                stage_ctx[0] = None

        try:
            # 1. drain: queued megabatches land on src (their admissions are
            # already journaled — nothing new can be lost past this point)
            _stage_enter("drain")
            src_h.engine.flush()
            hook("drain")
            _stage_exit()
            # 2. snapshot-slice: the tenants' exact state rows, published as
            # one atomic sha256-sealed artifact in src's outbox
            _stage_enter("snapshot")
            slices = {tid: src_h.engine.state_dict(tid) for tid in tids}
            pre_digests = {tid: tenant_state_digest(src_h.engine, tid) for tid in tids}
            sections: Dict[str, np.ndarray] = {}
            entries: List[Dict[str, Any]] = []
            for i, tid in enumerate(tids):
                sd = slices[tid]
                entries.append({
                    "id": _durability.encode_tenant_id(tid),
                    "update_count": int(sd.get("_update_count", 0)),
                    "keys": sorted(k for k in sd if not k.startswith("_")),
                })
                for name in entries[-1]["keys"]:
                    sections[f"t{i}/{name}"] = np.asarray(sd[name])
            outbox = _durability.SnapshotStore(src_h.outbox_dir)
            info = outbox.write({"src": src, "dst": dst, "tenants": entries}, sections)
            outbox_path, generation = info["path"], info["generation"]
            hook("snapshot")
            _stage_exit()
            _stage_enter("transfer")
            # 3. transfer: ship the artifact bytes to dst's inbox (the
            # simulated network copy — a kill here leaves at worst a torn
            # file that restore's sha256 check rejects)
            os.makedirs(dst_h.inbox_dir, exist_ok=True)
            inbox_path = os.path.join(dst_h.inbox_dir, os.path.basename(outbox_path))
            with open(outbox_path, "rb") as fh:
                payload = fh.read()
            with open(inbox_path, "wb") as fh:
                fh.write(payload)
            hook("transfer")
            _stage_exit()
            _stage_enter("restore")
            # 4. restore: decode the artifact ON DST (sha256-verified — a
            # torn transfer dies here, not after cutover) and park each
            # tenant's state on the destination engine
            meta, rx_sections = _durability.SnapshotStore(dst_h.inbox_dir).read(generation)
            for i, entry in enumerate(meta["tenants"]):
                tid = _durability.decode_tenant_id(entry["id"])
                sd: Dict[str, Any] = {
                    name: np.asarray(rx_sections[f"t{i}/{name}"]) for name in entry["keys"]
                }
                sd["_update_count"] = int(entry["update_count"])
                dst_h.engine.load_state_dict(tid, sd)
                restored.append(tid)
            hook("restore")
            _stage_exit()
        except BaseException as err:
            # ---- abort: ownership never flipped; scrub every partial effect
            _stage_exit()
            self.stats["aborted_migrations"] += 1
            for tid in restored:
                try:
                    dst_h.engine.forget(tid)
                except Exception:  # noqa: BLE001 — best-effort scrub
                    pass
            for path in (inbox_path, outbox_path):
                if path is not None and os.path.exists(path):
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
            raise MigrationAborted(
                f"migration {src!r} -> {dst!r} aborted before cutover "
                f"({len(tids)} tenants stay on {src!r}): {err}"
            ) from err
        # ---- 5. cutover: THE commit point. Ownership flips, the source
        # forgets, artifacts are swept, and both hosts snapshot so their own
        # "latest snapshot + journal tail" recipes stay complete. A kill
        # from here on is post-commit: the destination owns every tenant.
        _stage_enter("cutover")
        parity_failures = 0
        for tid in tids:
            if tenant_state_digest(dst_h.engine, tid) != pre_digests[tid]:
                parity_failures += 1
            self._owner[tid] = dst
            src_h.engine.forget(tid)
        for path in (inbox_path, outbox_path):
            if path is not None and os.path.exists(path):
                try:
                    os.unlink(path)
                except OSError:
                    pass
        self.snapshot_host(src)
        self.snapshot_host(dst)
        hook("cutover")
        _stage_exit()
        return len(tids), parity_failures

    # ------------------------------------------------------------- read side

    def compute(self, tenant_id: Hashable) -> Any:
        host = self._owner.get(tenant_id)
        if host is None:
            raise TorchMetricsUserError(f"unknown tenant {tenant_id!r}")
        return self._hosts[host].engine.compute(tenant_id)

    def tenants(self) -> Dict[Hashable, str]:
        """tenant → owning host (the routing table)."""
        return dict(self._owner)

    def engines(self) -> Dict[str, ServingEngine]:
        """host id → live engine (killed hosts excluded) — the read seam the
        control tower and the flight recorder's seating snapshot use."""
        return {
            host_id: h.engine
            for host_id, h in sorted(self._hosts.items())
            if not h.killed
        }

    # the control tower's per-host engine-stat → fleet-counter-field mapping
    # (one shared recorder serves every engine in this process, so per-host
    # attribution must come from each engine's own stats, not the counters)
    _STATS_COUNTER_MAP: Tuple[Tuple[str, str], ...] = (
        ("serve_dispatches", "dispatches"),
        ("serve_tenant_rows", "tenant_rows"),
        ("serve_padded_rows", "padded_rows"),
        ("tenant_spills", "spills"),
        ("tenant_readmits", "readmissions"),
        ("quarantines", "quarantined"),
        ("serve_rejected", "rejected_batches"),
        ("window_rotations", "window_rotations"),
    )

    def telemetry(self, top_k: int = 5) -> Dict[str, Any]:
        """The fleet control tower: one rollup of per-host counters (merged
        through :func:`aggregate_counters`), per-kind latency histograms,
        top-``top_k`` hot tenants (by folded rows, with spill/quarantine
        flags), lease states, and the controller's own lifecycle stats.
        This is what ``/fleetz`` serves and ``serve_demo --fleet`` prints."""
        from ..observability.counters import aggregate_counters

        live = self.engines()
        per_host: Dict[str, Dict[str, int]] = {
            host_id: {
                field: int(engine.stats.get(stat, 0))
                for field, stat in self._STATS_COUNTER_MAP
            }
            for host_id, engine in live.items()
        }
        hosts_sorted = sorted(per_host)
        totals: Dict[str, int] = {field: 0 for field, _ in self._STATS_COUNTER_MAP}
        if per_host:
            merged = aggregate_counters([per_host[h] for h in hosts_sorted])
            totals = {
                field: int(merged.totals.get(field, 0))
                for field, _ in self._STATS_COUNTER_MAP
            }
        hot: List[Dict[str, Any]] = []
        for host_id, engine in live.items():
            for tid, info in engine.tenants().items():
                hot.append({
                    "tenant": repr(tid)[:80],
                    "host": host_id,
                    "rows": int(info["update_count"]),
                    "spilled": bool(info["spilled"]),
                    "quarantined": bool(info["quarantined"]),
                })
        hot.sort(key=lambda r: (-r["rows"], r["tenant"], r["host"]))
        out: Dict[str, Any] = {
            "hosts": per_host,
            "totals": totals,
            "hot_tenants": hot[:max(0, int(top_k))],
            "tenant_count": len(hot),
            "membership": self.hosts(),
            "parked": len(self._parked),
            "stats": dict(self.stats),
        }
        rec = _observability._ACTIVE
        if rec is not None:
            out["latency"] = rec.latency_summary()
            if rec.history is not None:
                # the fleet sim shares one recorder per process, so this IS the
                # fleet-wide history: retained level boundaries ride the tower
                out["history"] = rec.history.levels()
        return out

    def tenant_digests(self) -> Dict[Hashable, str]:
        """Per-tenant state digests across the whole fleet (the parity
        oracle: compare against a single-host reference run)."""
        for h in self._hosts.values():
            if not h.killed:
                h.engine.flush()
        out: Dict[Hashable, str] = {}
        for tid, host in self._owner.items():
            h = self._hosts.get(host)
            if h is not None and not h.killed:
                out[tid] = tenant_state_digest(h.engine, tid)
        return out

    def flush(self) -> None:
        for h in self._hosts.values():
            if not h.killed:
                h.engine.flush()

    def close(self) -> None:
        global _ACTIVE_CONTROLLER
        for h in self._hosts.values():
            if not h.killed:
                h.engine.close()
        if _ACTIVE_CONTROLLER is not None and _ACTIVE_CONTROLLER() is self:
            _ACTIVE_CONTROLLER = None
