"""Shape tower — stateful metric classes (reference ``src/torchmetrics/shape/``)."""

from .procrustes import ProcrustesDisparity

__all__ = ["ProcrustesDisparity"]
