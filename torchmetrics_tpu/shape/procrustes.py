"""ProcrustesDisparity metric class (reference ``shape/procrustes.py:30``)."""

from __future__ import annotations

from typing import Any

import numpy as np
import jax.numpy as jnp

from ..functional.shape.procrustes import procrustes_disparity
from ..metric import Metric


class ProcrustesDisparity(Metric):
    """Running sum/mean of per-sample Procrustes disparity (two sum states).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.shape import ProcrustesDisparity
        >>> point_set1 = jnp.asarray([[[0.0, 0.0], [1.0, 0.0], [1.0, 1.0], [0.0, 1.0]]])
        >>> point_set2 = jnp.asarray([[[0.0, 0.0], [2.0, 0.0], [2.0, 2.0], [0.0, 2.0]]])
        >>> metric = ProcrustesDisparity()
        >>> metric.update(point_set1, point_set2)
        >>> metric.compute()
        Array(3.5527135e-15, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, reduction: str = "mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if reduction not in ("mean", "sum"):
            raise ValueError(f"Argument `reduction` must be one of ['mean', 'sum'], got {reduction}")
        self.reduction = reduction
        self.add_state("disparity", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros((), jnp.int32), dist_reduce_fx="sum")

    def _batch_state(self, point_cloud1, point_cloud2):
        disparity = procrustes_disparity(point_cloud1, point_cloud2)
        return {"disparity": disparity.sum(), "total": jnp.asarray(disparity.size, jnp.int32)}

    def _compute(self, state):
        if self.reduction == "mean":
            return state["disparity"] / state["total"]
        return state["disparity"]
