"""Audio metric classes (reference ``src/torchmetrics/audio/*.py``).

Every in-tree metric is a running mean over per-sample scores: two scalar sum states
(one psum each to sync). The SDR compute and the third-party-backed metrics run their
per-sample scores host-side (see ``functional/audio``), so those classes use the
HostMetric shell; the pure-jnp ones use the jitted path.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np
import jax.numpy as jnp

from ..functional.audio.external import (
    deep_noise_suppression_mean_opinion_score,
    non_intrusive_speech_quality_assessment,
    perceptual_evaluation_speech_quality,
    short_time_objective_intelligibility,
    speech_reverberation_modulation_energy_ratio,
)
from ..functional.audio.pit import permutation_invariant_training
from ..functional.audio.sdr import (
    scale_invariant_signal_distortion_ratio,
    signal_distortion_ratio,
    source_aggregated_signal_distortion_ratio,
)
from ..functional.audio.snr import (
    complex_scale_invariant_signal_noise_ratio,
    scale_invariant_signal_noise_ratio,
    signal_noise_ratio,
)
from ..metric import HostMetric, Metric


class _MeanAudioMetric(Metric):
    """Running mean of a per-sample jnp audio score."""

    full_state_update = False
    is_differentiable = True

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("score_sum", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros((), jnp.int32), dist_reduce_fx="sum")

    def _score(self, preds, target) -> jnp.ndarray:
        raise NotImplementedError

    def _batch_state(self, preds, target):
        score = self._score(preds, target)
        return {"score_sum": score.sum(), "total": jnp.asarray(score.size, jnp.int32)}

    def _compute(self, state):
        return state["score_sum"] / state["total"]


class SignalNoiseRatio(_MeanAudioMetric):
    """SNR (reference ``audio/snr.py:36``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.audio import SignalNoiseRatio
        >>> preds = jnp.asarray([2.8, -1.2, 0.06, 1.3])
        >>> target = jnp.asarray([3.0, -0.5, 0.1, 1.0])
        >>> metric = SignalNoiseRatio()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(12.176362, dtype=float32)
    """

    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean

    def _score(self, preds, target):
        return signal_noise_ratio(preds=preds, target=target, zero_mean=self.zero_mean)


class ScaleInvariantSignalNoiseRatio(_MeanAudioMetric):
    """SI-SNR (reference ``audio/snr.py:146``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.audio import ScaleInvariantSignalNoiseRatio
        >>> preds = jnp.asarray([2.8, -1.2, 0.06, 1.3])
        >>> target = jnp.asarray([3.0, -0.5, 0.1, 1.0])
        >>> metric = ScaleInvariantSignalNoiseRatio()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(12.534761, dtype=float32)
    """

    higher_is_better = True

    def _score(self, preds, target):
        return scale_invariant_signal_noise_ratio(preds=preds, target=target)


class ComplexScaleInvariantSignalNoiseRatio(_MeanAudioMetric):
    """C-SI-SNR (reference ``audio/snr.py:245``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.audio import ComplexScaleInvariantSignalNoiseRatio
        >>> preds = jnp.stack([jnp.sin(jnp.arange(48.0)).reshape(4, 12), jnp.cos(jnp.arange(48.0)).reshape(4, 12)], axis=-1)[None]
        >>> target = jnp.stack([jnp.cos(jnp.arange(48.0)).reshape(4, 12), jnp.sin(jnp.arange(48.0)).reshape(4, 12)], axis=-1)[None]
        >>> metric = ComplexScaleInvariantSignalNoiseRatio()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(-52.57505, dtype=float32)
    """

    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Expected argument `zero_mean` to be a bool, but got {zero_mean}")
        self.zero_mean = zero_mean

    def _score(self, preds, target):
        return complex_scale_invariant_signal_noise_ratio(preds=preds, target=target, zero_mean=self.zero_mean)


class ScaleInvariantSignalDistortionRatio(_MeanAudioMetric):
    """SI-SDR (reference ``audio/sdr.py:173``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.audio import ScaleInvariantSignalDistortionRatio
        >>> preds = jnp.asarray([2.8, -1.2, 0.06, 1.3])
        >>> target = jnp.asarray([3.0, -0.5, 0.1, 1.0])
        >>> metric = ScaleInvariantSignalDistortionRatio()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(12.216658, dtype=float32)
    """

    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean

    def _score(self, preds, target):
        return scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=self.zero_mean)


class SourceAggregatedSignalDistortionRatio(_MeanAudioMetric):
    """SA-SDR (reference ``audio/sdr.py:282``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.audio import SourceAggregatedSignalDistortionRatio
        >>> preds = jnp.stack([jnp.sin(jnp.arange(100.0) / 9), jnp.cos(jnp.arange(100.0) / 7)])[None]
        >>> target = jnp.stack([jnp.sin(jnp.arange(100.0) / 10), jnp.cos(jnp.arange(100.0) / 8)])[None]
        >>> metric = SourceAggregatedSignalDistortionRatio()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(-0.427748, dtype=float32)
    """

    higher_is_better = True

    def __init__(self, scale_invariant: bool = True, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(scale_invariant, bool):
            raise ValueError(f"Expected argument `scale_invariant` to be a bool, but got {scale_invariant}")
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Expected argument `zero_mean` to be a bool, but got {zero_mean}")
        self.scale_invariant = scale_invariant
        self.zero_mean = zero_mean

    def _score(self, preds, target):
        return source_aggregated_signal_distortion_ratio(
            preds=preds, target=target, scale_invariant=self.scale_invariant, zero_mean=self.zero_mean
        )


class _HostMeanAudioMetric(HostMetric):
    """Running mean of a per-sample host-computed audio score."""

    full_state_update = False
    is_differentiable = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("score_sum", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros((), jnp.int32), dist_reduce_fx="sum")

    def _score(self, preds, target=None) -> jnp.ndarray:
        raise NotImplementedError

    def _host_batch_state(self, preds, target=None):
        score = self._score(preds, target) if target is not None else self._score(preds)
        return {"score_sum": score.sum(), "total": jnp.asarray(score.size, jnp.int32)}

    def _compute(self, state):
        return state["score_sum"] / state["total"]


class SignalDistortionRatio(_HostMeanAudioMetric):
    """SDR (reference ``audio/sdr.py:38``) — per-sample Toeplitz solve on host.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.audio import SignalDistortionRatio
        >>> preds = jnp.sin(jnp.arange(800, dtype=jnp.float32) / 20)
        >>> target = jnp.sin(jnp.arange(800, dtype=jnp.float32) / 20 + 0.1)
        >>> metric = SignalDistortionRatio()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(32.214718, dtype=float32)
    """

    higher_is_better = True

    def __init__(
        self,
        use_cg_iter: Optional[int] = None,
        filter_length: int = 512,
        zero_mean: bool = False,
        load_diag: Optional[float] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.use_cg_iter = use_cg_iter
        self.filter_length = filter_length
        self.zero_mean = zero_mean
        self.load_diag = load_diag

    def _score(self, preds, target):
        return signal_distortion_ratio(
            preds, target, self.use_cg_iter, self.filter_length, self.zero_mean, self.load_diag
        )


class PermutationInvariantTraining(_HostMeanAudioMetric):
    """PIT (reference ``audio/pit.py:31``): mean of the best-permutation metric.

    Host-side update: the >3-speaker branch solves assignment with scipy, and user
    ``metric_func`` callables are not guaranteed jittable.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.audio import PermutationInvariantTraining
        >>> from torchmetrics_tpu.functional.audio import scale_invariant_signal_noise_ratio
        >>> preds = jnp.stack([jnp.sin(jnp.arange(100.0) / 9), jnp.cos(jnp.arange(100.0) / 7)])[None]
        >>> target = jnp.stack([jnp.cos(jnp.arange(100.0) / 8), jnp.sin(jnp.arange(100.0) / 10)])[None]
        >>> metric = PermutationInvariantTraining(scale_invariant_signal_noise_ratio, eval_func='max')
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(-0.18667257, dtype=float32)
    """

    higher_is_better = True

    def __init__(
        self,
        metric_func: Callable,
        mode: str = "speaker-wise",
        eval_func: str = "max",
        **kwargs: Any,
    ) -> None:
        base_kwargs = {
            k: kwargs.pop(k)
            for k in list(kwargs)
            if k
            in (
                "compute_on_cpu", "dist_sync_on_step", "process_group", "dist_sync_fn",
                "distributed_available_fn", "sync_on_compute", "compute_with_cache", "jit",
            )
        }
        super().__init__(**base_kwargs)
        if eval_func not in ("max", "min"):
            raise ValueError(f'eval_func can only be "max" or "min" but got {eval_func}')
        if mode not in ("speaker-wise", "permutation-wise"):
            raise ValueError(f'mode can only be "speaker-wise" or "permutation-wise" but got {mode}')
        self.metric_func = metric_func
        self.mode = mode
        self.eval_func = eval_func
        self.kwargs = kwargs

    def _score(self, preds, target):
        best_metric, _ = permutation_invariant_training(
            preds, target, self.metric_func, self.mode, self.eval_func, **self.kwargs
        )
        return best_metric

    def __hash__(self) -> int:
        return hash((self.__class__.__name__, id(self)))


class PerceptualEvaluationSpeechQuality(_HostMeanAudioMetric):
    """PESQ (reference ``audio/pesq.py:30``) — host callback into the pesq wheel."""

    higher_is_better = True
    plot_lower_bound = -0.5
    plot_upper_bound = 4.5

    def __init__(
        self, fs: int, mode: str, n_processes: int = 1, **kwargs: Any
    ) -> None:
        super().__init__(**kwargs)
        from ..functional.audio.external import _PESQ_AVAILABLE

        if not _PESQ_AVAILABLE:
            raise ModuleNotFoundError(
                "PESQ metric requires that pesq is installed."
                " Either install as `pip install torchmetrics[audio]` or `pip install pesq`."
            )
        if fs not in (8000, 16000):
            raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
        if mode not in ("wb", "nb"):
            raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
        self.fs = fs
        self.mode = mode
        self.n_processes = n_processes

    def _score(self, preds, target):
        return perceptual_evaluation_speech_quality(preds, target, self.fs, self.mode, n_processes=self.n_processes)


class ShortTimeObjectiveIntelligibility(_HostMeanAudioMetric):
    """STOI (reference ``audio/stoi.py:30``) — host callback into pystoi."""

    higher_is_better = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, fs: int, extended: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        from ..functional.audio.external import _PYSTOI_AVAILABLE

        if not _PYSTOI_AVAILABLE:
            raise ModuleNotFoundError(
                "STOI metric requires that `pystoi` is installed."
                " Either install as `pip install torchmetrics[audio]` or `pip install pystoi`."
            )
        self.fs = fs
        self.extended = extended

    def _score(self, preds, target):
        return short_time_objective_intelligibility(preds, target, self.fs, self.extended)


class SpeechReverberationModulationEnergyRatio(_HostMeanAudioMetric):
    """SRMR (reference ``audio/srmr.py:37``). The in-tree gammatone + modulation
    filterbank pipeline (``functional/audio/srmr.py``) needs no optional wheels —
    the reference requires ``gammatone`` + ``torchaudio`` for the same math."""

    higher_is_better = True

    def __init__(
        self,
        fs: int,
        n_cochlear_filters: int = 23,
        low_freq: float = 125,
        min_cf: float = 4,
        max_cf: Optional[float] = None,
        norm: bool = False,
        fast: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        from ..functional.audio.srmr import _srmr_arg_validate

        _srmr_arg_validate(fs, n_cochlear_filters, low_freq, min_cf, max_cf, norm, fast)
        self.fs = fs
        self.n_cochlear_filters = n_cochlear_filters
        self.low_freq = low_freq
        self.min_cf = min_cf
        self.max_cf = max_cf
        self.norm = norm
        self.fast = fast

    def _score(self, preds, target=None):
        return speech_reverberation_modulation_energy_ratio(
            preds, self.fs, self.n_cochlear_filters, self.low_freq, self.min_cf,
            self.max_cf, self.norm, self.fast,
        )


class DeepNoiseSuppressionMeanOpinionScore(_HostMeanAudioMetric):
    """DNSMOS (reference ``audio/dnsmos.py:36``). The melspec feature pipeline is
    in-tree numpy (``functional/audio/dnsmos.py``); only onnxruntime + the
    DNS-Challenge model files (or an injected ``infer_fns``) remain external."""

    higher_is_better = True

    def __init__(
        self,
        fs: int,
        personalized: bool,
        device: Optional[str] = None,
        num_threads: Optional[int] = None,
        cache_session: bool = True,
        infer_fns: Optional[Any] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        from ..functional.audio.dnsmos import _ONNXRUNTIME_AVAILABLE

        if infer_fns is None and not _ONNXRUNTIME_AVAILABLE:
            raise ModuleNotFoundError(
                "DNSMOS metric requires that onnxruntime is installed."
                " Install as `pip install onnxruntime`, or pass `infer_fns`."
            )
        self.fs = fs
        self.personalized = personalized
        self.num_threads = num_threads
        self.cache_session = cache_session
        self.infer_fns = infer_fns

    def _score(self, preds, target=None):
        return deep_noise_suppression_mean_opinion_score(
            preds, self.fs, self.personalized, num_threads=self.num_threads,
            cache_session=self.cache_session, infer_fns=self.infer_fns,
        )

    def _host_batch_state(self, preds, target=None):
        # keep the 4 MOS dimensions [p808, sig, bak, ovr] (reference dnsmos.py:127-128)
        score = np.asarray(self._score(preds)).reshape(-1, 4)
        return {"score_sum": score.sum(0), "total": jnp.asarray(score.shape[0], jnp.int32)}


class NonIntrusiveSpeechQualityAssessment(_HostMeanAudioMetric):
    """NISQA (reference ``audio/nisqa.py:35``). The melspec + CNN-self-attention
    pipeline is in-tree jnp (``functional/audio/nisqa.py``); only the published
    ``nisqa.tar`` checkpoint remains external (reference cache location or
    ``checkpoint_path``)."""

    higher_is_better = True

    def __init__(self, fs: int, checkpoint_path: Optional[str] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        from ..functional.audio.nisqa import ensure_checkpoint_exists

        ensure_checkpoint_exists(checkpoint_path)
        self.fs = fs
        self.checkpoint_path = checkpoint_path

    def _score(self, preds, target=None):
        return non_intrusive_speech_quality_assessment(preds, self.fs, self.checkpoint_path)

    def _host_batch_state(self, preds, target=None):
        # keep the 5 score dims [mos, noi, dis, col, loud] (reference nisqa.py:99-110)
        score = np.asarray(self._score(preds)).reshape(-1, 5)
        return {"score_sum": score.sum(0), "total": jnp.asarray(score.shape[0], jnp.int32)}
