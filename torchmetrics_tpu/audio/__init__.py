"""Audio tower — stateful metric classes (reference ``src/torchmetrics/audio/``)."""

from .metrics import (
    ComplexScaleInvariantSignalNoiseRatio,
    DeepNoiseSuppressionMeanOpinionScore,
    NonIntrusiveSpeechQualityAssessment,
    PerceptualEvaluationSpeechQuality,
    PermutationInvariantTraining,
    ScaleInvariantSignalDistortionRatio,
    ScaleInvariantSignalNoiseRatio,
    ShortTimeObjectiveIntelligibility,
    SignalDistortionRatio,
    SignalNoiseRatio,
    SourceAggregatedSignalDistortionRatio,
    SpeechReverberationModulationEnergyRatio,
)

__all__ = [
    "ComplexScaleInvariantSignalNoiseRatio",
    "DeepNoiseSuppressionMeanOpinionScore",
    "NonIntrusiveSpeechQualityAssessment",
    "PerceptualEvaluationSpeechQuality",
    "PermutationInvariantTraining",
    "ScaleInvariantSignalDistortionRatio",
    "ScaleInvariantSignalNoiseRatio",
    "ShortTimeObjectiveIntelligibility",
    "SignalDistortionRatio",
    "SignalNoiseRatio",
    "SourceAggregatedSignalDistortionRatio",
    "SpeechReverberationModulationEnergyRatio",
]
