"""Replayable production-shaped traffic.

A :class:`TrafficModel` turns one integer seed into the load pattern the
serving plane actually faces in production — and the same seed always turns
into the *same* pattern, event for event, byte for byte:

- **Zipf-skewed popularity**: tenant ``i``'s arrival weight is
  ``1/(rank+1)^s`` over the live roster, so a handful of head tenants stay
  resident while the long tail churns through the LRU spill plane.
- **Bursty arrivals** (doubly stochastic): each step draws a Poisson event
  count whose rate itself switches between a base level and a
  ``burst_factor`` multiple via a seeded burst state machine — the load
  shape that makes admission control and shed accounting interesting.
- **Mixed shape-classes**: each tenant is pinned to one batch size (the
  engine's stable-shape contract), so traffic exercises several compiled
  megabatch programs concurrently.
- **Scripted churn**: every ``churn_every`` steps a slice of the roster
  departs and a mix of brand-new and *readmitted* (previously departed)
  tenants arrives — deliberately thrashing spill/readmit.

Determinism has two layers. The **schedule** (which tenant fires at which
step) is simulated once with a Philox generator keyed on the seed. Each
event's **batch payload** is generated independently from a counter-based
Philox key ``(seed, event_index)`` — order-independent, so a replayed trace
regenerates identical batches without storing them. A trace file therefore
stores only the schedule arrays plus the config (a few bytes per event) in
a flat binary container with no timestamps: saving the same model twice
produces identical bytes, the replay contract ``docs/chaos.md`` documents.
"""

from __future__ import annotations

import dataclasses
import json
import os
import struct
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..utilities.exceptions import TorchMetricsUserError

_MAGIC = b"CHAOSTRC"
_VERSION = 1
# multiplicative hash constant (Knuth) — per-tenant accuracy profiles
_HASH = 2654435761


@dataclasses.dataclass(frozen=True)
class TrafficConfig:
    """Knobs for one seeded traffic stream (all defaults CPU-test sized).

    Args:
        seed: the whole stream — schedule AND per-event batches — is a pure
            function of this integer.
        tenants: initial roster size (churn grows ids past this).
        steps: simulated steps (one virtual clock tick each).
        zipf_exponent: popularity skew ``s`` in ``1/(rank+1)^s``; higher
            concentrates traffic on the head tenants.
        base_rate: mean events per step outside bursts (Poisson).
        burst_factor: rate multiplier while a burst is active.
        burst_prob: per-step probability a burst starts.
        burst_length: steps a burst lasts once started.
        shape_classes: batch sizes; tenant ``t`` is pinned to
            ``shape_classes[t % len(shape_classes)]`` forever.
        num_classes: label arity of the generated classification batches.
        churn_every: churn the roster every this many steps (0 disables).
        churn_count: tenants departed (and replaced) per churn event.
    """

    seed: int = 0
    tenants: int = 24
    steps: int = 120
    zipf_exponent: float = 1.1
    base_rate: float = 4.0
    burst_factor: float = 4.0
    burst_prob: float = 0.08
    burst_length: int = 6
    shape_classes: Tuple[int, ...] = (4, 8)
    num_classes: int = 3
    churn_every: int = 30
    churn_count: int = 4

    def __post_init__(self) -> None:
        if not (isinstance(self.seed, int) and 0 <= self.seed < 2 ** 64):
            raise ValueError(f"seed must be an integer in [0, 2**64), got {self.seed}")
        if not (isinstance(self.tenants, int) and self.tenants >= 1):
            raise ValueError(f"tenants must be a positive integer, got {self.tenants}")
        if not (isinstance(self.steps, int) and self.steps >= 1):
            raise ValueError(f"steps must be a positive integer, got {self.steps}")
        if self.zipf_exponent <= 0:
            raise ValueError(f"zipf_exponent must be > 0, got {self.zipf_exponent}")
        if self.base_rate <= 0 or self.burst_factor < 1.0:
            raise ValueError(
                f"base_rate must be > 0 and burst_factor >= 1, got "
                f"{self.base_rate}/{self.burst_factor}"
            )
        if not 0.0 <= self.burst_prob <= 1.0:
            raise ValueError(f"burst_prob must be in [0, 1], got {self.burst_prob}")
        if not self.shape_classes or any(int(b) < 1 for b in self.shape_classes):
            raise ValueError(f"shape_classes must be positive batch sizes, got {self.shape_classes}")
        if self.num_classes < 2:
            raise ValueError(f"num_classes must be >= 2, got {self.num_classes}")
        if self.churn_every < 0 or self.churn_count < 0:
            raise ValueError("churn_every/churn_count must be >= 0")


@dataclasses.dataclass(frozen=True)
class TrafficEvent:
    """One admitted-or-shed unit of load: a tenant's batch at a step."""

    index: int
    step: int
    tenant_id: int
    shape_class: int  # index into TrafficConfig.shape_classes
    batch: Tuple[np.ndarray, np.ndarray]  # (preds, target) labels


def _zipf_weights(n: int, exponent: float) -> np.ndarray:
    w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64), exponent)
    return w / w.sum()


class TrafficModel:
    """The seeded stream. Construction simulates the full schedule (two
    int32 arrays: step and tenant per event); batches are generated lazily
    per event from the counter-based key, so iteration is cheap to restart.
    """

    def __init__(
        self,
        config: TrafficConfig,
        _schedule: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    ) -> None:
        self.config = config
        if _schedule is not None:
            self._steps, self._tenants = _schedule
        else:
            self._steps, self._tenants = self._simulate()
        self.replayed = _schedule is not None

    # ------------------------------------------------------------- simulation

    def _simulate(self) -> Tuple[np.ndarray, np.ndarray]:
        cfg = self.config
        rng = np.random.Generator(np.random.Philox(key=np.uint64(cfg.seed)))
        active: List[int] = list(range(cfg.tenants))
        departed: List[int] = []
        next_id = cfg.tenants
        burst_left = 0
        ev_steps: List[int] = []
        ev_tenants: List[int] = []
        for step in range(cfg.steps):
            if cfg.churn_every and step and step % cfg.churn_every == 0 and cfg.churn_count:
                # depart from the tail half (head tenants are the hot set that
                # must stay resident for the Zipf skew to mean anything)
                k = min(cfg.churn_count, max(len(active) - 1, 0))
                if k:
                    tail = active[len(active) // 2:]
                    out_idx = rng.choice(len(tail), size=min(k, len(tail)), replace=False)
                    leaving = {tail[i] for i in out_idx}
                    active = [t for t in active if t not in leaving]
                    departed.extend(sorted(leaving))
                    # arrivals: readmit up to half from the departed pool
                    # (their spilled state thaws), fill the rest with new ids
                    readmit = min(len(departed) - len(leaving), k // 2)
                    for _ in range(max(readmit, 0)):
                        active.append(departed.pop(0))
                    while len(active) < cfg.tenants:
                        active.append(next_id)
                        next_id += 1
            if burst_left > 0:
                burst_left -= 1
                rate = cfg.base_rate * cfg.burst_factor
            elif rng.random() < cfg.burst_prob:
                burst_left = cfg.burst_length - 1
                rate = cfg.base_rate * cfg.burst_factor
            else:
                rate = cfg.base_rate
            n = int(rng.poisson(rate))
            if n == 0:
                continue
            weights = _zipf_weights(len(active), cfg.zipf_exponent)
            picks = rng.choice(len(active), size=n, p=weights)
            for i in picks:
                ev_steps.append(step)
                ev_tenants.append(active[int(i)])
        return (
            np.asarray(ev_steps, np.int32),
            np.asarray(ev_tenants, np.int32),
        )

    # --------------------------------------------------------------- batches

    def shape_class(self, tenant_id: int) -> int:
        return int(tenant_id) % len(self.config.shape_classes)

    def _batch(self, index: int, tenant_id: int) -> Tuple[np.ndarray, np.ndarray]:
        cfg = self.config
        size = cfg.shape_classes[self.shape_class(tenant_id)]
        key = (np.uint64(cfg.seed).item() << 64) | np.uint64(index).item()
        rng = np.random.Generator(np.random.Philox(key=key))
        target = rng.integers(0, cfg.num_classes, size=size).astype(np.int32)
        # per-tenant accuracy profile: stable agreement probability per id
        agree = 0.45 + 0.5 * (((tenant_id * _HASH) & 0xFFFF) / 0xFFFF)
        flip = rng.random(size) >= agree
        offset = rng.integers(1, cfg.num_classes, size=size).astype(np.int32)
        preds = np.where(flip, (target + offset) % cfg.num_classes, target).astype(np.int32)
        return preds, target

    def events(self) -> Iterator[TrafficEvent]:
        """Iterate the stream; batches regenerate identically every pass."""
        for i in range(self._steps.shape[0]):
            tid = int(self._tenants[i])
            yield TrafficEvent(
                index=i,
                step=int(self._steps[i]),
                tenant_id=tid,
                shape_class=self.shape_class(tid),
                batch=self._batch(i, tid),
            )

    @property
    def num_events(self) -> int:
        return int(self._steps.shape[0])

    def schedule(self) -> Tuple[np.ndarray, np.ndarray]:
        """Copies of the (step, tenant) schedule arrays."""
        return self._steps.copy(), self._tenants.copy()

    # ----------------------------------------------------------------- traces

    def trace_bytes(self) -> bytes:
        """The canonical trace encoding: magic + version + sorted-key JSON
        header + raw little-endian int32 schedule arrays. No timestamps, no
        compression dictionaries — identical model ⇒ identical bytes."""
        header = json.dumps(
            {
                "config": dataclasses.asdict(self.config),
                "events": self.num_events,
            },
            sort_keys=True,
            separators=(",", ":"),
        ).encode("utf-8")
        out = bytearray()
        out += _MAGIC
        out += struct.pack("<II", _VERSION, len(header))
        out += header
        out += self._steps.astype("<i4", copy=False).tobytes()
        out += self._tenants.astype("<i4", copy=False).tobytes()
        return bytes(out)

    def save_trace(self, path: str) -> int:
        """Write the trace file atomically; returns bytes written.

        tmp + fsync + ``os.replace``: a crash mid-write leaves either the
        previous trace or none — never a torn file that ``load_trace`` would
        half-parse into a silently different replay.
        """
        import uuid

        payload = self.trace_bytes()
        path = str(path)
        tmp = f"{path}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        try:
            with open(tmp, "wb") as fh:
                fh.write(payload)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return len(payload)

    @classmethod
    def load_trace(cls, path: str) -> "TrafficModel":
        """Rebuild a model from a trace file — the schedule is read back
        verbatim (no re-simulation), batches regenerate from the counter
        keys, so the replay is byte-for-byte the recorded run."""
        with open(path, "rb") as fh:
            raw = fh.read()
        if raw[: len(_MAGIC)] != _MAGIC:
            raise TorchMetricsUserError(f"{path!r} is not a chaos trace (bad magic).")
        version, hlen = struct.unpack_from("<II", raw, len(_MAGIC))
        if version != _VERSION:
            raise TorchMetricsUserError(f"unsupported trace version {version} in {path!r}")
        off = len(_MAGIC) + 8
        header = json.loads(raw[off : off + hlen].decode("utf-8"))
        off += hlen
        cfg_dict = dict(header["config"])
        cfg_dict["shape_classes"] = tuple(cfg_dict["shape_classes"])
        config = TrafficConfig(**cfg_dict)
        n = int(header["events"])
        need = off + 2 * 4 * n
        if len(raw) < need:
            raise TorchMetricsUserError(
                f"trace {path!r} is truncated: {len(raw)} bytes, need {need}."
            )
        steps = np.frombuffer(raw, dtype="<i4", count=n, offset=off).astype(np.int32)
        tenants = np.frombuffer(raw, dtype="<i4", count=n, offset=off + 4 * n).astype(np.int32)
        return cls(config, _schedule=(steps, tenants))

    def __repr__(self) -> str:
        return (
            f"TrafficModel(seed={self.config.seed}, events={self.num_events}, "
            f"steps={self.config.steps}, replayed={self.replayed})"
        )
