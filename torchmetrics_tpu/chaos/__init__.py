"""Chaos plane: replayable traffic, scheduled faults, the production soak.

Three pieces (see ``docs/chaos.md``):

- :class:`TrafficModel` / :class:`TrafficConfig` — a seeded, Zipf-skewed,
  bursty, churning tenant stream; same seed ⇒ same stream, serializable to
  a byte-for-byte replayable trace file;
- :class:`FaultSchedule` / :class:`FaultSpec` — declarative arming of the
  repo's existing fault-injection seams at exact steps;
- :func:`run_soak` / :class:`SoakConfig` / :class:`SoakReport` — the
  end-to-end harness driving the serving + streaming + reliability +
  observability planes through one trace, with SLO verdicts and a
  deterministic fault/recovery/shed ledger. ``bench.py``'s
  ``production_soak`` config and ``tools/chaos_soak.py`` front it.
"""

from .schedule import FAULT_KINDS, FaultSchedule, FaultSpec, default_fault_schedule
from .soak import SoakConfig, SoakReport, run_fleet_soak, run_soak, soak_rules
from .traffic import TrafficConfig, TrafficEvent, TrafficModel

__all__ = [
    "FAULT_KINDS",
    "FaultSchedule",
    "FaultSpec",
    "SoakConfig",
    "SoakReport",
    "TrafficConfig",
    "TrafficEvent",
    "TrafficModel",
    "default_fault_schedule",
    "run_fleet_soak",
    "run_soak",
    "soak_rules",
]
