"""Declarative, deterministic fault schedules.

A :class:`FaultSchedule` is a list of :class:`FaultSpec` entries arming the
repo's EXISTING injection points at exact traffic steps — no new failure
machinery, just a scheduler over the seams every recovery path already
tests through (``reliability/faults.py``, the serving ``_fault_hook``, the
token-bucket clock):

==================  ==========================================================
kind                what fires, and what "recovered" means
==================  ==========================================================
dispatch_transient  the next ``count`` MEGABATCH dispatches raise a transient
                    infra error (the round-5 crash class). The quarantine
                    path re-drives per tenant; the transient does not
                    reproduce on re-drives, so every tenant survives —
                    recovered = each raise absorbed with zero quarantines.
tenant_fault        a deterministic per-tenant poison: every dispatch whose
                    megabatch contains tenant ``target`` raises, INCLUDING
                    the single-tenant re-drive — so the engine quarantines
                    exactly that tenant and readmits the peers. Counted as
                    a quarantined (contained) fault, never unrecovered.
state_poison        ``poison_state_leaf`` NaN-floods the witness metric's
                    leaf ``target`` (default ``"tp"``) at the step; the next
                    sync epoch's ``validate_state`` raises
                    ``StateCorruptionError`` and the harness resets the
                    witness — recovered at that epoch.
gather_flaky        the witness's next sync gathers through ``FlakyGather``
                    (first ``count`` collective calls drop a participant);
                    the metric's retry policy re-enters the sync — recovered
                    when the sync lands within budget.
clock_skew          the virtual admission clock jumps by ``float(target)``
                    seconds (negative = backwards skew, which DRAINS the
                    token bucket — the refill formula sees a negative
                    delta); recovered when the first post-skew batch is
                    admitted again.
rank_loss           the witness's gather seam dies as ``DeadRank``: every
                    collective row for the simulated peer rank is an
                    all-zero tombstone. The coalesced plane completes each
                    sync over the survivor quorum (``degraded_syncs``
                    counts them); ``count`` sync epochs later the rank
                    revives — recovered when the rejoin sync reconciles it
                    (``rank_rejoins``) with zero hangs or double counts.
coordination_outage the next ``count`` collective calls raise an
                    UNAVAILABLE coordination-service error BEFORE any
                    collective is entered (all ranks fail in lockstep);
                    the retry policy re-enters the sync — recovered when
                    the sync lands within budget.
host_loss           (fleet soak only) member host ``target`` crashes: its
                    journal tears at the last fsync, heartbeats stop, the
                    lease runs to expiry — recovered when the survivors
                    adopt its tenants from its latest snapshot generation
                    plus the journal tail (``host_failovers`` ticks,
                    bitwise parity against the uninterrupted reference).
host_join           (fleet soak only) a new member host joins (``target``
                    names it, default ``host-<n>``): the rendezvous fair
                    share of tenants migrates onto it via the full
                    drain → cutover protocol — recovered when the minimal
                    move set commits with per-tenant state parity.
==================  ==========================================================

Schedules serialize to/from JSON (``to_json``/``from_json``, ``save``/
``load``) so a failing soak's faults replay alongside its traffic trace.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, Iterable, List, Optional, Tuple

from ..utilities.exceptions import TorchMetricsUserError

FAULT_KINDS = (
    "dispatch_transient",
    "tenant_fault",
    "state_poison",
    "gather_flaky",
    "clock_skew",
    "rank_loss",
    "coordination_outage",
    "host_loss",
    "host_join",
)


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault.

    Args:
        step: traffic step at which the fault arms (0-based; fires before
            the step's events are driven).
        kind: one of :data:`FAULT_KINDS`.
        target: kind-specific — tenant id (``tenant_fault``), state leaf
            name (``state_poison``), skew seconds (``clock_skew``), host id
            (``host_loss``, required; ``host_join``, optional); unused
            otherwise.
        count: kind-specific repetition — failing dispatches
            (``dispatch_transient``), failing gather calls
            (``gather_flaky`` / ``coordination_outage``), or degraded sync
            epochs before the dead rank revives (``rank_loss``).
    """

    step: int
    kind: str
    target: Optional[str] = None
    count: int = 1

    def __post_init__(self) -> None:
        if not (isinstance(self.step, int) and self.step >= 0):
            raise ValueError(f"step must be a non-negative integer, got {self.step}")
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"kind must be one of {FAULT_KINDS}, got {self.kind!r}")
        if not (isinstance(self.count, int) and self.count >= 1):
            raise ValueError(f"count must be a positive integer, got {self.count}")
        if self.kind == "tenant_fault" and self.target is None:
            raise ValueError("tenant_fault needs target=<tenant id>")
        if self.kind == "host_loss" and self.target is None:
            raise ValueError("host_loss needs target=<host id>")
        if self.kind == "clock_skew":
            try:
                float(self.target)  # type: ignore[arg-type]
            except (TypeError, ValueError):
                raise ValueError(
                    f"clock_skew needs target=<seconds as float string>, got {self.target!r}"
                ) from None


class FaultSchedule:
    """An ordered, replayable set of :class:`FaultSpec` entries."""

    def __init__(self, specs: Iterable[FaultSpec] = ()) -> None:
        specs = list(specs)
        for s in specs:
            if not isinstance(s, FaultSpec):
                raise TorchMetricsUserError(
                    f"FaultSchedule entries must be FaultSpec, got {type(s).__name__}"
                )
        self.specs: Tuple[FaultSpec, ...] = tuple(sorted(specs, key=lambda s: (s.step, s.kind)))

    def due(self, step: int) -> List[FaultSpec]:
        """Specs arming exactly at ``step``."""
        return [s for s in self.specs if s.step == step]

    @property
    def last_step(self) -> int:
        return max((s.step for s in self.specs), default=-1)

    def __len__(self) -> int:
        return len(self.specs)

    def __iter__(self):
        return iter(self.specs)

    # ------------------------------------------------------------ round trip

    def to_json(self) -> str:
        return json.dumps(
            {"version": 1, "faults": [dataclasses.asdict(s) for s in self.specs]},
            sort_keys=True,
            indent=2,
        )

    @classmethod
    def from_json(cls, text: str) -> "FaultSchedule":
        try:
            doc = json.loads(text)
        except json.JSONDecodeError as err:
            # a torn/garbage file must fail cleanly, not leak a decoder error
            raise TorchMetricsUserError(f"malformed fault schedule: {err}") from err
        entries = doc["faults"] if isinstance(doc, dict) else doc
        try:
            return cls(FaultSpec(**e) for e in entries)
        except TypeError as err:
            raise TorchMetricsUserError(f"malformed fault schedule: {err}") from err

    def save(self, path: str) -> None:
        # atomic: a schedule torn by a mid-write crash must never replay as a
        # plausible-but-wrong fault set (same tmp+fsync+rename discipline as
        # the AOT cache and the durability snapshot store)
        import os
        import uuid

        path = str(path)
        tmp = f"{path}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(self.to_json() + "\n")
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    @classmethod
    def load(cls, path: str) -> "FaultSchedule":
        with open(path, "r", encoding="utf-8") as fh:
            return cls.from_json(fh.read())

    def __repr__(self) -> str:
        kinds: Dict[str, int] = {}
        for s in self.specs:
            kinds[s.kind] = kinds.get(s.kind, 0) + 1
        return f"FaultSchedule({len(self.specs)} faults: {kinds})"


def default_fault_schedule(steps: int, tenant: int = 1) -> FaultSchedule:
    """One fault of every kind, spread across the run — the schedule the
    demo/bench/CLI use when none is supplied. ``tenant`` is the id the
    ``tenant_fault`` entry quarantines (pick a mid-popularity one so its
    loss is visible but not dominant)."""
    if steps < 10:
        raise ValueError(f"need >= 10 steps to spread the default faults, got {steps}")
    return FaultSchedule(
        [
            FaultSpec(step=max(1, steps // 10), kind="rank_loss", count=1),
            FaultSpec(step=max(1, steps // 5), kind="dispatch_transient", count=2),
            FaultSpec(step=max(2, (2 * steps) // 5), kind="tenant_fault", target=str(tenant)),
            FaultSpec(step=max(3, steps // 2), kind="state_poison", target="tp"),
            FaultSpec(step=max(4, (3 * steps) // 5), kind="gather_flaky", count=2),
            FaultSpec(step=max(5, (3 * steps) // 4), kind="clock_skew", target="-2.0"),
            FaultSpec(step=max(6, (7 * steps) // 10), kind="coordination_outage", count=2),
        ]
    )
