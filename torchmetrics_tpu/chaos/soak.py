"""The production soak: every plane, one run, verdicts attached.

``run_soak`` drives a :class:`~torchmetrics_tpu.serving.ServingEngine`
(quarantine mode, LRU spill with an optional codec, token-bucket admission
on a VIRTUAL clock, optional per-tenant windows, optional AOT self-warming)
plus :class:`~torchmetrics_tpu.streaming.SlidingWindow` /
:class:`~torchmetrics_tpu.streaming.DriftMonitor` side-channels through one
seeded :class:`~torchmetrics_tpu.chaos.TrafficModel`, arming a
:class:`~torchmetrics_tpu.chaos.FaultSchedule` at exact steps, inside one
telemetry session whose SLO engine (``default_rules()`` + :func:`soak_rules`)
renders verdicts each sync epoch.

Determinism contract: the ``SoakReport.counters`` block — admission/shed,
engine stats (minus wall-clock nanoseconds), and the fault ledger
(injected/recovered/quarantined/unrecovered) — is a pure function of
``(SoakConfig, seed, fault schedule)``. Admission runs on a virtual clock
advancing ``seconds_per_step`` per traffic step (``ServingConfig(clock=)``),
so even shed counts replay exactly. Latency percentiles and SLO breach
timing ride real wall-clock and live in the non-contractual ``timing`` /
``slo_breaches`` blocks.

Fault accounting (``docs/chaos.md`` has the full table):

- *recovered* — the plane absorbed the fault and service continued:
  transient megabatch raises re-driven clean, poisons caught by
  ``validate_state`` and reset, flaky gathers retried home, clock skews
  admitting again;
- *quarantined* — the engine CONTAINED a deterministic per-tenant fault by
  quarantining exactly the offender (the designed blast radius, not a
  failure of recovery);
- *unrecovered* — anything that escaped: an exception out of the serve
  loop, a sync that exhausted its retry budget, corruption detected with no
  armed poison, a skew still shedding at run end. A healthy soak reports
  **zero**, and the ``production_soak`` bench gate pins that.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .. import observability as _observability
from ..observability import spans as _spans
from ..classification import MulticlassAccuracy
from ..observability.slo import SloRule, default_rules
from ..parallel import SyncConfig
from ..parallel import coalesce as _coalesce
from ..reliability import (
    DeadRank,
    FlakyGather,
    ReliabilityConfig,
    RetryPolicy,
    make_transient_error,
    poison_state_leaf,
    validate_state,
)
from ..serving import ServingConfig, ServingEngine, TrafficJournal
from ..streaming import DriftMonitor, SlidingWindow
from ..utilities.exceptions import StateCorruptionError, TorchMetricsUserError
from .schedule import FAULT_KINDS, FaultSchedule, FaultSpec, default_fault_schedule
from .traffic import TrafficConfig, TrafficModel


@dataclasses.dataclass(frozen=True)
class SoakConfig:
    """One soak run, fully specified (defaults are CPU-test sized).

    Args:
        traffic: the seeded load (ignored when ``run_soak`` is handed a
            replayed :class:`TrafficModel` directly).
        faults: the schedule; ``None`` arms :func:`default_fault_schedule`
            over the traffic's step count.
        capacity / megabatch_size / spill_codec / window /
        max_tenants_per_sec / aot_cache_dir: forwarded into
            :class:`~torchmetrics_tpu.serving.ServingConfig` (quarantine
            mode and spill are always on — the soak exists to exercise
            them).
        seconds_per_step: virtual seconds the admission clock advances per
            traffic step.
        sync_every: sync-epoch cadence in steps — each epoch validates the
            witness, syncs it through the (possibly flaky) gather, commits
            the engine's async stacked sync (or ``compute_all`` on windowed
            engines), and evaluates the SLO rules.
        sync_codec: ``None`` syncs exact; else a
            :class:`~torchmetrics_tpu.parallel.SyncConfig` codec name for
            quantize-on-sync (one config instance lives across the run, so
            error-feedback residuals fold correctly).
        side_channel_every: update the SlidingWindow/DriftMonitor side
            channels every Nth event (they dispatch per update — this keeps
            the CPU soak fast without changing the engine path).
        drift_reference / drift_test: DriftMonitor window geometry.
        shed_rate_max: threshold for the ``soak_shed_rate`` SLO rule.
        retry_attempts: witness sync retry budget (the ``gather_flaky`` /
            ``coordination_outage`` recovery headroom).
        durability_dir: root directory for the durability plane — the
            engine's write-ahead journal lives in ``<dir>/journal`` and
            crash-consistent snapshots in ``<dir>/snapshots``. Required
            when ``snapshot_every`` or ``failover_at`` is set.
        snapshot_every: snapshot the engine every N traffic steps (the
            standby's restore point).
        failover_at: at this step the primary engine is KILLED and a cold
            standby takes over: restore the latest snapshot, replay the
            journal tail against the retained batches, and verify bitwise
            state parity against the pre-kill primary. ``timing`` gains
            ``failover_rto_ms``; ``counters`` gain the replay/parity block.
        journal_fsync_every: fsync cadence of the write-ahead journal
            (1 = every record, the RPO=0 setting the parity gate assumes).
        retain_snapshots: keep only the newest N snapshot generations per
            engine (``ServingConfig.retain_snapshots``) — journal segments
            every retained snapshot covers are pruned with them. ``None``
            retains everything (unbounded growth under ``snapshot_every``).
        fleet_hosts: run the FLEET soak (:func:`run_fleet_soak`) over this
            many member hosts behind one :class:`FleetController` instead
            of a single engine. Fleet mode admits unlimited (the per-tenant
            parity gate compares against an uninterrupted single-host
            reference, so admission must not fork) and arms only the
            ``host_loss`` / ``host_join`` fault kinds.
        fleet_suspect_after / fleet_dead_after: lease thresholds in virtual
            seconds (suspect keeps its tenants — the flap window; dead
            triggers adoption). Heartbeats renew every traffic step.
    """

    traffic: TrafficConfig = dataclasses.field(default_factory=TrafficConfig)
    faults: Optional[FaultSchedule] = None
    capacity: int = 16
    megabatch_size: int = 4
    spill_codec: str = "none"
    window: Optional[int] = None
    max_tenants_per_sec: Optional[float] = 40.0
    aot_cache_dir: Optional[str] = None
    seconds_per_step: float = 0.25
    sync_every: int = 20
    sync_codec: Optional[str] = None
    side_channel_every: int = 4
    drift_reference: int = 48
    drift_test: int = 16
    shed_rate_max: float = 0.5
    retry_attempts: int = 5
    durability_dir: Optional[str] = None
    snapshot_every: Optional[int] = None
    failover_at: Optional[int] = None
    journal_fsync_every: int = 1
    retain_snapshots: Optional[int] = None
    fleet_hosts: Optional[int] = None
    fleet_suspect_after: float = 0.75
    fleet_dead_after: float = 1.5

    def __post_init__(self) -> None:
        if self.sync_every < 1:
            raise ValueError(f"sync_every must be >= 1, got {self.sync_every}")
        if self.snapshot_every is not None and self.snapshot_every < 1:
            raise ValueError(f"snapshot_every must be >= 1, got {self.snapshot_every}")
        if self.failover_at is not None and self.failover_at < 1:
            raise ValueError(f"failover_at must be >= 1, got {self.failover_at}")
        if (self.snapshot_every is not None or self.failover_at is not None) and not self.durability_dir:
            raise ValueError("snapshot_every/failover_at need durability_dir")
        if self.journal_fsync_every < 1:
            raise ValueError(f"journal_fsync_every must be >= 1, got {self.journal_fsync_every}")
        if self.seconds_per_step <= 0:
            raise ValueError(f"seconds_per_step must be > 0, got {self.seconds_per_step}")
        if self.side_channel_every < 1:
            raise ValueError(f"side_channel_every must be >= 1, got {self.side_channel_every}")
        if not 0.0 < self.shed_rate_max <= 1.0:
            raise ValueError(f"shed_rate_max must be in (0, 1], got {self.shed_rate_max}")
        if self.retry_attempts < 1:
            raise ValueError(f"retry_attempts must be >= 1, got {self.retry_attempts}")
        if self.retain_snapshots is not None and self.retain_snapshots < 1:
            raise ValueError(f"retain_snapshots must be >= 1, got {self.retain_snapshots}")
        if self.fleet_hosts is not None:
            if self.fleet_hosts < 2:
                raise ValueError(
                    f"fleet_hosts must be >= 2 (a fleet of one cannot fail over), "
                    f"got {self.fleet_hosts}"
                )
            if not self.durability_dir:
                raise ValueError("fleet_hosts needs durability_dir (per-host journals/snapshots)")
        if not self.fleet_dead_after > self.fleet_suspect_after > 0:
            raise ValueError(
                f"need fleet_dead_after > fleet_suspect_after > 0, got "
                f"{self.fleet_dead_after} / {self.fleet_suspect_after}"
            )


def soak_rules(
    shed_rate_max: float = 0.5,
    drift_threshold: float = 0.75,
) -> Tuple[SloRule, ...]:
    """Soak-specific SLO rules layered on ``default_rules()``: overload shed
    rate, any quarantine in the window, and sustained side-channel drift."""
    return (
        SloRule(
            name="soak_shed_rate",
            expr=(
                "serve_rejected >= 3 and "
                f"serve_rejected / max(serve_tenant_rows + serve_rejected, 1) > {shed_rate_max}"
            ),
            window=120.0,
            severity="critical",
            description="admission shedding more than the overload budget",
        ),
        SloRule(
            name="soak_quarantine",
            expr="quarantines > 0",
            window=120.0,
            severity="warning",
            description="a tenant was quarantined this window (contained deterministic fault)",
        ),
        SloRule(
            name="soak_drift",
            expr=f"drift('soak') > {drift_threshold}",
            window=240.0,
            severity="warning",
            description="side-channel stream drifted past the soak threshold",
        ),
    )


@dataclasses.dataclass
class SoakReport:
    """Structured soak verdict. ``counters`` is the deterministic block (the
    replay/determinism contract); ``timing`` and ``slo_breaches`` carry
    wall-clock observations; ``faults`` is the per-spec ledger;
    ``reconciliation`` is the health-plane identity
    ``jit_compiles + jit_cache_hits + aot_cache_hits == dispatches``."""

    counters: Dict[str, Any]
    timing: Dict[str, float]
    faults: List[Dict[str, Any]]
    slo_breaches: List[Dict[str, Any]]
    reconciliation: Dict[str, Any]
    config: Dict[str, Any]
    # the fleet control tower rollup (FleetController.telemetry()) captured
    # just before teardown — fleet soaks only; carries wall-clock latency
    # summaries, so it lives OUTSIDE the counters determinism contract
    fleet_telemetry: Optional[Dict[str, Any]] = None
    # the telemetry history's deterministic export (recorder.history_block()):
    # retained level boundaries keyed by the soak's virtual clock, so two
    # same-seed runs carry byte-identical blocks — INSIDE the determinism
    # contract, same standing as ``counters`` (pinned by test and bench)
    history: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def summary(self) -> str:
        c = self.counters
        return (
            f"soak seed={self.config.get('seed')}: {c['events']} events, "
            f"{c['admitted']} admitted, {c['shed']} shed "
            f"(rate {c['shed_rate']:.3f}); faults injected={c['faults_injected']} "
            f"recovered={c['recovered_faults']} quarantined={c['quarantined_faults']} "
            f"unrecovered={c['unrecovered_faults']}; "
            f"reconciliation={'OK' if self.reconciliation['exact'] else 'BROKEN'}"
        )


class _ChaosHook:
    """Multiplexing ``ServingEngine._fault_hook``: one seam, two behaviors.

    Transient faults fire only on MEGABATCH dispatches (``len > 1``) so the
    quarantine path's single-tenant re-drives always pass — a transient by
    definition does not reproduce. Tenant faults fire whenever the target is
    present, re-drive included, so exactly that tenant quarantines; the hook
    disarms on the single-entry raise (the raise that quarantines)."""

    def __init__(self) -> None:
        self.transient_left = 0
        self.transient_raised = 0
        self.tenant_targets: set = set()
        self.tenant_raised = 0
        self.tenant_contained = 0

    def __call__(self, tenant_ids: List[Any]) -> None:
        tids = [int(t) for t in tenant_ids]
        armed = [t for t in tids if t in self.tenant_targets]
        if armed:
            self.tenant_raised += 1
            if len(tids) == 1:
                # the re-drive raise: the engine quarantines this tenant next
                self.tenant_targets.discard(tids[0])
                self.tenant_contained += 1
            raise RuntimeError(
                f"chaos: deterministic fault pinned to tenant {armed[0]}"
            )
        if self.transient_left > 0 and len(tids) > 1:
            self.transient_left -= 1
            self.transient_raised += 1
            raise make_transient_error()


class _WitnessGather:
    """World-of-one gather for the witness sync, with the schedule's
    collective faults layered over it:

    - ``arm(n)`` — a ``FlakyGather`` drops a participant on the next ``n``
      calls (``gather_flaky``);
    - ``arm_outage(n)`` — a second ``FlakyGather`` raises an UNAVAILABLE
      coordination-service error on the next ``n`` calls
      (``coordination_outage``);
    - ``arm_dead_rank()`` — every collective runs through a
      :class:`~torchmetrics_tpu.reliability.DeadRank` world-of-two whose
      peer rank is tombstoned until :meth:`revive_rank` — the coalesced
      plane's degraded-quorum path, not a raise.

    Layering order on a call: flaky raise, then outage raise, then the
    (possibly dead-rank-widened) collective.
    """

    def __init__(self) -> None:
        self._flaky: Optional[FlakyGather] = None
        self._outage: Optional[FlakyGather] = None
        self._dead: Optional[DeadRank] = None

    def base(self, value: Any, group: Any = None) -> List[Any]:
        return [jnp.asarray(value)]

    def _inner(self, value: Any, group: Any = None) -> List[Any]:
        if self._dead is not None:
            return self._dead(value, group)
        return self.base(value, group)

    def arm(self, fail_times: int) -> None:
        self._flaky = FlakyGather(inner=self._inner, fail_times=fail_times)

    @property
    def armed_failures(self) -> int:
        return self._flaky.failures if self._flaky is not None else 0

    def disarm(self) -> None:
        self._flaky = None

    def arm_outage(self, fail_times: int) -> None:
        self._outage = FlakyGather(
            inner=self._inner,
            fail_times=fail_times,
            exc_factory=lambda: make_transient_error(
                "UNAVAILABLE: coordination service unreachable during collective setup"
            ),
        )

    @property
    def outage_failures(self) -> int:
        return self._outage.failures if self._outage is not None else 0

    def disarm_outage(self) -> None:
        self._outage = None

    def arm_dead_rank(self) -> None:
        self._dead = DeadRank(inner=self.base, world=2, rank=1)

    def revive_rank(self) -> None:
        if self._dead is not None:
            self._dead.revive()

    def disarm_dead_rank(self) -> None:
        self._dead = None

    def __call__(self, value: Any, group: Any = None) -> List[Any]:
        if self._flaky is not None and self._flaky.failures < self._flaky.fail_times:
            return self._flaky(value, group)  # raises (participant drop)
        if self._outage is not None and self._outage.failures < self._outage.fail_times:
            return self._outage(value, group)  # raises (coordination outage)
        return self._inner(value, group)


def _metric(num_classes: int, reliability: Optional[ReliabilityConfig] = None) -> MulticlassAccuracy:
    return MulticlassAccuracy(
        num_classes=num_classes, average="micro", validate_args=False,
        reliability=reliability,
    )


def _engine_digest(engine: ServingEngine) -> str:
    """Canonical digest of the whole engine's tenant state — id, quarantine
    flag, update count, and every state leaf's exact bytes, in sorted tenant
    order. Two engines with equal digests are bitwise-identical as far as
    any tenant read can tell; the failover parity gate compares these."""
    h = hashlib.sha256()
    roster = engine.tenants()
    for tid in sorted(roster, key=repr):
        info = roster[tid]
        h.update(f"{tid!r}|{info['quarantined']}|{info['update_count']}".encode("utf-8"))
        if info["quarantined"]:
            continue  # a quarantined tenant's state is frozen garbage by contract
        state = engine.state_dict(tid)
        for name in sorted(state):
            if name.startswith("_"):
                continue
            arr = np.asarray(state[name])
            h.update(name.encode("utf-8"))
            h.update(str(arr.dtype).encode("utf-8"))
            h.update(str(arr.shape).encode("utf-8"))
            h.update(arr.tobytes())
    return h.hexdigest()


def run_soak(
    config: Optional[SoakConfig] = None,
    traffic_model: Optional[TrafficModel] = None,
) -> SoakReport:
    """Run one soak; see the module docstring for the contract. Pass
    ``traffic_model`` (e.g. :meth:`TrafficModel.load_trace`) to replay a
    recorded stream instead of simulating ``config.traffic``."""
    cfg = config if config is not None else SoakConfig()
    if cfg.fleet_hosts is not None:
        return run_fleet_soak(cfg, traffic_model)
    model = traffic_model if traffic_model is not None else TrafficModel(cfg.traffic)
    traffic = model.config
    faults = cfg.faults if cfg.faults is not None else default_fault_schedule(traffic.steps)
    if faults.last_step >= traffic.steps:
        raise TorchMetricsUserError(
            f"fault schedule reaches step {faults.last_step} but the traffic "
            f"runs only {traffic.steps} steps."
        )
    fleet_kinds = [s.kind for s in faults if s.kind in ("host_loss", "host_join")]
    if fleet_kinds:
        raise TorchMetricsUserError(
            f"{sorted(set(fleet_kinds))} faults need the fleet soak — set "
            "SoakConfig(fleet_hosts=N)"
        )

    _coalesce.clear_dead_ranks()  # liveness ledger is process-global — fresh run, fresh ledger
    journal_dir = os.path.join(cfg.durability_dir, "journal") if cfg.durability_dir else None
    snap_dir = os.path.join(cfg.durability_dir, "snapshots") if cfg.durability_dir else None
    clock = {"t": 0.0}

    def _serving_config() -> ServingConfig:
        return ServingConfig(
            capacity=cfg.capacity,
            megabatch_size=cfg.megabatch_size,
            spill=True,
            spill_codec=cfg.spill_codec,
            on_error="quarantine",
            max_tenants_per_sec=cfg.max_tenants_per_sec,
            clock=lambda: clock["t"],
            window=cfg.window,
            aot_cache_dir=cfg.aot_cache_dir,
            journal=journal_dir,
            journal_fsync_every=cfg.journal_fsync_every,
            retain_snapshots=cfg.retain_snapshots,
        )

    flight = (
        _observability.FlightRecorder(
            dump_dir=os.path.join(cfg.durability_dir, "flightrec"))
        if cfg.durability_dir else None
    )
    engine = ServingEngine(_metric(traffic.num_classes), _serving_config())
    hook = _ChaosHook()
    engine._fault_hook = hook
    gather = _WitnessGather()
    # the witness: a fleet-level side metric whose sync path carries the
    # gather_flaky/state_poison faults (its retry budget is the recovery)
    witness = _metric(
        traffic.num_classes,
        reliability=ReliabilityConfig(
            retry=RetryPolicy(
                max_attempts=cfg.retry_attempts, backoff_base=0.0, jitter=0.0,
                sleep_fn=lambda _s: None,
            )
        ),
    )
    sync_cfg = SyncConfig(codec=cfg.sync_codec) if cfg.sync_codec else None
    sliding = SlidingWindow(_metric(traffic.num_classes), cfg.drift_test * 2)
    drift = DriftMonitor(
        _metric(traffic.num_classes),
        reference_window=cfg.drift_reference,
        test_window=cfg.drift_test,
        threshold=0.75,
        name="soak",
        eval_every=cfg.drift_test,
    )

    # fault ledger: per-spec records resolved as recoveries land (FIFO per kind)
    records: List[Dict[str, Any]] = []
    pending: Dict[str, List[Dict[str, Any]]] = {k: [] for k in FAULT_KINDS}
    recovered = 0
    unrecovered = 0
    skew_pending = 0
    armed_poisons = 0
    # rank_loss staged recovery: N degraded sync epochs, revive, then the
    # rejoin sync reconciles — tracked via the degraded_syncs/rank_rejoins
    # counter deltas each epoch
    dead_epochs_left = 0
    awaiting_rejoin = False
    # retained admitted batches keyed by journal seq — the failover standby's
    # replay source (pruned at every snapshot: covered seqs never replay)
    retained: Dict[int, Tuple[tuple, dict]] = {}
    failover_info: Dict[str, Any] = {}
    epochs = 0
    slo_breaches: List[Dict[str, Any]] = []
    quarantined_tids: set = set()
    known_quarantines = 0
    admitted = 0
    shed = 0
    dropped_quarantined = 0
    events_total = 0

    def _arm(spec: FaultSpec) -> None:
        nonlocal skew_pending, armed_poisons, dead_epochs_left, awaiting_rejoin
        rec = {
            "step": spec.step, "kind": spec.kind, "target": spec.target,
            "count": spec.count, "outcome": "pending",
            "trace_id": _spans.derive_trace_id(
                "fault", traffic.seed, spec.step, spec.kind, spec.target),
        }
        records.append(rec)
        pending[spec.kind].append(rec)
        if spec.kind == "dispatch_transient":
            hook.transient_left += spec.count
        elif spec.kind == "tenant_fault":
            hook.tenant_targets.add(int(spec.target))  # type: ignore[arg-type]
        elif spec.kind == "state_poison":
            poison_state_leaf(witness, spec.target or "tp")
            armed_poisons += 1
        elif spec.kind == "gather_flaky":
            gather.arm(spec.count)
        elif spec.kind == "clock_skew":
            clock["t"] += float(spec.target)  # type: ignore[arg-type]
            skew_pending += 1
        elif spec.kind == "rank_loss":
            gather.arm_dead_rank()
            dead_epochs_left = spec.count
            awaiting_rejoin = False
        elif spec.kind == "coordination_outage":
            gather.arm_outage(spec.count)

    def _resolve(kind: str, outcome: str, n: int = 1) -> None:
        for _ in range(n):
            if pending[kind]:
                pending[kind].pop(0)["outcome"] = outcome

    def _sync_epoch() -> None:
        nonlocal recovered, unrecovered, armed_poisons, epochs
        nonlocal dead_epochs_left, awaiting_rejoin
        epochs += 1
        engine.flush()
        act = _observability._ACTIVE
        deg0 = act.counters.value("degraded_syncs") if act is not None else 0
        rej0 = act.counters.value("rank_rejoins") if act is not None else 0
        # 1. witness integrity: an armed poison MUST be caught here
        try:
            validate_state(witness, context=f"soak epoch {epochs}")
        except StateCorruptionError:
            witness.reset()
            if armed_poisons:
                recovered += armed_poisons
                _resolve("state_poison", "recovered", armed_poisons)
                armed_poisons = 0
            else:
                unrecovered += 1
                if flight is not None:
                    flight.dump("state_corruption", extra={"epoch": epochs})
        # 2. witness sync through the (possibly flaky/dead-rank) gather,
        # retry armed
        try:
            witness.sync(
                dist_sync_fn=gather,
                distributed_available=lambda: True,
                sync_config=sync_cfg,
            )
            witness.unsync()
            if gather.armed_failures:
                recovered += gather.armed_failures
                _resolve("gather_flaky", "recovered")
            gather.disarm()
            if gather.outage_failures:
                recovered += gather.outage_failures
                _resolve("coordination_outage", "recovered")
            gather.disarm_outage()
            # rank_loss staged flow: each degraded epoch ticks the countdown;
            # at zero the rank revives, and the NEXT sync's rejoin resolves it
            if awaiting_rejoin:
                if act is not None and act.counters.value("rank_rejoins") > rej0:
                    recovered += 1
                    _resolve("rank_loss", "recovered")
                    awaiting_rejoin = False
                    gather.disarm_dead_rank()
            elif dead_epochs_left > 0:
                if act is not None and act.counters.value("degraded_syncs") > deg0:
                    dead_epochs_left -= 1
                    if dead_epochs_left == 0:
                        gather.revive_rank()
                        awaiting_rejoin = True
        except Exception:  # noqa: BLE001 — an escaped sync is an unrecovered fault
            unrecovered += 1
            _resolve("gather_flaky", "unrecovered")
            _resolve("coordination_outage", "unrecovered")
            gather.disarm()
            gather.disarm_outage()
        # 3. engine read side: async stacked sync (plain engines) or the
        # windowed per-tenant read (sync_async rejects windowed stacks)
        if cfg.window is None:
            engine.sync_async(dist_sync_fn=gather.base, sync_config=sync_cfg).commit()
        else:
            engine.compute_all()
        # 4. SLO verdicts (real-clock windows — informational)
        rec = _observability._ACTIVE
        if rec is not None:
            for alert in rec.evaluate_slos():
                slo_breaches.append({
                    "epoch": epochs,
                    "rule": alert.get("rule", "?"),
                    "severity": alert.get("severity", "?"),
                })

    def _refresh_quarantined() -> None:
        nonlocal known_quarantines
        known_quarantines = engine.stats["quarantined"]
        quarantined_tids.clear()
        quarantined_tids.update(
            tid for tid, info in engine.tenants().items() if info["quarantined"]
        )

    def _snapshot() -> None:
        info = engine.snapshot(snap_dir)
        failover_info["snapshots"] = failover_info.get("snapshots", 0) + 1
        failover_info["last_generation"] = info["generation"]
        # everything the snapshot covers never replays — prune the retention
        # buffer so its footprint is one snapshot interval, not the whole run
        cutoff = engine._applied_seq
        for seq in [s for s in retained if s <= cutoff]:
            del retained[seq]

    def _failover() -> None:
        """Kill the primary, bring up a cold standby from the latest snapshot
        plus the journal tail, and verify bitwise state parity."""
        nonlocal engine
        # parity reference: the primary's exact pre-kill state (flush first so
        # queued megabatches land — the journal already holds their admissions)
        engine.flush()
        pre_digest = _engine_digest(engine)
        pre_seq = engine._applied_seq  # the last admission the primary applied
        engine.close()  # the kill point: after the last durable journal write
        # ---- the primary is dead from here on ----
        t_rto = time.perf_counter()
        standby = ServingEngine(_metric(traffic.num_classes), _serving_config())
        standby._fault_hook = hook
        if failover_info.get("snapshots"):
            standby.restore(snap_dir)
        # with no snapshot yet the standby replays the journal from scratch
        replayed = standby.replay_journal(
            TrafficJournal.read(journal_dir), lambda r: retained[r.seq],
        )
        standby.flush()
        rto_ms = (time.perf_counter() - t_rto) * 1000.0
        post_digest = _engine_digest(standby)
        engine = standby
        _refresh_quarantined()
        failover_info.update(
            failovers=failover_info.get("failovers", 0) + 1,
            rto_ms=round(rto_ms, 3),
            replayed=replayed,
            # RPO in records: admissions the primary applied that the standby
            # could not reconstruct (0 with fsync-per-record journaling)
            rpo_records=max(0, pre_seq - standby._applied_seq),
            state_parity=1.0 if post_digest == pre_digest else 0.0,
            pre_digest=pre_digest,
            post_digest=post_digest,
        )

    t0 = time.perf_counter()
    with _observability.telemetry_session(
        _observability.TelemetryConfig(
            slo_rules=tuple(default_rules()) + soak_rules(shed_rate_max=cfg.shed_rate_max),
            sinks=(
                (_observability.RingBufferSink(), flight) if flight is not None else ()
            ),
            # history keyed by the soak's virtual clock: same seed ⇒ same
            # block boundaries ⇒ byte-identical SoakReport.history
            history_clock=lambda: clock["t"],
        )
    ) as rec:
        current_step = -1
        for ev in model.events():
            while current_step < ev.step:
                current_step += 1
                clock["t"] += cfg.seconds_per_step
                for spec in faults.due(current_step):
                    _arm(spec)
                if cfg.snapshot_every and current_step and current_step % cfg.snapshot_every == 0:
                    _snapshot()
                if cfg.failover_at is not None and current_step == cfg.failover_at:
                    _failover()
                if current_step and current_step % cfg.sync_every == 0:
                    _sync_epoch()
            events_total += 1
            tid = int(ev.tenant_id)
            if tid in quarantined_tids:
                dropped_quarantined += 1
                continue
            try:
                ok = engine.update(tid, ev.batch[0], ev.batch[1])
            except Exception:  # noqa: BLE001 — an escaped dispatch is unrecovered
                unrecovered += 1
                ok = False
            if ok:
                admitted += 1
                if engine._journal is not None:
                    # the standby's replay source for this journaled admission
                    retained[engine._applied_seq] = ((ev.batch[0], ev.batch[1]), {})
                if skew_pending:
                    # service admitted again after the jump: skew absorbed
                    recovered += skew_pending
                    _resolve("clock_skew", "recovered", skew_pending)
                    skew_pending = 0
            else:
                shed += 1
            if engine.stats["quarantined"] != known_quarantines:
                _refresh_quarantined()
            if ev.index % cfg.side_channel_every == 0:
                witness.update(ev.batch[0], ev.batch[1])
                sliding.update(ev.batch[0], ev.batch[1])
                drift.update(ev.batch[0], ev.batch[1])
        # drain the remaining steps (faults/epochs past the last event)
        while current_step < traffic.steps - 1:
            current_step += 1
            clock["t"] += cfg.seconds_per_step
            for spec in faults.due(current_step):
                _arm(spec)
            if cfg.snapshot_every and current_step and current_step % cfg.snapshot_every == 0:
                _snapshot()
            if cfg.failover_at is not None and current_step == cfg.failover_at:
                _failover()
            if current_step and current_step % cfg.sync_every == 0:
                _sync_epoch()
        _sync_epoch()  # the closing epoch: catches late poisons/flaky syncs
        elapsed = time.perf_counter() - t0

        # ledger close-out
        if skew_pending:
            unrecovered += skew_pending
            _resolve("clock_skew", "unrecovered", skew_pending)
        recovered += hook.transient_raised
        consumed = hook.transient_raised
        for r in list(pending["dispatch_transient"]):
            if consumed >= r["count"]:
                consumed -= r["count"]
                _resolve("dispatch_transient", "recovered")
        _resolve("tenant_fault", "quarantined", hook.tenant_contained)
        # a rank_loss that armed but never reconciled (rejoin sync never came)
        # is unrecovered — every other still-pending spec simply never fired
        for r in list(pending["rank_loss"]):
            unrecovered += 1
            _resolve("rank_loss", "unrecovered")
        for kind_pending in pending.values():
            for r in kind_pending:
                if r["outcome"] == "pending":
                    r["outcome"] = "not_fired"
        if unrecovered and flight is not None:
            flight.dump("unrecovered_faults", extra={"ledger": records})
        quarantined_faults = engine.stats["quarantined"]
        injected = (
            hook.transient_raised + hook.tenant_raised + sum(
                1 for r in records if r["kind"] in ("state_poison", "clock_skew", "rank_loss")
            ) + sum(
                r["count"] for r in records
                if r["kind"] in ("gather_flaky", "coordination_outage")
            )
        )

        snap = rec.counters.snapshot().counts
        lat = rec.latency_summary()
        history_block = rec.history_block(last_n=16)
        reconciliation = {
            "dispatches": int(snap.get("dispatches", 0)),
            "jit_compiles": int(snap.get("jit_compiles", 0)),
            "jit_cache_hits": int(snap.get("jit_cache_hits", 0)),
            "aot_cache_hits": int(snap.get("aot_cache_hits", 0)),
        }
        reconciliation["exact"] = (
            reconciliation["jit_compiles"]
            + reconciliation["jit_cache_hits"]
            + reconciliation["aot_cache_hits"]
            == reconciliation["dispatches"]
        )
        update_kind = "vwupdate" if cfg.window is not None else "vupdate"
        kind_lat = lat.get(update_kind) or {}

    final_digest = _engine_digest(engine)
    engine.close()  # release the journal segment cleanly
    # degraded-sync reconciliation: every scheduled rank loss recovered AND
    # the liveness ledger drained (no rank still marked dead at run end)
    rank_loss_ok = all(
        r["outcome"] in ("recovered", "not_fired")
        for r in records if r["kind"] == "rank_loss"
    )
    degraded_parity = 1.0 if rank_loss_ok and not _coalesce.dead_ranks() else 0.0

    stats = dict(engine.stats)
    stats.pop("spill_ns", None)  # wall-clock — outside the determinism contract
    served = admitted
    shed_rate = round(shed / max(served + shed, 1), 6)
    counters: Dict[str, Any] = {
        "events": events_total,
        "admitted": admitted,
        "shed": shed,
        "shed_rate": shed_rate,
        "dropped_quarantined": dropped_quarantined,
        "steps": traffic.steps,
        "epochs": epochs,
        "tenants": len(engine.tenants()),
        "drift_evals": len(drift.history),
        "faults_injected": injected,
        "recovered_faults": recovered,
        "quarantined_faults": quarantined_faults,
        "unrecovered_faults": unrecovered,
        "degraded_syncs": int(snap.get("degraded_syncs", 0)),
        "rank_rejoins": int(snap.get("rank_rejoins", 0)),
        "degraded_sync_parity": degraded_parity,
        **{f"engine_{k}": int(v) for k, v in stats.items()},
    }
    if cfg.durability_dir:
        counters.update({
            "journal_records": int(snap.get("journal_records", 0)),
            "journal_fsyncs": int(snap.get("journal_fsyncs", 0)),
            "snapshots": int(snap.get("snapshots", 0)),
            "snapshot_restores": int(snap.get("snapshot_restores", 0)),
            "replayed_records": int(failover_info.get("replayed", 0)),
            "failovers": int(failover_info.get("failovers", 0)),
            "failover_rpo_records": int(failover_info.get("rpo_records", 0)),
            "failover_state_parity": float(failover_info.get("state_parity", 1.0)),
        })
    timing = {
        "elapsed_s": round(elapsed, 6),
        "tenants_per_sec": round(stats["tenant_rows"] / max(elapsed, 1e-9), 3),
        "update_p50_us": float(kind_lat.get("p50_us", 0.0)),
        "update_p99_us": float(kind_lat.get("p99_us", 0.0)),
        "failover_rto_ms": float(failover_info.get("rto_ms", 0.0)),
    }
    return SoakReport(
        counters=counters,
        timing=timing,
        faults=records,
        slo_breaches=slo_breaches,
        reconciliation=reconciliation,
        config={
            "seed": traffic.seed,
            "steps": traffic.steps,
            "tenants": traffic.tenants,
            "spill_codec": cfg.spill_codec,
            "sync_codec": cfg.sync_codec,
            "window": cfg.window,
            "capacity": cfg.capacity,
            "megabatch_size": cfg.megabatch_size,
            "faults": len(faults),
            "replayed": model.replayed,
            "snapshot_every": cfg.snapshot_every,
            "failover_at": cfg.failover_at,
            "state_digest": final_digest,
        },
        history=history_block,
    )


def run_fleet_soak(
    config: Optional[SoakConfig] = None,
    traffic_model: Optional[TrafficModel] = None,
) -> SoakReport:
    """The fleet soak: one :class:`~torchmetrics_tpu.fleet.FleetController`
    over ``cfg.fleet_hosts`` member engines, driven by the same seeded
    traffic, arming ``host_loss`` (crash a member, lease runs to expiry,
    survivors adopt) and ``host_join`` (late member, rendezvous rebalance)
    at exact steps.

    The verdict is the per-tenant parity gate: after the run, the SAME
    traffic folds into one uninterrupted single-host reference engine, and
    every tenant's state digest must match bitwise —
    ``fleet_failover_parity`` 1.0 means no kill point lost a batch, seated
    a tenant twice, or double-folded a journaled record. Admission runs
    unlimited in fleet mode so the reference cannot fork on shed decisions.
    The ``counters`` block stays a pure function of (config, seed, faults);
    ``migration_us`` is wall-clock and reports under ``timing``."""
    if config is None or config.fleet_hosts is None:
        raise TorchMetricsUserError(
            "run_fleet_soak needs SoakConfig(fleet_hosts=N, durability_dir=...)"
        )
    cfg = config
    from ..fleet import FleetController, LeaseConfig

    model = traffic_model if traffic_model is not None else TrafficModel(cfg.traffic)
    traffic = model.config
    faults = cfg.faults if cfg.faults is not None else FaultSchedule([])
    if faults.last_step >= traffic.steps:
        raise TorchMetricsUserError(
            f"fault schedule reaches step {faults.last_step} but the traffic "
            f"runs only {traffic.steps} steps."
        )
    foreign = sorted({s.kind for s in faults} - {"host_loss", "host_join"})
    if foreign:
        raise TorchMetricsUserError(
            f"the fleet soak arms only host_loss/host_join, got {foreign} — "
            "run the single-host soak for the other kinds"
        )

    clock = {"t": 0.0}
    serving = ServingConfig(
        capacity=cfg.capacity,
        megabatch_size=cfg.megabatch_size,
        spill=True,
        spill_codec=cfg.spill_codec,
        on_error="quarantine",
        max_tenants_per_sec=None,  # parity: admission must match the reference
        window=cfg.window,
        aot_cache_dir=cfg.aot_cache_dir,
        journal_fsync_every=cfg.journal_fsync_every,
        retain_snapshots=cfg.retain_snapshots,
    )

    def _fleet_metric() -> MulticlassAccuracy:
        return _metric(traffic.num_classes)

    records: List[Dict[str, Any]] = []
    pending: Dict[str, List[Dict[str, Any]]] = {k: [] for k in FAULT_KINDS}
    recovered = 0
    unrecovered = 0
    joined_hosts = 0
    events_total = 0
    served = 0
    # arrival-ordered replay source for the reference engine: the exact
    # batches the fleet saw (CPU-test sized traffic — bounded by the run)
    replay_log: List[Tuple[int, tuple, dict]] = []

    def _resolve(kind: str, outcome: str, n: int = 1) -> None:
        for _ in range(n):
            if pending[kind]:
                pending[kind].pop(0)["outcome"] = outcome

    flight = _observability.FlightRecorder(
        dump_dir=os.path.join(cfg.durability_dir, "flightrec"))
    t0 = time.perf_counter()
    with _observability.telemetry_session(
        _observability.TelemetryConfig(
            slo_rules=tuple(default_rules()) + soak_rules(shed_rate_max=cfg.shed_rate_max),
            sinks=(_observability.RingBufferSink(), flight),
            # same virtual-clock keying as the single-host soak: same seed ⇒
            # byte-identical SoakReport.history across fleet runs
            history_clock=lambda: clock["t"],
        )
    ) as rec:
        controller = FleetController(
            _fleet_metric,
            root=os.path.join(cfg.durability_dir, "fleet"),
            hosts=cfg.fleet_hosts,
            serving=serving,
            lease=LeaseConfig(
                heartbeat_interval=cfg.seconds_per_step,
                suspect_after=cfg.fleet_suspect_after,
                dead_after=cfg.fleet_dead_after,
            ),
            clock=lambda: clock["t"],
        )

        def _arm(spec: FaultSpec) -> None:
            nonlocal joined_hosts, recovered, unrecovered
            entry = {
                "step": spec.step, "kind": spec.kind, "target": spec.target,
                "count": spec.count, "outcome": "pending",
                "trace_id": _spans.derive_trace_id(
                    "fault", traffic.seed, spec.step, spec.kind, spec.target),
            }
            records.append(entry)
            pending[spec.kind].append(entry)
            if spec.kind == "host_loss":
                ctx = _spans.enter(
                    "fault", spec.kind, str(spec.target), trace=entry["trace_id"])
                try:
                    controller.kill_host(str(spec.target))
                finally:
                    _spans.exit(ctx)
            elif spec.kind == "host_join":
                host_id = spec.target or f"host-{cfg.fleet_hosts + joined_hosts}"
                joined_hosts += 1
                bad_before = controller.stats["migration_parity_failures"]
                controller.add_host(str(host_id))
                # the rebalance commits synchronously: recovered iff every
                # move landed with per-tenant parity intact
                if controller.stats["migration_parity_failures"] == bad_before:
                    recovered += 1
                    _resolve("host_join", "recovered")
                else:
                    unrecovered += 1
                    _resolve("host_join", "unrecovered")

        def _tick(step: int) -> None:
            nonlocal recovered
            clock["t"] += cfg.seconds_per_step
            controller.heartbeat_all()
            for host_id in controller.poll():
                # survivors adopted the dead host's roster — host_loss done
                recovered += 1
                _resolve("host_loss", "recovered")
            for spec in faults.due(step):
                _arm(spec)
            if cfg.snapshot_every and step and step % cfg.snapshot_every == 0:
                controller.snapshot_all()

        current_step = -1
        for ev in model.events():
            while current_step < ev.step:
                current_step += 1
                _tick(current_step)
            events_total += 1
            tid = int(ev.tenant_id)
            replay_log.append((tid, (ev.batch[0], ev.batch[1]), {}))
            if controller.serve(tid, ev.batch[0], ev.batch[1]):
                served += 1
            else:
                unrecovered += 1  # unlimited admission: a rejection is a bug
        while current_step < traffic.steps - 1:
            current_step += 1
            _tick(current_step)
        # run the leases out so a kill near the end still fails over inside
        # the run (the drain window is part of the soak, not lost coverage)
        drain_ticks = int(cfg.fleet_dead_after / cfg.seconds_per_step) + 2
        for _ in range(drain_ticks):
            if not pending["host_loss"]:
                break
            current_step += 1
            _tick(current_step)
        controller.flush()
        fleet_digests = controller.tenant_digests()
        fleet_counts = {
            tid: controller._hosts[host].engine.tenants()[tid]["update_count"]
            for tid, host in controller.tenants().items()
            if host in controller._hosts and not controller._hosts[host].killed
        }
        elapsed = time.perf_counter() - t0

        # ---- the uninterrupted single-host reference: same batches, same
        # arrival order, one engine, no faults — the parity oracle
        reference = ServingEngine(
            _fleet_metric(),
            dataclasses.replace(serving, journal=None, clock=lambda: clock["t"]),
        )
        for tid, args, kwargs in replay_log:
            reference.update(tid, *args, **kwargs)
        reference.flush()
        from ..fleet import tenant_state_digest as _tsd

        ref_digests = {tid: _tsd(reference, tid) for tid in reference.tenants()}
        ref_counts = {
            tid: info["update_count"] for tid, info in reference.tenants().items()
        }
        parity = 1.0 if fleet_digests == ref_digests else 0.0
        double_counted = sum(
            max(0, int(fleet_counts.get(tid, 0)) - int(ref_counts.get(tid, 0)))
            for tid in set(fleet_counts) | set(ref_counts)
        )
        reference.close()
        fleet_telemetry = controller.telemetry()
        controller.close()

        # ledger close-out: a host_loss whose lease never expired in-run is
        # unrecovered; anything else still pending never fired
        for entry in list(pending["host_loss"]):
            unrecovered += 1
            _resolve("host_loss", "unrecovered")
        for kind_pending in pending.values():
            for entry in kind_pending:
                if entry["outcome"] == "pending":
                    entry["outcome"] = "not_fired"
        if unrecovered:
            flight.dump("unrecovered_faults", extra={"ledger": records})
        injected = sum(1 for r in records if r["outcome"] != "not_fired")

        snap = rec.counters.snapshot().counts
        history_block = rec.history_block(last_n=16)
        reconciliation = {
            "dispatches": int(snap.get("dispatches", 0)),
            "jit_compiles": int(snap.get("jit_compiles", 0)),
            "jit_cache_hits": int(snap.get("jit_cache_hits", 0)),
            "aot_cache_hits": int(snap.get("aot_cache_hits", 0)),
        }
        reconciliation["exact"] = (
            reconciliation["jit_compiles"]
            + reconciliation["jit_cache_hits"]
            + reconciliation["aot_cache_hits"]
            == reconciliation["dispatches"]
        )

    cstats = controller.stats
    migration_parity = 1.0 if cstats["migration_parity_failures"] == 0 else 0.0
    digest_h = hashlib.sha256()
    for tid in sorted(fleet_digests, key=repr):
        digest_h.update(f"{tid!r}={fleet_digests[tid]}".encode("utf-8"))
    counters: Dict[str, Any] = {
        "events": events_total,
        "admitted": served,
        "shed": 0,
        "shed_rate": 0.0,
        "steps": traffic.steps,
        "tenants": len(fleet_digests),
        "hosts": int(cfg.fleet_hosts),
        "hosts_joined": joined_hosts,
        "faults_injected": injected,
        "recovered_faults": recovered,
        "quarantined_faults": 0,
        "unrecovered_faults": unrecovered,
        "fleet_failover_parity": parity,
        "migration_parity": migration_parity,
        "failover_rpo_records": int(cstats["rpo_records"]),
        "double_counted_batches": int(double_counted),
        "host_failovers": int(snap.get("host_failovers", 0)),
        "tenant_migrations": int(snap.get("tenant_migrations", 0)),
        "lease_expiries": int(snap.get("lease_expiries", 0)),
        "fleet_heartbeats": int(snap.get("fleet_heartbeats", 0)),
        "adopted_tenants": int(cstats["adopted_tenants"]),
        "parked_batches": int(cstats["parked"]),
        "replayed_records": int(cstats["failover_replayed"]),
        "snapshots": int(snap.get("snapshots", 0)),
        "snapshot_restores": int(snap.get("snapshot_restores", 0)),
        "journal_records": int(snap.get("journal_records", 0)),
        "journal_fsyncs": int(snap.get("journal_fsyncs", 0)),
    }
    timing = {
        "elapsed_s": round(elapsed, 6),
        "migration_us": float(snap.get("migration_us", 0)),
    }
    return SoakReport(
        counters=counters,
        timing=timing,
        faults=records,
        slo_breaches=[],
        reconciliation=reconciliation,
        config={
            "seed": traffic.seed,
            "steps": traffic.steps,
            "tenants": traffic.tenants,
            "spill_codec": cfg.spill_codec,
            "window": cfg.window,
            "capacity": cfg.capacity,
            "megabatch_size": cfg.megabatch_size,
            "fleet_hosts": cfg.fleet_hosts,
            "faults": len(faults),
            "replayed": model.replayed,
            "snapshot_every": cfg.snapshot_every,
            "state_digest": digest_h.hexdigest(),
        },
        fleet_telemetry=fleet_telemetry,
        history=history_block,
    )
