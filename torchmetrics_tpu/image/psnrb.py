"""PeakSignalNoiseRatioWithBlockedEffect metric class (reference ``image/psnrb.py:29``)."""

from __future__ import annotations

from typing import Any, Tuple, Union

import numpy as np
import jax.numpy as jnp

from ..functional.image.psnrb import _psnrb_compute, _psnrb_update
from ..metric import Metric


class PeakSignalNoiseRatioWithBlockedEffect(Metric):
    """PSNR-B over three scalar sum states (squared error, block effect, count).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.image import PeakSignalNoiseRatioWithBlockedEffect
        >>> preds = (jnp.arange(256, dtype=jnp.float32).reshape(1, 1, 16, 16) * 37 % 97) / 97
        >>> target = (jnp.arange(256, dtype=jnp.float32).reshape(1, 1, 16, 16) * 31 % 89) / 89
        >>> metric = PeakSignalNoiseRatioWithBlockedEffect(data_range=1.0, block_size=8)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(7.6286116, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        data_range: Union[float, Tuple[float, float]],
        block_size: int = 8,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(block_size, int) or block_size < 1:
            raise ValueError("Argument `block_size` should be a positive integer")
        self.block_size = block_size
        self.add_state("sum_squared_error", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros((), jnp.int32), dist_reduce_fx="sum")
        self.add_state("bef", default=np.zeros(()), dist_reduce_fx="sum")
        self.clamp_range = None
        if isinstance(data_range, tuple):
            self.data_range_val = float(data_range[1] - data_range[0])
            self.clamp_range = (float(data_range[0]), float(data_range[1]))
        else:
            self.data_range_val = float(data_range)

    def _batch_state(self, preds, target):
        if self.clamp_range is not None:
            preds = jnp.clip(preds, *self.clamp_range)
            target = jnp.clip(target, *self.clamp_range)
        sum_squared_error, bef, num_obs = _psnrb_update(
            jnp.asarray(preds), jnp.asarray(target), block_size=self.block_size
        )
        return {"sum_squared_error": sum_squared_error, "bef": bef, "total": num_obs.astype(jnp.int32)}

    def _compute(self, state):
        return _psnrb_compute(
            state["sum_squared_error"], state["bef"], state["total"], jnp.asarray(self.data_range_val)
        )
