"""DeepImageStructureAndTextureSimilarity metric class (reference ``image/dists.py:31``)."""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from ..functional.image.dists import DISTSNetwork
from ..metric import Metric


class DeepImageStructureAndTextureSimilarity(Metric):
    """Running-mean DISTS (two scalar sum states). ``weights_path`` points at a
    converted weight pickle; ``pretrained=False`` runs the machinery on deterministic
    random parameters (offline testing)."""

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        reduction: str = "mean",
        weights_path: Optional[str] = None,
        pretrained: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        # only sum states are kept, so per-image 'none' output cannot be honored here
        if reduction not in ("mean", "sum"):
            raise ValueError(f"Argument `reduction` must be one of ('mean', 'sum'), got {reduction}")
        self.reduction = reduction
        self.net = DISTSNetwork(pretrained=pretrained, weights_path=weights_path)
        self.add_state("sum_scores", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def _prepare_inputs(self, preds, target):
        return (jnp.asarray(self.net(preds, target)),), {}

    def _batch_state(self, scores):
        return {"sum_scores": scores.sum(), "total": jnp.asarray(float(scores.shape[0]))}

    def _compute(self, state):
        if self.reduction == "mean":
            return state["sum_scores"] / state["total"]
        return state["sum_scores"]
