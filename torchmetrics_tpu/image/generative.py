"""Generative-model metrics: FID, KID, InceptionScore, MiFID (reference
``image/{fid,kid,inception,mifid}.py``).

State designs mirror the reference: FID keeps O(F^2) feature/cov sums (six psums to
sync — ``image/fid.py:376-382``); KID/IS/MiFID keep cat feature rows. Device-side
accumulation is float32 (TPU f64 is emulated); the final Gaussian/MMD algebra runs in
numpy float64 on host, which bounds the precision loss to the running sums.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from ..metric import HostMetric, Metric
from ._extractors import resolve_feature_extractor


def _extract_features(extractor, imgs, quantize: bool):
    """Run the (possibly FeatureShare-cached) extractor with the CALLER's array
    object as the cache key. Extractors advertising ``accepts_normalize`` do the
    [0,1]→uint8 quantize themselves, so share members with identical settings
    hit the same id-keyed NetworkCache entry instead of each quantizing (and
    thereby re-keying) a private copy. Legacy custom callables keep the
    metric-side quantize."""
    if getattr(extractor, "accepts_normalize", False):
        return extractor(imgs, normalize=quantize)
    if quantize:
        imgs = (jnp.asarray(imgs) * 255).astype(jnp.uint8)
    return extractor(imgs)


def _compute_fid(mu1, sigma1, mu2, sigma2) -> float:
    """Frechet distance between two Gaussians (eigenvalue form, f64 host)."""
    a = float(((mu1 - mu2) ** 2).sum())
    b = float(np.trace(sigma1) + np.trace(sigma2))
    eigvals = np.linalg.eigvals(sigma1 @ sigma2)
    c = float(np.sqrt(eigvals.astype(np.complex128)).real.sum())
    return a + b - 2 * c


class FrechetInceptionDistance(Metric):
    """FID (reference ``image/fid.py:197``).

    ``feature`` is the 2048-d in-tree InceptionV3 (int, converted weights required for
    meaningful values) or any callable ``imgs -> (N, F)`` — e.g. a jitted flax apply.


    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.image import FrechetInceptionDistance
        >>> def tiny_extractor(imgs):
        ...     return imgs.reshape(imgs.shape[0], -1)[:, :8].astype(jnp.float32)
        >>> metric = FrechetInceptionDistance(feature=tiny_extractor, normalize=True)
        >>> imgs_real = (jnp.arange(2 * 3 * 16 * 16, dtype=jnp.float32).reshape(2, 3, 16, 16) * 37 % 97) / 97
        >>> imgs_fake = (jnp.arange(2 * 3 * 16 * 16, dtype=jnp.float32).reshape(2, 3, 16, 16) * 31 % 89) / 89
        >>> metric.update(imgs_real, real=True)
        >>> metric.update(imgs_fake, real=False)
        >>> round(float(metric.compute()), 4)
        1.4741
    """
    # extractor attribute FeatureShare dedupes (reference declares the same name)
    feature_network: str = "inception"

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    _jittable_compute = False

    def __init__(
        self,
        feature: Union[int, Any] = 2048,
        reset_real_features: bool = True,
        normalize: bool = False,
        input_img_size: Tuple[int, int, int] = (3, 299, 299),
        feature_extractor_weights_path: Optional[str] = None,
        antialias: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.normalize = normalize
        self.antialias = antialias
        self.inception, num_features, self.used_custom_model = resolve_feature_extractor(
            feature, normalize, input_img_size,
            weights_path=feature_extractor_weights_path, antialias=antialias,
        )
        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        self.reset_real_features = reset_real_features
        self.num_features = num_features
        mx = (num_features, num_features)
        self.add_state("real_features_sum", jnp.zeros(num_features), dist_reduce_fx="sum")
        self.add_state("real_features_cov_sum", jnp.zeros(mx), dist_reduce_fx="sum")
        self.add_state("real_features_num_samples", jnp.zeros((), jnp.int32), dist_reduce_fx="sum")
        self.add_state("fake_features_sum", jnp.zeros(num_features), dist_reduce_fx="sum")
        self.add_state("fake_features_cov_sum", jnp.zeros(mx), dist_reduce_fx="sum")
        self.add_state("fake_features_num_samples", jnp.zeros((), jnp.int32), dist_reduce_fx="sum")

    def _prepare_inputs(self, imgs, real: bool):
        # fused path: raw images go straight into the jitted update, where
        # quantize+resize+trunk+cov run as ONE dispatch (the tunnel's
        # per-dispatch latency costs ~11% img/s on the split path). The probe
        # looks at the TYPE, not the instance: FeatureShare swaps `inception`
        # for a NetworkCache whose __getattr__ would forward `in_graph_forward`
        # to the wrapped extractor and silently bypass the shared memoization.
        if getattr(type(self.inception), "in_graph_forward", None) is not None and getattr(imgs, "ndim", 0) == 4:
            return (jnp.asarray(imgs), jnp.asarray(bool(real))), {}
        features = jnp.asarray(
            _extract_features(self.inception, imgs, self.normalize and not self.used_custom_model)
        )
        return (features, jnp.asarray(bool(real))), {}

    def _batch_state(self, features, real):
        # `real` arrives as a traced 0/1 scalar so one jitted update serves both
        # branches (multiplicative masking instead of Python control flow)
        if features.ndim == 4:  # raw (N, C, H, W) images: extractor runs in-graph
            if self.normalize and not self.used_custom_model:
                # normalize=True contract: [0,1] floats quantize to uint8 levels
                # exactly like the host path (reference image/fid.py:309)
                features = (features * 255).astype(jnp.uint8)
            features = self.inception.in_graph_forward(features)
        f = features.astype(jnp.float32)
        fsum = f.sum(axis=0)
        cov = jnp.matmul(f.T, f, precision="highest")
        n = jnp.asarray(f.shape[0], jnp.int32)
        mask = real.astype(jnp.float32)
        n_mask = real.astype(jnp.int32)
        return {
            "real_features_sum": fsum * mask,
            "real_features_cov_sum": cov * mask,
            "real_features_num_samples": n * n_mask,
            "fake_features_sum": fsum * (1 - mask),
            "fake_features_cov_sum": cov * (1 - mask),
            "fake_features_num_samples": n * (1 - n_mask),
        }

    def _compute(self, state):
        n_real = int(state["real_features_num_samples"])
        n_fake = int(state["fake_features_num_samples"])
        if n_real < 2 or n_fake < 2:
            raise RuntimeError("More than one sample is required for both the real and fake distributed to compute FID")
        mean_real = np.asarray(state["real_features_sum"], np.float64) / n_real
        mean_fake = np.asarray(state["fake_features_sum"], np.float64) / n_fake
        cov_real = (np.asarray(state["real_features_cov_sum"], np.float64) - n_real * np.outer(mean_real, mean_real)) / (n_real - 1)
        cov_fake = (np.asarray(state["fake_features_cov_sum"], np.float64) - n_fake * np.outer(mean_fake, mean_fake)) / (n_fake - 1)
        return jnp.asarray(_compute_fid(mean_real, cov_real, mean_fake, cov_fake), jnp.float32)

    def reset(self) -> None:
        if not self.reset_real_features:
            keep = {
                k: self._state[k]
                for k in ("real_features_sum", "real_features_cov_sum", "real_features_num_samples")
            }
            super().reset()
            self._state.update(keep)
        else:
            super().reset()


def maximum_mean_discrepancy(k_xx, k_xy, k_yy) -> np.ndarray:
    m = k_xx.shape[0]
    kt_xx_sum = (k_xx.sum(axis=-1) - np.diag(k_xx)).sum()
    kt_yy_sum = (k_yy.sum(axis=-1) - np.diag(k_yy)).sum()
    k_xy_sum = k_xy.sum()
    value = (kt_xx_sum + kt_yy_sum) / (m * (m - 1))
    return value - 2 * k_xy_sum / (m**2)


def poly_kernel(f1, f2, degree: int = 3, gamma: Optional[float] = None, coef: float = 1.0) -> np.ndarray:
    if gamma is None:
        gamma = 1.0 / f1.shape[1]
    return (f1 @ f2.T * gamma + coef) ** degree


def poly_mmd(f_real, f_fake, degree: int = 3, gamma: Optional[float] = None, coef: float = 1.0) -> np.ndarray:
    k_11 = poly_kernel(f_real, f_real, degree, gamma, coef)
    k_22 = poly_kernel(f_fake, f_fake, degree, gamma, coef)
    k_12 = poly_kernel(f_real, f_fake, degree, gamma, coef)
    return maximum_mean_discrepancy(k_11, k_12, k_22)


class KernelInceptionDistance(HostMetric):
    """KID (reference ``image/kid.py:71``): polynomial-kernel MMD over random feature
    subsets; cat feature states."""
    # extractor attribute FeatureShare dedupes (reference declares the same name)
    feature_network: str = "inception"

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    _jittable_compute = False

    def __init__(
        self,
        feature: Union[int, Any] = 2048,
        subsets: int = 100,
        subset_size: int = 1000,
        degree: int = 3,
        gamma: Optional[float] = None,
        coef: float = 1.0,
        reset_real_features: bool = True,
        normalize: bool = False,
        feature_extractor_weights_path: Optional[str] = None,
        seed: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.inception, self.num_features, self.used_custom_model = resolve_feature_extractor(
            feature, normalize, weights_path=feature_extractor_weights_path
        )
        # subset sampling seed: the reference relies on torch's global RNG (users
        # control it via torch.manual_seed); an explicit kwarg is the jax-idiomatic
        # equivalent. None -> fresh entropy per compute, like the reference default.
        self.seed = seed
        if not (isinstance(subsets, int) and subsets > 0):
            raise ValueError("Argument `subsets` expected to be integer larger than 0")
        self.subsets = subsets
        if not (isinstance(subset_size, int) and subset_size > 0):
            raise ValueError("Argument `subset_size` expected to be integer larger than 0")
        self.subset_size = subset_size
        if not (isinstance(degree, int) and degree > 0):
            raise ValueError("Argument `degree` expected to be integer larger than 0")
        self.degree = degree
        if gamma is not None and not (isinstance(gamma, float) and gamma > 0):
            raise ValueError("Argument `gamma` expected to be `None` or float larger than 0")
        self.gamma = gamma
        if not (isinstance(coef, float) and coef > 0):
            raise ValueError("Argument `coef` expected to be float larger than 0")
        self.coef = coef
        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        self.reset_real_features = reset_real_features
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.normalize = normalize
        self.add_state("real_features", default=[], dist_reduce_fx="cat")
        self.add_state("fake_features", default=[], dist_reduce_fx="cat")

    def _host_batch_state(self, imgs, real: bool):
        features = jnp.asarray(
            _extract_features(self.inception, imgs, self.normalize and not self.used_custom_model)
        )
        empty = jnp.zeros((0, features.shape[-1]), features.dtype)
        if real:
            return {"real_features": features, "fake_features": empty}
        return {"fake_features": features, "real_features": empty}

    def _compute(self, state) -> Tuple[jnp.ndarray, jnp.ndarray]:
        real_features = np.asarray(state["real_features"], np.float64)
        fake_features = np.asarray(state["fake_features"], np.float64)
        if real_features.shape[0] < self.subset_size or fake_features.shape[0] < self.subset_size:
            raise ValueError("Argument `subset_size` should be smaller than the number of samples")
        rng = np.random.default_rng(self.seed)
        kid_scores = []
        for _ in range(self.subsets):
            f_real = real_features[rng.permutation(real_features.shape[0])[: self.subset_size]]
            f_fake = fake_features[rng.permutation(fake_features.shape[0])[: self.subset_size]]
            kid_scores.append(poly_mmd(f_real, f_fake, self.degree, self.gamma, self.coef))
        kid = np.asarray(kid_scores)
        return jnp.asarray(kid.mean(), jnp.float32), jnp.asarray(kid.std(ddof=0), jnp.float32)

    def reset(self) -> None:
        if not self.reset_real_features:
            keep = list(self._state["real_features"])
            super().reset()
            self._state["real_features"] = keep
        else:
            super().reset()


class InceptionScore(Metric):
    """Inception Score (reference ``image/inception.py:35``): exp KL between
    conditional and marginal label distributions over splits; cat logit states."""
    # extractor attribute FeatureShare dedupes (reference declares the same name)
    feature_network: str = "inception"

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    _jittable_compute = False

    def __init__(
        self,
        feature: Union[str, int, Any] = "logits_unbiased",
        splits: int = 10,
        normalize: bool = False,
        feature_extractor_weights_path: Optional[str] = None,
        seed: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.seed = seed  # shuffle seed; None -> fresh entropy (reference: torch global RNG)
        self.normalize = normalize
        if feature == "logits_unbiased":
            raise ModuleNotFoundError(
                "InceptionScore's default `logits_unbiased` head needs the pretrained InceptionV3 "
                "classifier, whose weights cannot be downloaded in this air-gapped environment. "
                "Pass a custom callable producing class logits instead."
            )
        self.inception, self.num_features, self.used_custom_model = resolve_feature_extractor(
            feature, normalize, weights_path=feature_extractor_weights_path
        )
        if not (isinstance(splits, int) and splits > 0):
            raise ValueError("Argument `splits` expected to be integer larger than 0")
        self.splits = splits
        self.add_state("features", default=[], dist_reduce_fx="cat")

    def _prepare_inputs(self, imgs):
        imgs = jnp.asarray(imgs)
        # the reference byte-converts for custom extractors too (inception.py:151 has
        # no used_custom_model check, unlike FID/KID) — quirk preserved for parity
        return (jnp.asarray(_extract_features(self.inception, imgs, self.normalize)),), {}

    def _batch_state(self, features):
        return {"features": features}

    def _compute(self, state) -> Tuple[jnp.ndarray, jnp.ndarray]:
        features = np.asarray(state["features"], np.float64)
        idx = np.random.default_rng(self.seed).permutation(features.shape[0])
        features = features[idx]
        shifted = features - features.max(axis=1, keepdims=True)
        log_prob = shifted - np.log(np.exp(shifted).sum(axis=1, keepdims=True))
        prob = np.exp(log_prob)
        kl_scores = []
        for chunk_p, chunk_lp in zip(np.array_split(prob, self.splits), np.array_split(log_prob, self.splits)):
            mean_prob = chunk_p.mean(axis=0, keepdims=True)
            kl = chunk_p * (chunk_lp - np.log(mean_prob))
            kl_scores.append(np.exp(kl.sum(axis=1).mean()))
        kl = np.asarray(kl_scores)
        return jnp.asarray(kl.mean(), jnp.float32), jnp.asarray(kl.std(), jnp.float32)


class MemorizationInformedFrechetInceptionDistance(HostMetric):
    """MiFID (reference ``image/mifid.py:67``): FID penalized by the memorization
    (minimum cosine distance) between fake and real features; cat feature states."""
    # extractor attribute FeatureShare dedupes (reference declares the same name)
    feature_network: str = "inception"

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    _jittable_compute = False

    def __init__(
        self,
        feature: Union[int, Any] = 2048,
        reset_real_features: bool = True,
        normalize: bool = False,
        cosine_distance_eps: float = 0.1,
        feature_extractor_weights_path: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.inception, self.num_features, self.used_custom_model = resolve_feature_extractor(
            feature, normalize, weights_path=feature_extractor_weights_path
        )
        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        self.reset_real_features = reset_real_features
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.normalize = normalize
        if not (isinstance(cosine_distance_eps, float) and 1 > cosine_distance_eps > 0):
            raise ValueError("Argument `cosine_distance_eps` expected to be a float greater than 0 and less than 1")
        self.cosine_distance_eps = cosine_distance_eps
        self.add_state("real_features", default=[], dist_reduce_fx="cat")
        self.add_state("fake_features", default=[], dist_reduce_fx="cat")

    def _host_batch_state(self, imgs, real: bool):
        features = jnp.asarray(
            _extract_features(self.inception, imgs, self.normalize and not self.used_custom_model)
        )
        empty = jnp.zeros((0, features.shape[-1]), features.dtype)
        if real:
            return {"real_features": features, "fake_features": empty}
        return {"fake_features": features, "real_features": empty}

    def _compute(self, state):
        real = np.asarray(state["real_features"], np.float64)
        fake = np.asarray(state["fake_features"], np.float64)
        mean_real, mean_fake = real.mean(axis=0), fake.mean(axis=0)
        cov_real = np.cov(real.T)
        cov_fake = np.cov(fake.T)
        fid = _compute_fid(mean_real, cov_real, mean_fake, cov_fake)
        # memorization distance: per real row, min cosine distance to the fake set
        # (zero rows dropped — reference mifid.py:37-48)
        real_nz = real[real.sum(axis=1) != 0]
        fake_nz = fake[fake.sum(axis=1) != 0]
        norm_r = real_nz / np.linalg.norm(real_nz, axis=1, keepdims=True)
        norm_f = fake_nz / np.linalg.norm(fake_nz, axis=1, keepdims=True)
        d = 1.0 - np.abs(norm_r @ norm_f.T)
        mean_min_d = d.min(axis=1).mean()
        distance = mean_min_d if mean_min_d < self.cosine_distance_eps else 1.0
        value = fid / (distance + 10e-15) if fid > 1e-8 else 0.0
        return jnp.asarray(value, jnp.float32)

    def reset(self) -> None:
        if not self.reset_real_features:
            keep = list(self._state["real_features"])
            super().reset()
            self._state["real_features"] = keep
        else:
            super().reset()
