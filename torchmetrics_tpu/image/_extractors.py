"""Feature extractors for generative-model metrics (reference ``image/fid.py:45-171``).

The reference embeds torch-fidelity's ``NoTrainInceptionV3`` (downloaded weights).
Here the contract is a plain callable ``imgs -> (N, num_features)``; the in-tree
``InceptionV3Features`` is a jitted jnp InceptionV3 forward whose parameters load from
a converted torch checkpoint (no network access is assumed — conversion happens
offline via ``convert_torchvision_inception_weights``). Custom extractors (any
callable, e.g. a jitted flax apply) plug into FID/KID/IS/MiFID exactly like the
reference's ``feature: Module`` path.
"""

from __future__ import annotations

import pickle
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..functional.image._resize import resize_bilinear_antialias, resize_bilinear_tf1


def _conv(x, w, stride=1, padding="SAME"):
    # bf16 trunk runs at MXU-native precision; the f32 parity trunk pins HIGHEST so
    # XLA cannot silently drop the conv stack to bf16 passes
    precision = lax.Precision.DEFAULT if x.dtype == jnp.bfloat16 else lax.Precision.HIGHEST
    return lax.conv_general_dilated(
        x, w, (stride, stride), padding, dimension_numbers=("NCHW", "OIHW", "NCHW"),
        precision=precision,
    )


def _bn(x, scale, bias, mean, var, eps=1e-3):
    inv = scale / jnp.sqrt(var + eps)
    return x * inv[None, :, None, None] + (bias - mean * inv)[None, :, None, None]


def _fold_bn(params):
    """Fold inference BN into conv weights once at load: ``relu(conv(x,w)*inv + s)``
    == ``relu(conv(x, w*inv) + s)``. Removes one elementwise pass per conv
    (~+3% trunk throughput, measured) and shrinks the param pytree. Folding in
    f32 regardless of trunk dtype keeps the bf16 path's weights rounded once."""

    def fold(p):
        if isinstance(p, dict) and "b" in p:  # already folded (e.g. re-saved params)
            return p
        if isinstance(p, dict) and "w" in p:
            inv = (p["scale"] / jnp.sqrt(p["var"] + 1e-3)).astype(jnp.float32)
            w = p["w"].astype(jnp.float32) * inv[:, None, None, None]
            b = (p["bias"] - p["mean"] * inv).astype(jnp.float32)
            return {"w": w.astype(p["w"].dtype), "b": b.astype(p["w"].dtype)}
        if isinstance(p, dict):
            return {k: fold(v) for k, v in p.items()}
        return p

    return fold(params)


def _basic_conv(x, p, stride=1, padding="SAME"):
    if "b" in p:  # BN-folded form (production path)
        return jax.nn.relu(_conv(x, p["w"], stride, padding) + p["b"][None, :, None, None])
    x = _conv(x, p["w"], stride, padding)
    return jax.nn.relu(_bn(x, p["scale"], p["bias"], p["mean"], p["var"]))


def _maxpool(x, window=3, stride=2):
    return lax.reduce_window(x, -jnp.inf, lax.max, (1, 1, window, window), (1, 1, stride, stride), "VALID")


def _avgpool(x, window=3, stride=1, padding="SAME"):
    # count_include_pad semantics (torchvision inception): constant window divisor —
    # a reduce_window over a ones constant also traps XLA in slow constant folding
    summed = lax.reduce_window(x, 0.0, lax.add, (1, 1, window, window), (1, 1, stride, stride), padding)
    return summed / (window * window)


def _inception_a(x, p):
    b1 = _basic_conv(x, p["b1"])
    b5 = _basic_conv(_basic_conv(x, p["b5_1"]), p["b5_2"])
    b3 = _basic_conv(_basic_conv(_basic_conv(x, p["b3_1"]), p["b3_2"]), p["b3_3"])
    bp = _basic_conv(_avgpool(x), p["pool"])
    return jnp.concatenate([b1, b5, b3, bp], axis=1)


def _inception_b(x, p):
    b3 = _basic_conv(x, p["b3"], stride=2, padding="VALID")
    b3d = _basic_conv(_basic_conv(_basic_conv(x, p["b3d_1"]), p["b3d_2"]), p["b3d_3"], stride=2, padding="VALID")
    bp = _maxpool(x)
    return jnp.concatenate([b3, b3d, bp], axis=1)


def _inception_c(x, p):
    b1 = _basic_conv(x, p["b1"])
    b7 = _basic_conv(_basic_conv(_basic_conv(x, p["b7_1"]), p["b7_2"]), p["b7_3"])
    b7d = x
    for key in ("b7d_1", "b7d_2", "b7d_3", "b7d_4", "b7d_5"):
        b7d = _basic_conv(b7d, p[key])
    bp = _basic_conv(_avgpool(x), p["pool"])
    return jnp.concatenate([b1, b7, b7d, bp], axis=1)


def _inception_d(x, p):
    b3 = _basic_conv(_basic_conv(x, p["b3_1"]), p["b3_2"], stride=2, padding="VALID")
    b7 = x
    for key in ("b7_1", "b7_2", "b7_3"):
        b7 = _basic_conv(b7, p[key])
    b7 = _basic_conv(b7, p["b7_4"], stride=2, padding="VALID")
    bp = _maxpool(x)
    return jnp.concatenate([b3, b7, bp], axis=1)


def _inception_e(x, p):
    b1 = _basic_conv(x, p["b1"])
    b3 = _basic_conv(x, p["b3_1"])
    b3 = jnp.concatenate([_basic_conv(b3, p["b3_2a"]), _basic_conv(b3, p["b3_2b"])], axis=1)
    b3d = _basic_conv(_basic_conv(x, p["b3d_1"]), p["b3d_2"])
    b3d = jnp.concatenate([_basic_conv(b3d, p["b3d_3a"]), _basic_conv(b3d, p["b3d_3b"])], axis=1)
    bp = _basic_conv(_avgpool(x), p["pool"])
    return jnp.concatenate([b1, b3, b3d, bp], axis=1)


def _inception_forward(params: Dict[str, Any], imgs: jnp.ndarray) -> jnp.ndarray:
    """InceptionV3 pool3 features ``(N, 2048)`` from NCHW images on the 0-255 scale
    at 299x299.

    Runs in the dtype of ``imgs`` (f32 parity trunk or bf16 MXU trunk); the global
    average pool at the end accumulates in f32 either way."""
    params = jax.tree.map(lambda p: p.astype(imgs.dtype), params)
    # torch-fidelity trunk normalization (reference image/fid.py:103): (x - 128)/128
    # on 0-255 input — NOT the torchvision (x - 127.5)/127.5 centering
    x = (imgs - 128.0) / 128.0
    x = _basic_conv(x, params["stem1"], stride=2, padding="VALID")
    x = _basic_conv(x, params["stem2"], padding="VALID")
    x = _basic_conv(x, params["stem3"])
    x = _maxpool(x)
    x = _basic_conv(x, params["stem4"], padding="VALID")
    x = _basic_conv(x, params["stem5"], padding="VALID")
    x = _maxpool(x)
    for key in ("mixed_a1", "mixed_a2", "mixed_a3"):
        x = _inception_a(x, params[key])
    x = _inception_b(x, params["mixed_b"])
    for key in ("mixed_c1", "mixed_c2", "mixed_c3", "mixed_c4"):
        x = _inception_c(x, params[key])
    x = _inception_d(x, params["mixed_d"])
    x = _inception_e(x, params["mixed_e1"])
    x = _inception_e(x, params["mixed_e2"])
    return x.astype(jnp.float32).mean(axis=(2, 3))  # global average pool -> (N, 2048), f32 accumulation


class InceptionV3Features:
    """Jitted InceptionV3 pool3 feature extractor.

    Parameters load from a converted checkpoint (pickle of the jnp param pytree). No
    pretrained weights ship in-tree and none can be downloaded in an air-gapped pod.
    Random init is available for pipeline tests.

    ``compute_dtype``: ``"float32"`` (default, ``Precision.HIGHEST`` parity trunk) or
    ``"bfloat16"`` (MXU-native trunk, ~MXU-peak convs; feature means still accumulate
    in f32). ``resize_antialias`` selects between the reference extractor's two input
    resize forks (reference ``image/fid.py:88-101``): ``True`` (its default) is torch
    ``F.interpolate(..., antialias=True)`` — the PIL-style triangle filter; ``False``
    is torch-fidelity's TF1-legacy bilinear (``half_pixel_centers=False``), the fork
    that reproduces the original TF1 FID resize. Both are parity-tested against their
    torch anchors in ``tests/test_resize_parity.py``.
    """

    num_features = 2048
    # generative metrics pass their normalize flag THROUGH the call instead of
    # quantizing a private copy first: under FeatureShare the id-keyed cache
    # then sees every member's ORIGINAL input buffer (one trunk forward per
    # batch, as the wrapper documents)
    accepts_normalize = True

    def __init__(
        self,
        weights_path: Optional[str] = None,
        seed: int = 0,
        compute_dtype: str = "float32",
        resize_antialias: bool = True,
    ) -> None:
        if weights_path is not None:
            with open(weights_path, "rb") as f:
                self.params = jax.tree.map(jnp.asarray, pickle.load(f))
        else:
            self.params = self._random_params(jax.random.PRNGKey(seed))
        self.params = _fold_bn(self.params)
        self.compute_dtype = jnp.dtype(compute_dtype)
        if self.compute_dtype != jnp.float32:
            # cast once here; the in-forward cast is then a no-op instead of a
            # per-batch ~24M-param conversion
            self.params = jax.tree.map(lambda p: p.astype(self.compute_dtype), self.params)
        self.resize_antialias = resize_antialias
        self._apply = jax.jit(self.in_graph_forward)

    def in_graph_forward(self, imgs) -> jnp.ndarray:
        """Fully traceable preprocess+trunk: safe to call INSIDE a caller's jit.

        Integer input is taken as 0-255; float input as normalized [0, 1] (scaled
        back to 0-255 here — the trunk and both resize forks run on the 0-255 scale
        exactly like the reference extractor, whose uint8 contract means resize and
        normalization both see 0-255 values). FID fuses this into its jitted update
        (one dispatch per batch instead of ~6: measured +11% img/s through the
        dispatch-latency-bound TPU tunnel)."""
        imgs = jnp.asarray(imgs)
        if jnp.issubdtype(imgs.dtype, jnp.integer):
            imgs = imgs.astype(jnp.float32)
        else:
            imgs = imgs.astype(jnp.float32) * 255.0
        if imgs.shape[-2:] != (299, 299):
            # resize in f32 regardless of trunk dtype: interpolation parity is what
            # makes FID comparable across extractors (SURVEY §7 hard part)
            # both forks mirror the reference extractor (image/fid.py:88-101):
            # antialias=True -> torch F.interpolate(..., antialias=True);
            # antialias=False -> torch-fidelity's TF1-legacy bilinear
            if self.resize_antialias:
                imgs = resize_bilinear_antialias(imgs, (299, 299))
            else:
                imgs = resize_bilinear_tf1(imgs, (299, 299))
        return _inception_forward(self.params, imgs.astype(self.compute_dtype))

    def __call__(self, imgs, normalize: bool = False) -> jnp.ndarray:
        imgs = jnp.asarray(imgs)
        if normalize:  # [0,1] floats quantize to uint8 levels (reference image/fid.py:309)
            imgs = (imgs * 255).astype(jnp.uint8)
        return self._apply(imgs)

    # ---------------------------------------------------------------- params

    @staticmethod
    def _conv_params(key, c_in, c_out, kh, kw):
        k1, _ = jax.random.split(key)
        fan_in = c_in * kh * kw
        return {
            "w": jax.random.normal(k1, (c_out, c_in, kh, kw), jnp.float32) / np.sqrt(fan_in),
            "scale": jnp.ones(c_out),
            "bias": jnp.zeros(c_out),
            "mean": jnp.zeros(c_out),
            "var": jnp.ones(c_out),
        }

    @classmethod
    def _random_params(cls, key) -> Dict[str, Any]:
        keys = iter(jax.random.split(key, 128))
        cp = cls._conv_params

        def block_a(c_in, pool_features):
            return {
                "b1": cp(next(keys), c_in, 64, 1, 1),
                "b5_1": cp(next(keys), c_in, 48, 1, 1),
                "b5_2": cp(next(keys), 48, 64, 5, 5),
                "b3_1": cp(next(keys), c_in, 64, 1, 1),
                "b3_2": cp(next(keys), 64, 96, 3, 3),
                "b3_3": cp(next(keys), 96, 96, 3, 3),
                "pool": cp(next(keys), c_in, pool_features, 1, 1),
            }

        def block_c(c_in, c7):
            return {
                "b1": cp(next(keys), c_in, 192, 1, 1),
                "b7_1": cp(next(keys), c_in, c7, 1, 1),
                "b7_2": cp(next(keys), c7, c7, 1, 7),
                "b7_3": cp(next(keys), c7, 192, 7, 1),
                "b7d_1": cp(next(keys), c_in, c7, 1, 1),
                "b7d_2": cp(next(keys), c7, c7, 7, 1),
                "b7d_3": cp(next(keys), c7, c7, 1, 7),
                "b7d_4": cp(next(keys), c7, c7, 7, 1),
                "b7d_5": cp(next(keys), c7, 192, 1, 7),
                "pool": cp(next(keys), c_in, 192, 1, 1),
            }

        def block_e(c_in):
            return {
                "b1": cp(next(keys), c_in, 320, 1, 1),
                "b3_1": cp(next(keys), c_in, 384, 1, 1),
                "b3_2a": cp(next(keys), 384, 384, 1, 3),
                "b3_2b": cp(next(keys), 384, 384, 3, 1),
                "b3d_1": cp(next(keys), c_in, 448, 1, 1),
                "b3d_2": cp(next(keys), 448, 384, 3, 3),
                "b3d_3a": cp(next(keys), 384, 384, 1, 3),
                "b3d_3b": cp(next(keys), 384, 384, 3, 1),
                "pool": cp(next(keys), c_in, 192, 1, 1),
            }

        return {
            "stem1": cp(next(keys), 3, 32, 3, 3),
            "stem2": cp(next(keys), 32, 32, 3, 3),
            "stem3": cp(next(keys), 32, 64, 3, 3),
            "stem4": cp(next(keys), 64, 80, 1, 1),
            "stem5": cp(next(keys), 80, 192, 3, 3),
            "mixed_a1": block_a(192, 32),
            "mixed_a2": block_a(256, 64),
            "mixed_a3": block_a(288, 64),
            "mixed_b": {
                "b3": cp(next(keys), 288, 384, 3, 3),
                "b3d_1": cp(next(keys), 288, 64, 1, 1),
                "b3d_2": cp(next(keys), 64, 96, 3, 3),
                "b3d_3": cp(next(keys), 96, 96, 3, 3),
            },
            "mixed_c1": block_c(768, 128),
            "mixed_c2": block_c(768, 160),
            "mixed_c3": block_c(768, 160),
            "mixed_c4": block_c(768, 192),
            "mixed_d": {
                "b3_1": cp(next(keys), 768, 192, 1, 1),
                "b3_2": cp(next(keys), 192, 320, 3, 3),
                "b7_1": cp(next(keys), 768, 192, 1, 1),
                "b7_2": cp(next(keys), 192, 192, 1, 7),
                "b7_3": cp(next(keys), 192, 192, 7, 1),
                "b7_4": cp(next(keys), 192, 192, 3, 3),
            },
            "mixed_e1": block_e(1280),
            "mixed_e2": block_e(2048),
        }


def convert_torchvision_inception_weights(state_dict: Dict[str, Any], out_path: str) -> None:
    """Convert a torchvision ``inception_v3`` state_dict into the pickle pytree this
    extractor loads (run offline where the torch weights are available)."""
    import numpy as _np

    def conv(prefix):
        return {
            "w": _np.asarray(state_dict[f"{prefix}.conv.weight"]),
            "scale": _np.asarray(state_dict[f"{prefix}.bn.weight"]),
            "bias": _np.asarray(state_dict[f"{prefix}.bn.bias"]),
            "mean": _np.asarray(state_dict[f"{prefix}.bn.running_mean"]),
            "var": _np.asarray(state_dict[f"{prefix}.bn.running_var"]),
        }

    params = {
        "stem1": conv("Conv2d_1a_3x3"),
        "stem2": conv("Conv2d_2a_3x3"),
        "stem3": conv("Conv2d_2b_3x3"),
        "stem4": conv("Conv2d_3b_1x1"),
        "stem5": conv("Conv2d_4a_3x3"),
    }
    for i, name in enumerate(("Mixed_5b", "Mixed_5c", "Mixed_5d"), start=1):
        params[f"mixed_a{i}"] = {
            "b1": conv(f"{name}.branch1x1"),
            "b5_1": conv(f"{name}.branch5x5_1"),
            "b5_2": conv(f"{name}.branch5x5_2"),
            "b3_1": conv(f"{name}.branch3x3dbl_1"),
            "b3_2": conv(f"{name}.branch3x3dbl_2"),
            "b3_3": conv(f"{name}.branch3x3dbl_3"),
            "pool": conv(f"{name}.branch_pool"),
        }
    params["mixed_b"] = {
        "b3": conv("Mixed_6a.branch3x3"),
        "b3d_1": conv("Mixed_6a.branch3x3dbl_1"),
        "b3d_2": conv("Mixed_6a.branch3x3dbl_2"),
        "b3d_3": conv("Mixed_6a.branch3x3dbl_3"),
    }
    for i, name in enumerate(("Mixed_6b", "Mixed_6c", "Mixed_6d", "Mixed_6e"), start=1):
        params[f"mixed_c{i}"] = {
            "b1": conv(f"{name}.branch1x1"),
            "b7_1": conv(f"{name}.branch7x7_1"),
            "b7_2": conv(f"{name}.branch7x7_2"),
            "b7_3": conv(f"{name}.branch7x7_3"),
            "b7d_1": conv(f"{name}.branch7x7dbl_1"),
            "b7d_2": conv(f"{name}.branch7x7dbl_2"),
            "b7d_3": conv(f"{name}.branch7x7dbl_3"),
            "b7d_4": conv(f"{name}.branch7x7dbl_4"),
            "b7d_5": conv(f"{name}.branch7x7dbl_5"),
            "pool": conv(f"{name}.branch_pool"),
        }
    params["mixed_d"] = {
        "b3_1": conv("Mixed_7a.branch3x3_1"),
        "b3_2": conv("Mixed_7a.branch3x3_2"),
        "b7_1": conv("Mixed_7a.branch7x7x3_1"),
        "b7_2": conv("Mixed_7a.branch7x7x3_2"),
        "b7_3": conv("Mixed_7a.branch7x7x3_3"),
        "b7_4": conv("Mixed_7a.branch7x7x3_4"),
    }
    for i, name in enumerate(("Mixed_7b", "Mixed_7c"), start=1):
        params[f"mixed_e{i}"] = {
            "b1": conv(f"{name}.branch1x1"),
            "b3_1": conv(f"{name}.branch3x3_1"),
            "b3_2a": conv(f"{name}.branch3x3_2a"),
            "b3_2b": conv(f"{name}.branch3x3_2b"),
            "b3d_1": conv(f"{name}.branch3x3dbl_1"),
            "b3d_2": conv(f"{name}.branch3x3dbl_2"),
            "b3d_3a": conv(f"{name}.branch3x3dbl_3a"),
            "b3d_3b": conv(f"{name}.branch3x3dbl_3b"),
            "pool": conv(f"{name}.branch_pool"),
        }
    with open(out_path, "wb") as f:
        pickle.dump(params, f)


def resolve_feature_extractor(
    feature,
    normalize: bool,
    input_img_size: Tuple[int, int, int] = (3, 299, 299),
    weights_path: Optional[str] = None,
    antialias: bool = True,
) -> Tuple[Callable, int, bool]:
    """Reference ``feature: int | Module`` resolution: int selects the in-tree
    InceptionV3 (converted weights REQUIRED — random features would yield plausible
    but meaningless scores), any callable is used as-is. ``antialias`` picks the
    reference extractor's resize fork (``image/fid.py:88-101``).
    Returns (extractor, num_features, used_custom)."""
    if isinstance(feature, int):
        if feature != 2048:
            raise ValueError(
                "The in-tree InceptionV3 extractor exposes the 2048-d pool3 features; "
                f"got feature={feature}. Pass a custom callable for other dimensions."
            )
        if weights_path is None:
            raise ModuleNotFoundError(
                "The integer `feature` selector needs converted InceptionV3 weights, which "
                "cannot be downloaded in an air-gapped environment. Convert them offline with "
                "`convert_torchvision_inception_weights` and pass "
                "`feature_extractor_weights_path`, or pass a custom extractor callable "
                "(e.g. `InceptionV3Features()` explicitly for random-weight throughput tests)."
            )
        return InceptionV3Features(weights_path, resize_antialias=antialias), 2048, False
    if callable(feature):
        num_features = getattr(feature, "num_features", None)
        if num_features is None:
            dummy = (
                jnp.zeros((1, *input_img_size), jnp.float32)
                if normalize
                else jnp.zeros((1, *input_img_size), jnp.uint8)
            )
            # eval_shape: shape inference without execution or device→host readback
            try:
                num_features = int(jax.eval_shape(feature, dummy).shape[-1])
            except Exception:  # extractor not traceable (host-side model): run it
                num_features = int(np.asarray(feature(dummy)).shape[-1])
        return feature, int(num_features), True
    raise TypeError("Got unknown input to argument `feature`")
