"""LearnedPerceptualImagePatchSimilarity metric class (reference ``image/lpip.py:41``)."""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from ..functional.image.lpips import LPIPSNetwork
from ..metric import Metric


class LearnedPerceptualImagePatchSimilarity(Metric):
    """Running-mean LPIPS (two scalar sum states). ``weights_path`` points at a
    converted weight pickle; ``pretrained=False`` runs the machinery on deterministic
    random parameters (offline testing)."""
    # extractor attribute FeatureShare dedupes (reference declares the same name)
    feature_network: str = "net"

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        net_type: str = "alex",
        reduction: str = "mean",
        normalize: bool = False,
        weights_path: Optional[str] = None,
        pretrained: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        valid_reduction = ("mean", "sum")
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction} but got {reduction}")
        self.reduction = reduction
        if not isinstance(normalize, bool):
            raise ValueError(f"Argument `normalize` should be a bool but got {normalize}")
        self.normalize = normalize
        self.net = LPIPSNetwork(net_type, pretrained=pretrained, weights_path=weights_path)
        self.add_state("sum_scores", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def _prepare_inputs(self, img1, img2):
        return (jnp.asarray(self.net(img1, img2, normalize=self.normalize)),), {}

    def _batch_state(self, loss):
        return {"sum_scores": loss.sum(), "total": jnp.asarray(float(loss.shape[0]))}

    def _compute(self, state):
        if self.reduction == "mean":
            return state["sum_scores"] / state["total"]
        return state["sum_scores"]
