"""PeakSignalNoiseRatio metric class (reference ``image/psnr.py:32``)."""

from __future__ import annotations

from collections.abc import Sequence
from typing import Any, Optional, Tuple, Union

import numpy as np
import jax.numpy as jnp

from ..functional.image.psnr import _psnr_compute, _psnr_update
from ..metric import Metric
from ..utilities.prints import rank_zero_warn


class PeakSignalNoiseRatio(Metric):
    """PSNR over accumulated squared error. ``dim=None`` keeps two scalar sum states;
    with ``dim`` set, per-update error tensors are concatenated (cat states).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.image import PeakSignalNoiseRatio
        >>> preds = jnp.asarray([[0.0, 1.0], [2.0, 3.0]])
        >>> target = jnp.asarray([[3.0, 2.0], [1.0, 0.0]])
        >>> metric = PeakSignalNoiseRatio(data_range=3.0)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(2.552725, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        data_range: Union[float, Tuple[float, float]],
        base: float = 10.0,
        reduction: Optional[str] = "elementwise_mean",
        dim: Optional[Union[int, Tuple[int, ...]]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if dim is None and reduction != "elementwise_mean":
            rank_zero_warn(f"The `reduction={reduction}` will not have any effect when `dim` is None.")
        if dim is None:
            self.add_state("sum_squared_error", default=np.zeros(()), dist_reduce_fx="sum")
            self.add_state("total", default=np.zeros((), jnp.int32), dist_reduce_fx="sum")
        else:
            self.add_state("sum_squared_error", default=[], dist_reduce_fx="cat")
            self.add_state("total", default=[], dist_reduce_fx="cat")
        self.clamp_range: Optional[Tuple[float, float]] = None
        if isinstance(data_range, tuple):
            self.data_range_val = float(data_range[1] - data_range[0])
            self.clamp_range = (float(data_range[0]), float(data_range[1]))
        else:
            self.data_range_val = float(data_range)
        self.base = base
        self.reduction = reduction
        self.dim = tuple(dim) if isinstance(dim, Sequence) else dim

    def _batch_state(self, preds, target):
        if self.clamp_range is not None:
            preds = jnp.clip(preds, *self.clamp_range)
            target = jnp.clip(target, *self.clamp_range)
        sum_squared_error, num_obs = _psnr_update(preds, target, dim=self.dim)
        if self.dim is None:
            return {"sum_squared_error": sum_squared_error, "total": num_obs.astype(jnp.int32)}
        return {"sum_squared_error": sum_squared_error, "total": num_obs}

    def _compute(self, state):
        return _psnr_compute(
            state["sum_squared_error"],
            state["total"],
            jnp.asarray(self.data_range_val),
            base=self.base,
            reduction=self.reduction,
        )


class _CompatPeakSignalNoiseRatio(PeakSignalNoiseRatio):
    """Top-level ``torchmetrics_tpu.PeakSignalNoiseRatio`` alias: the reference
    exports its deprecated wrapper there, whose ``data_range`` defaults to 3.0
    (reference ``image/_deprecated.py``), unlike the strict ``image`` export."""

    def __init__(
        self,
        data_range: Union[float, Tuple[float, float]] = 3.0,
        base: float = 10.0,
        reduction: Optional[str] = "elementwise_mean",
        dim: Optional[Union[int, Tuple[int, ...]]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(data_range, base, reduction, dim, **kwargs)
