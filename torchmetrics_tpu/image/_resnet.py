"""ResNet-50 feature trunk in plain jnp (NCHW), for ARNIQA's encoder.

Standard He et al. bottleneck architecture matching torchvision's ``resnet50``
layer-for-layer (conv1 7x7/2 -> maxpool 3x3/2 -> layers [3,4,6,3] of expansion-4
bottlenecks with stride on the 3x3 conv -> global average pool, BN eps 1e-5).
``convert_resnet50_state_dict`` accepts either torchvision-style key names or the
index-renamed keys an ``nn.Sequential``-wrapped encoder produces (the layout of
the published ARNIQA checkpoint, reference ``functional/image/arniqa.py:95-103``).
Architecture parity is tested against a from-scratch torch ResNet-50 with shared
random weights in ``tests/test_arniqa.py``.
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np
from jax import lax

_LAYERS = (3, 4, 6, 3)


def _conv(x: jnp.ndarray, w: jnp.ndarray, stride: int, pad: int) -> jnp.ndarray:
    return lax.conv_general_dilated(
        x, w, (stride, stride), [(pad, pad), (pad, pad)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )


def _bn(x: jnp.ndarray, p: Dict[str, jnp.ndarray], eps: float = 1e-5) -> jnp.ndarray:
    inv = p["weight"] / jnp.sqrt(p["running_var"] + eps)
    return x * inv[None, :, None, None] + (p["bias"] - p["running_mean"] * inv)[None, :, None, None]


def _maxpool_3x3_s2(x: jnp.ndarray) -> jnp.ndarray:
    return lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 1, 3, 3), (1, 1, 2, 2), [(0, 0), (0, 0), (1, 1), (1, 1)]
    )


def _bottleneck(x: jnp.ndarray, p: Dict[str, Any], stride: int) -> jnp.ndarray:
    out = jnp.maximum(_bn(_conv(x, p["conv1"], 1, 0), p["bn1"]), 0)
    out = jnp.maximum(_bn(_conv(out, p["conv2"], stride, 1), p["bn2"]), 0)
    out = _bn(_conv(out, p["conv3"], 1, 0), p["bn3"])
    if "downsample_conv" in p:
        x = _bn(_conv(x, p["downsample_conv"], stride, 0), p["downsample_bn"])
    return jnp.maximum(out + x, 0)


def resnet50_features(params: Dict[str, Any], x: jnp.ndarray) -> jnp.ndarray:
    """(N, 3, H, W) -> (N, 2048) globally-average-pooled trunk features."""
    x = jnp.maximum(_bn(_conv(x, params["conv1"], 2, 3), params["bn1"]), 0)
    x = _maxpool_3x3_s2(x)
    for li, blocks in enumerate(_LAYERS, start=1):
        for bi in range(blocks):
            stride = 2 if (li > 1 and bi == 0) else 1
            x = _bottleneck(x, params[f"layer{li}"][bi], stride)
    return x.mean(axis=(2, 3))


def convert_resnet50_state_dict(sd: Dict[str, Any]) -> Dict[str, Any]:
    """torch state_dict (torchvision names OR Sequential-indexed names) -> params."""
    arrs = {k: np.asarray(v) for k, v in sd.items()}
    # Sequential-wrapped encoders rename: 0->conv1, 1->bn1, 4..7->layer1..4
    if any(k.startswith("0.") for k in arrs):
        remap = {"0": "conv1", "1": "bn1", "4": "layer1", "5": "layer2", "6": "layer3", "7": "layer4"}
        arrs = {
            ".".join([remap.get(k.split(".")[0], k.split(".")[0]), *k.split(".")[1:]]): v
            for k, v in arrs.items()
        }

    def bn(prefix: str) -> Dict[str, jnp.ndarray]:
        return {
            key: jnp.asarray(arrs[f"{prefix}.{key}"])
            for key in ("weight", "bias", "running_mean", "running_var")
        }

    params: Dict[str, Any] = {"conv1": jnp.asarray(arrs["conv1.weight"]), "bn1": bn("bn1")}
    for li, blocks in enumerate(_LAYERS, start=1):
        layer = []
        for bi in range(blocks):
            pre = f"layer{li}.{bi}"
            block = {
                "conv1": jnp.asarray(arrs[f"{pre}.conv1.weight"]),
                "bn1": bn(f"{pre}.bn1"),
                "conv2": jnp.asarray(arrs[f"{pre}.conv2.weight"]),
                "bn2": bn(f"{pre}.bn2"),
                "conv3": jnp.asarray(arrs[f"{pre}.conv3.weight"]),
                "bn3": bn(f"{pre}.bn3"),
            }
            if f"{pre}.downsample.0.weight" in arrs:
                block["downsample_conv"] = jnp.asarray(arrs[f"{pre}.downsample.0.weight"])
                block["downsample_bn"] = bn(f"{pre}.downsample.1")
            layer.append(block)
        params[f"layer{li}"] = layer
    return params
