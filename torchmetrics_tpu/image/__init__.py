"""Image tower — stateful metric classes (reference ``src/torchmetrics/image/``)."""

from .metrics import (
    ErrorRelativeGlobalDimensionlessSynthesis,
    QualityWithNoReference,
    RelativeAverageSpectralError,
    RootMeanSquaredErrorUsingSlidingWindow,
    SpatialCorrelationCoefficient,
    SpatialDistortionIndex,
    SpectralAngleMapper,
    SpectralDistortionIndex,
    TotalVariation,
    UniversalImageQualityIndex,
    VisualInformationFidelity,
)
from .psnr import PeakSignalNoiseRatio
from .psnrb import PeakSignalNoiseRatioWithBlockedEffect
from .ssim import MultiScaleStructuralSimilarityIndexMeasure, StructuralSimilarityIndexMeasure

__all__ = [
    "ErrorRelativeGlobalDimensionlessSynthesis",
    "MultiScaleStructuralSimilarityIndexMeasure",
    "PeakSignalNoiseRatio",
    "PeakSignalNoiseRatioWithBlockedEffect",
    "QualityWithNoReference",
    "RelativeAverageSpectralError",
    "RootMeanSquaredErrorUsingSlidingWindow",
    "SpatialCorrelationCoefficient",
    "SpatialDistortionIndex",
    "SpectralAngleMapper",
    "SpectralDistortionIndex",
    "StructuralSimilarityIndexMeasure",
    "TotalVariation",
    "UniversalImageQualityIndex",
    "VisualInformationFidelity",
]
