"""Image tower — stateful metric classes (reference ``src/torchmetrics/image/``)."""

from .metrics import (
    ARNIQA,
    ErrorRelativeGlobalDimensionlessSynthesis,
    QualityWithNoReference,
    RelativeAverageSpectralError,
    RootMeanSquaredErrorUsingSlidingWindow,
    SpatialCorrelationCoefficient,
    SpatialDistortionIndex,
    SpectralAngleMapper,
    SpectralDistortionIndex,
    TotalVariation,
    UniversalImageQualityIndex,
    VisualInformationFidelity,
)
from .generative import (
    FrechetInceptionDistance,
    InceptionScore,
    KernelInceptionDistance,
    MemorizationInformedFrechetInceptionDistance,
)
from .dists import DeepImageStructureAndTextureSimilarity
from .lpip import LearnedPerceptualImagePatchSimilarity
from .perceptual_path_length import PerceptualPathLength
from .psnr import PeakSignalNoiseRatio
from .psnrb import PeakSignalNoiseRatioWithBlockedEffect
from .ssim import MultiScaleStructuralSimilarityIndexMeasure, StructuralSimilarityIndexMeasure

__all__ = [
    "ARNIQA",
    "DeepImageStructureAndTextureSimilarity",
    "ErrorRelativeGlobalDimensionlessSynthesis",
    "FrechetInceptionDistance",
    "InceptionScore",
    "KernelInceptionDistance",
    "LearnedPerceptualImagePatchSimilarity",
    "MemorizationInformedFrechetInceptionDistance",
    "MultiScaleStructuralSimilarityIndexMeasure",
    "PeakSignalNoiseRatio",
    "PerceptualPathLength",
    "PeakSignalNoiseRatioWithBlockedEffect",
    "QualityWithNoReference",
    "RelativeAverageSpectralError",
    "RootMeanSquaredErrorUsingSlidingWindow",
    "SpatialCorrelationCoefficient",
    "SpatialDistortionIndex",
    "SpectralAngleMapper",
    "SpectralDistortionIndex",
    "StructuralSimilarityIndexMeasure",
    "TotalVariation",
    "UniversalImageQualityIndex",
    "VisualInformationFidelity",
]
