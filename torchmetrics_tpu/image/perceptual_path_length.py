"""PerceptualPathLength metric class (reference ``image/perceptual_path_length.py:32``)."""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple, Union

import jax.numpy as jnp

from ..functional.image.perceptual_path_length import (
    _perceptual_path_length_validate_arguments,
    _quantile_filtered_stats,
    perceptual_path_length,
)
from ..metric import HostMetric


class PerceptualPathLength(HostMetric):
    """Generator-probing metric: ``update(generator)`` runs the full PPL probe (the
    reference's class works the same way — the generator IS the input)."""

    is_differentiable = False
    higher_is_better = False
    full_state_update = True

    def __init__(
        self,
        num_samples: int = 10_000,
        conditional: bool = False,
        batch_size: int = 128,
        interpolation_method: str = "lerp",
        epsilon: float = 1e-4,
        resize: Optional[int] = 64,
        lower_discard: Optional[float] = 0.01,
        upper_discard: Optional[float] = 0.99,
        sim_net: Union[Callable, str] = "vgg",
        sim_net_weights_path: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        _perceptual_path_length_validate_arguments(
            num_samples, conditional, batch_size, interpolation_method, epsilon, resize, lower_discard, upper_discard
        )
        self.num_samples = num_samples
        self.conditional = conditional
        self.batch_size = batch_size
        self.interpolation_method = interpolation_method
        self.epsilon = epsilon
        self.resize = resize
        self.lower_discard = lower_discard
        self.upper_discard = upper_discard
        self.sim_net = sim_net
        self.sim_net_weights_path = sim_net_weights_path
        self.add_state("distances", default=[], dist_reduce_fx="cat")

    def _host_batch_state(self, generator):
        _, _, dist = perceptual_path_length(
            generator,
            num_samples=self.num_samples,
            conditional=self.conditional,
            batch_size=self.batch_size,
            interpolation_method=self.interpolation_method,
            epsilon=self.epsilon,
            resize=self.resize,
            lower_discard=None,
            upper_discard=None,
            sim_net=self.sim_net,
            sim_net_weights_path=self.sim_net_weights_path,
        )
        return {"distances": dist}

    def _compute(self, state) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        dist = jnp.asarray(state["distances"])
        mean, std = _quantile_filtered_stats(dist, self.lower_discard, self.upper_discard)
        return mean, std, dist

    def __hash__(self) -> int:
        return hash((self.__class__.__name__, id(self)))
