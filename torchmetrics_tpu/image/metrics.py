"""Remaining tensor-math image metric classes (reference ``src/torchmetrics/image/``):
UQI, VIF, TotalVariation, SAM, SCC, ERGAS, RASE, RMSE-SW, D_lambda, D_s, QNR.

State designs follow the reference: cheap metrics keep scalar sum states; metrics whose
statistic is not batch-decomposable (UQI/SAM with ``reduction='none'``, ERGAS/RASE,
the pan-sharpening indices) keep cat states of raw images.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

import numpy as np
import jax.numpy as jnp

from ..functional.image.d_lambda import _spectral_distortion_index_compute, _spectral_distortion_index_update
from ..functional.image.d_s import _spatial_distortion_index_compute, _spatial_distortion_index_update
from ..functional.image.ergas import _ergas_compute, _ergas_update
from ..functional.image.rase import _rase_compute
from ..functional.image.rmse_sw import _rmse_sw_compute, _rmse_sw_update
from ..functional.image.sam import _sam_compute, _sam_update
from ..functional.image.scc import spatial_correlation_coefficient
from ..functional.image.tv import _total_variation_compute, _total_variation_update
from ..functional.image.uqi import _uqi_compute, _uqi_update
from ..functional.image.utils import uniform_filter
from ..functional.image.vif import _vif_per_channel
from ..metric import HostMetric, Metric


class UniversalImageQualityIndex(Metric):
    """UQI (reference ``image/uqi.py:31``). Mean/sum reductions fold into two scalar
    states; ``reduction='none'`` stores raw images (per-pixel map output).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.image import UniversalImageQualityIndex
        >>> preds = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 37 % 97) / 97
        >>> target = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 31 % 89) / 89
        >>> metric = UniversalImageQualityIndex()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.05859955, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        kernel_size: Sequence[int] = (11, 11),
        sigma: Sequence[float] = (1.5, 1.5),
        reduction: Optional[str] = "elementwise_mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if reduction not in ("elementwise_mean", "sum", "none", None):
            raise ValueError(
                f"Argument `reduction` must be one of ('elementwise_mean', 'sum', 'none', None), got {reduction}"
            )
        self.kernel_size = kernel_size
        self.sigma = sigma
        self.reduction = reduction
        if reduction in ("none", None):
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")
        else:
            self.add_state("sum_uqi", jnp.zeros(()), dist_reduce_fx="sum")
            self.add_state("numel", jnp.zeros((), jnp.int32), dist_reduce_fx="sum")

    def _batch_state(self, preds, target):
        preds, target = _uqi_update(preds, target)
        if self.reduction in ("none", None):
            return {"preds": preds, "target": target}
        uqi_map = _uqi_compute(preds, target, self.kernel_size, self.sigma, reduction="none")
        return {"sum_uqi": uqi_map.sum(), "numel": jnp.asarray(uqi_map.size, jnp.int32)}

    def _compute(self, state):
        if self.reduction in ("none", None):
            return _uqi_compute(state["preds"], state["target"], self.kernel_size, self.sigma, self.reduction)
        value = state["sum_uqi"] / state["numel"]
        return value if self.reduction == "elementwise_mean" else state["sum_uqi"]


class VisualInformationFidelity(Metric):
    """VIF (reference ``image/vif.py:25``) — per-batch scores concatenate.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.image import VisualInformationFidelity
        >>> preds = (jnp.arange(3 * 48 * 48, dtype=jnp.float32).reshape(1, 3, 48, 48) * 37 % 97) / 97
        >>> target = (jnp.arange(3 * 48 * 48, dtype=jnp.float32).reshape(1, 3, 48, 48) * 31 % 89) / 89
        >>> metric = VisualInformationFidelity()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.00125213, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, sigma_n_sq: float = 2.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(sigma_n_sq, (float, int)) or sigma_n_sq < 0:
            raise ValueError(f"Argument `sigma_n_sq` is expected to be a positive float or int, but got {sigma_n_sq}")
        self.sigma_n_sq = sigma_n_sq
        self.add_state("vif_score", default=[], dist_reduce_fx="cat")

    def _batch_state(self, preds, target):
        preds = jnp.asarray(preds, jnp.float32)
        target = jnp.asarray(target, jnp.float32)
        channels = preds.shape[1]
        vif_per_channel = [
            _vif_per_channel(preds[:, i], target[:, i], self.sigma_n_sq) for i in range(channels)
        ]
        score = jnp.mean(jnp.stack(vif_per_channel), axis=0) if channels > 1 else vif_per_channel[0]
        return {"vif_score": score}

    def _compute(self, state):
        return jnp.mean(state["vif_score"])


class TotalVariation(Metric):
    """Total variation (reference ``image/tv.py:31``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.image import TotalVariation
        >>> preds = (jnp.arange(48, dtype=jnp.float32).reshape(1, 3, 4, 4) * 37 % 97) / 97
        >>> metric = TotalVariation()
        >>> metric.update(preds)
        >>> metric.compute()
        Array(34.62887, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, reduction: Optional[str] = "sum", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if reduction is not None and reduction not in ("sum", "mean", "none"):
            raise ValueError("Expected argument `reduction` to either be 'sum', 'mean', 'none' or None")
        self.reduction = reduction
        if reduction in (None, "none"):
            self.add_state("score_list", default=[], dist_reduce_fx="cat")
        else:
            self.add_state("score", default=np.zeros(()), dist_reduce_fx="sum")
            self.add_state("num_elements", default=np.zeros((), jnp.int32), dist_reduce_fx="sum")

    def _batch_state(self, img):
        score, num_elements = _total_variation_update(img)
        if self.reduction in (None, "none"):
            return {"score_list": score}
        return {"score": score.sum(), "num_elements": jnp.asarray(num_elements, jnp.int32)}

    def _compute(self, state):
        if self.reduction in (None, "none"):
            return state["score_list"]
        return _total_variation_compute(state["score"], state["num_elements"], self.reduction)


class SpectralAngleMapper(Metric):
    """SAM (reference ``image/sam.py:31``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.image import SpectralAngleMapper
        >>> preds = (jnp.arange(48, dtype=jnp.float32).reshape(1, 3, 4, 4) * 37 % 97) / 97
        >>> target = (jnp.arange(48, dtype=jnp.float32).reshape(1, 3, 4, 4) * 31 % 89) / 89
        >>> metric = SpectralAngleMapper()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.6083106, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, reduction: Optional[str] = "elementwise_mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if reduction not in ("elementwise_mean", "sum", "none", None):
            raise ValueError(
                f"Argument `reduction` must be one of ('elementwise_mean', 'sum', 'none', None), got {reduction}"
            )
        self.reduction = reduction
        if reduction in ("none", None):
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")
        else:
            self.add_state("sum_sam", jnp.zeros(()), dist_reduce_fx="sum")
            self.add_state("numel", jnp.zeros((), jnp.int32), dist_reduce_fx="sum")

    def _batch_state(self, preds, target):
        preds, target = _sam_update(preds, target)
        if self.reduction in ("none", None):
            return {"preds": preds, "target": target}
        sam_map = _sam_compute(preds, target, reduction="none")
        return {"sum_sam": sam_map.sum(), "numel": jnp.asarray(sam_map.size, jnp.int32)}

    def _compute(self, state):
        if self.reduction in ("none", None):
            return _sam_compute(state["preds"], state["target"], self.reduction)
        value = state["sum_sam"] / state["numel"]
        return value if self.reduction == "elementwise_mean" else state["sum_sam"]


class SpatialCorrelationCoefficient(Metric):
    """SCC (reference ``image/scc.py:24``) — two scalar sum states.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.image import SpatialCorrelationCoefficient
        >>> preds = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 37 % 97) / 97
        >>> target = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 31 % 89) / 89
        >>> metric = SpatialCorrelationCoefficient()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(-0.03273272, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self, high_pass_filter: Optional[jnp.ndarray] = None, window_size: int = 8, **kwargs: Any
    ) -> None:
        super().__init__(**kwargs)
        if high_pass_filter is None:
            high_pass_filter = jnp.asarray([[-1, -1, -1], [-1, 8, -1], [-1, -1, -1]])
        if not isinstance(window_size, int) or window_size < 1:
            raise ValueError(f"Expected `window_size` to be a positive integer. Got {window_size}.")
        self.hp_filter = high_pass_filter
        self.ws = window_size
        self.add_state("scc_score", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, preds, target):
        scores = spatial_correlation_coefficient(preds, target, self.hp_filter, self.ws, reduction="none")
        return {"scc_score": scores.sum(), "total": jnp.asarray(float(scores.shape[0]))}

    def _compute(self, state):
        return state["scc_score"] / state["total"]


class ErrorRelativeGlobalDimensionlessSynthesis(Metric):
    """ERGAS (reference ``image/ergas.py:32``) — cat states of raw images.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.image import ErrorRelativeGlobalDimensionlessSynthesis
        >>> preds = (jnp.arange(48, dtype=jnp.float32).reshape(1, 3, 4, 4) * 37 % 97) / 97
        >>> target = (jnp.arange(48, dtype=jnp.float32).reshape(1, 3, 4, 4) * 31 % 89) / 89
        >>> metric = ErrorRelativeGlobalDimensionlessSynthesis()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(21.296127, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, ratio: float = 4, reduction: Optional[str] = "elementwise_mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if reduction not in ("elementwise_mean", "sum", "none", None):
            raise ValueError(
                f"Argument `reduction` must be one of ('elementwise_mean', 'sum', 'none', None), got {reduction}"
            )
        self.ratio = ratio
        self.reduction = reduction
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def _batch_state(self, preds, target):
        preds, target = _ergas_update(preds, target)
        return {"preds": preds, "target": target}

    def _compute(self, state):
        return _ergas_compute(state["preds"], state["target"], self.ratio, self.reduction)


class RelativeAverageSpectralError(Metric):
    """RASE (reference ``image/rase.py:30``) — cat states (the per-window statistic
    depends on the global target mean).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.image import RelativeAverageSpectralError
        >>> preds = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 37 % 97) / 97
        >>> target = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 31 % 89) / 89
        >>> metric = RelativeAverageSpectralError()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(5315.8853, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, window_size: int = 8, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(window_size, int) or window_size < 1:
            raise ValueError(f"Argument `window_size` is expected to be a positive integer, but got {window_size}")
        self.window_size = window_size
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def _batch_state(self, preds, target):
        return {"preds": jnp.asarray(preds), "target": jnp.asarray(target)}

    def _compute(self, state):
        preds = state["preds"]
        target = state["target"]
        img_shape = target.shape[1:]
        rmse_map = jnp.zeros(img_shape, target.dtype)
        target_sum = jnp.zeros(img_shape, target.dtype)
        _, rmse_map, total_images = _rmse_sw_update(
            preds, target, self.window_size, rmse_val_sum=None, rmse_map=rmse_map, total_images=jnp.asarray(0.0)
        )
        target_sum = target_sum + jnp.sum(uniform_filter(target, self.window_size) / (self.window_size**2), axis=0)
        return _rase_compute(rmse_map, target_sum, total_images, self.window_size)


class RootMeanSquaredErrorUsingSlidingWindow(Metric):
    """RMSE-SW (reference ``image/rmse_sw.py:30``) — two scalar sum states.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.image import RootMeanSquaredErrorUsingSlidingWindow
        >>> preds = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 37 % 97) / 97
        >>> target = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 31 % 89) / 89
        >>> metric = RootMeanSquaredErrorUsingSlidingWindow()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.4098781, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, window_size: int = 8, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(window_size, int) or window_size < 1:
            raise ValueError("Argument `window_size` is expected to be a positive integer.")
        self.window_size = window_size
        self.add_state("rmse_val_sum", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("total_images", default=np.zeros(()), dist_reduce_fx="sum")

    def _batch_state(self, preds, target):
        rmse_val_sum, _, total_images = _rmse_sw_update(
            preds, target, self.window_size, rmse_val_sum=None, rmse_map=None, total_images=None
        )
        return {"rmse_val_sum": rmse_val_sum, "total_images": total_images}

    def _compute(self, state):
        rmse, _ = _rmse_sw_compute(state["rmse_val_sum"], jnp.zeros(()), state["total_images"])
        return rmse


class SpectralDistortionIndex(Metric):
    """D_lambda (reference ``image/d_lambda.py:31``) — cat states of raw images."""

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, p: int = 1, reduction: Optional[str] = "elementwise_mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(p, int) or p <= 0:
            raise ValueError(f"Expected `p` to be a positive integer. Got p: {p}.")
        self.p = p
        if reduction not in ("elementwise_mean", "sum", "none"):
            raise ValueError(
                f"Expected argument `reduction` be one of ('elementwise_mean', 'sum', 'none') but got {reduction}"
            )
        self.reduction = reduction
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def _batch_state(self, preds, target):
        preds, target = _spectral_distortion_index_update(preds, target)
        return {"preds": preds, "target": target}

    def _compute(self, state):
        return _spectral_distortion_index_compute(state["preds"], state["target"], self.p, self.reduction)


class SpatialDistortionIndex(Metric):
    """D_s (reference ``image/d_s.py:35``) — ``target`` is a dict with ms/pan[/pan_lr]."""

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self, norm_order: int = 1, window_size: int = 7, reduction: Optional[str] = "elementwise_mean", **kwargs: Any
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(norm_order, int) or norm_order <= 0:
            raise ValueError(f"Expected `norm_order` to be a positive integer. Got norm_order: {norm_order}.")
        self.norm_order = norm_order
        if not isinstance(window_size, int) or window_size <= 0:
            raise ValueError(f"Expected `window_size` to be a positive integer. Got window_size: {window_size}.")
        self.window_size = window_size
        if reduction not in ("elementwise_mean", "sum", "none"):
            raise ValueError(
                f"Expected argument `reduction` be one of ('elementwise_mean', 'sum', 'none') but got {reduction}"
            )
        self.reduction = reduction
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("ms", default=[], dist_reduce_fx="cat")
        self.add_state("pan", default=[], dist_reduce_fx="cat")
        self.add_state("pan_lr", default=[], dist_reduce_fx="cat")

    def _batch_state(self, preds, target: Dict[str, Any]):
        if "ms" not in target or "pan" not in target:
            raise ValueError(f"Expected `target` to contain keys ms and pan. Got target: {list(target.keys())}")
        preds, ms, pan, pan_lr = _spatial_distortion_index_update(
            preds, target["ms"], target["pan"], target.get("pan_lr")
        )
        out = {"preds": preds, "ms": ms, "pan": pan}
        if pan_lr is not None:
            out["pan_lr"] = pan_lr
        return out

    def _compute(self, state):
        pan_lr = state["pan_lr"] if hasattr(state["pan_lr"], "shape") and state["pan_lr"].size else None
        return _spatial_distortion_index_compute(
            state["preds"], state["ms"], state["pan"], pan_lr, self.norm_order, self.window_size, self.reduction
        )


class QualityWithNoReference(Metric):
    """QNR (reference ``image/qnr.py:38``) — composition of D_lambda and D_s."""

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        alpha: float = 1,
        beta: float = 1,
        norm_order: int = 1,
        window_size: int = 7,
        reduction: Optional[str] = "elementwise_mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(alpha, (int, float)) or alpha < 0:
            raise ValueError(f"Expected `alpha` to be a non-negative real number. Got alpha: {alpha}.")
        self.alpha = alpha
        if not isinstance(beta, (int, float)) or beta < 0:
            raise ValueError(f"Expected `beta` to be a non-negative real number. Got beta: {beta}.")
        self.beta = beta
        if not isinstance(norm_order, int) or norm_order <= 0:
            raise ValueError(f"Expected `norm_order` to be a positive integer. Got norm_order: {norm_order}.")
        self.norm_order = norm_order
        if not isinstance(window_size, int) or window_size <= 0:
            raise ValueError(f"Expected `window_size` to be a positive integer. Got window_size: {window_size}.")
        self.window_size = window_size
        if reduction not in ("elementwise_mean", "sum", "none"):
            raise ValueError(
                f"Expected argument `reduction` be one of ('elementwise_mean', 'sum', 'none') but got {reduction}"
            )
        self.reduction = reduction
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("ms", default=[], dist_reduce_fx="cat")
        self.add_state("pan", default=[], dist_reduce_fx="cat")
        self.add_state("pan_lr", default=[], dist_reduce_fx="cat")

    def _batch_state(self, preds, target: Dict[str, Any]):
        if "ms" not in target or "pan" not in target:
            raise ValueError(f"Expected `target` to contain keys ms and pan. Got target: {list(target.keys())}")
        preds, ms, pan, pan_lr = _spatial_distortion_index_update(
            preds, target["ms"], target["pan"], target.get("pan_lr")
        )
        out = {"preds": preds, "ms": ms, "pan": pan}
        if pan_lr is not None:
            out["pan_lr"] = pan_lr
        return out

    def _compute(self, state):
        pan_lr = state["pan_lr"] if hasattr(state["pan_lr"], "shape") and state["pan_lr"].size else None
        d_lambda = _spectral_distortion_index_compute(state["preds"], state["ms"], self.norm_order, self.reduction)
        d_s = _spatial_distortion_index_compute(
            state["preds"], state["ms"], state["pan"], pan_lr, self.norm_order, self.window_size, self.reduction
        )
        return (1 - d_lambda) ** self.alpha * (1 - d_s) ** self.beta


class ARNIQA(HostMetric):
    """ARNIQA no-reference quality (reference ``image/arniqa.py:47``): in-tree jnp
    ResNet-50 encoder + linear regressor (``functional/image/arniqa.py``); only the
    trained weights are external (torch-hub cache, explicit arrays, or a custom
    ``scorer``)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        regressor_dataset: str = "koniq10k",
        reduction: str = "mean",
        normalize: bool = True,
        autocast: bool = False,
        scorer: Optional[Callable] = None,
        encoder_weights: Optional[Any] = None,
        regressor_weights: Optional[Any] = None,
        **kwargs: Any,
    ) -> None:
        from ..functional.image.arniqa import _REGRESSOR_DATASETS

        super().__init__(**kwargs)
        if regressor_dataset not in _REGRESSOR_DATASETS:
            raise ValueError(
                f"Argument `regressor_dataset` must be one of ('kadid10k', 'koniq10k'), but got {regressor_dataset}"
            )
        if reduction not in ("mean", "sum", "none"):
            raise ValueError(f"Argument `reduction` must be one of ('mean', 'sum', 'none'), but got {reduction}")
        if not isinstance(normalize, bool):
            raise ValueError(f"Argument `normalize` should be a bool but got {normalize}")
        self.regressor_dataset = regressor_dataset
        self.reduction = reduction
        self.normalize = normalize
        self.scorer = scorer
        self.encoder_weights = encoder_weights
        self.regressor_weights = regressor_weights
        self.add_state("sum_scores", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("num_scores", default=np.zeros((), np.int32), dist_reduce_fx="sum")
        if reduction == "none":
            # unbounded per-image state only when the caller actually wants it
            self.add_state("scores", default=[], dist_reduce_fx="cat")

    def _host_batch_state(self, img) -> Dict[str, Any]:
        from ..functional.image.arniqa import arniqa

        scores = np.asarray(
            arniqa(
                img, self.regressor_dataset, reduction="none", normalize=self.normalize,
                scorer=self.scorer, encoder_weights=self.encoder_weights,
                regressor_weights=self.regressor_weights,
            )
        ).reshape(-1)
        state = {"sum_scores": scores.sum(), "num_scores": np.asarray(scores.size, np.int32)}
        if self.reduction == "none":
            state["scores"] = scores
        return state

    def _compute(self, state):
        if self.reduction == "mean":
            return jnp.asarray(state["sum_scores"]) / jnp.asarray(state["num_scores"])
        if self.reduction == "sum":
            return jnp.asarray(state["sum_scores"])
        return jnp.asarray(np.asarray(state["scores"]))
